"""Scalability-envelope stress bench (VERDICT r3 item 1).

Models the reference's release scalability suite
(reference: release/benchmarks/README.md:7-33 — 1M tasks queued on one
node, many-object get, many-arg tasks, 1k+ actors, 1 GiB broadcast)
scaled to a single box: every case boots a REAL multi-daemon runtime
(in-box Cluster, the same code path a pod runs) and commits measured
numbers to SCALEBENCH.json.

Each case runs in its own subprocess under a hard timeout so a wedge
in one case can neither hang the suite nor poison the next case's
runtime. A case's line is {"seconds": ..., "rate": ..., "ok": bool}.

Usage:
  python scalebench.py              # run all cases -> SCALEBENCH.json
  python scalebench.py --case NAME  # run one case, print its JSON
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

#: Process-start clock: case-internal budgets must count the SAME
#: window the orchestrator's subprocess timeout counts (cluster boot
#: included), or a case computes a result it never lives to print.
_PROC_START = time.monotonic()


def _reap_group(pgid: int) -> None:
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass

REPO = os.path.dirname(os.path.abspath(__file__))
CASE_TIMEOUT = float(os.environ.get("RT_SCALEBENCH_TIMEOUT", "570"))
#: Heavyweight cases get their own budget: 10k dedicated worker
#: processes on a 1-core box spawn at ~25-30/s once the box is under
#: its own load — a legitimate ~7-minute case, not a wedge.
CASE_TIMEOUT_OVERRIDES = {
    "actors_10k_16_daemons": float(
        os.environ.get("RT_SCALEBENCH_TIMEOUT_10K", "900")
    ),
}


# ---------------------------------------------------------------------------
# cases (each runs in a fresh subprocess)
# ---------------------------------------------------------------------------

def case_tasks_100k_one_daemon() -> dict:
    """100k nop tasks submitted through one daemon (reference envelope:
    '1,000,000+ tasks queued on one node' — in-box at 1/10 scale)."""
    import ray_tpu as rt

    rt.init(num_cpus=8)
    try:
        @rt.remote
        def nop():
            return None

        rt.get(nop.remote(), timeout=60)
        n = 100_000
        t0 = time.perf_counter()
        refs = [nop.remote() for _ in range(n)]
        submitted = time.perf_counter()
        rt.get(refs, timeout=CASE_TIMEOUT - 60)
        dt = time.perf_counter() - t0
        return {
            "n": n,
            "seconds": round(dt, 1),
            "rate": round(n / dt, 1),
            "submit_rate": round(n / (submitted - t0), 1),
            "unit": "tasks/s",
        }
    finally:
        rt.shutdown()


def case_get_10k_objects() -> dict:
    """put 10k objects then one get() over all of them (reference:
    many_args/many-object wait envelope)."""
    import ray_tpu as rt

    rt.init(num_cpus=4)
    try:
        n = 10_000
        refs = [rt.put(i) for i in range(n)]
        t0 = time.perf_counter()
        vals = rt.get(refs, timeout=300)
        dt = time.perf_counter() - t0
        assert vals[-1] == n - 1
        return {
            "n": n,
            "seconds": round(dt, 3),
            "rate": round(n / dt, 1),
            "unit": "objects/s",
        }
    finally:
        rt.shutdown()


def case_args_and_returns_1k() -> dict:
    """One task taking 1000 ObjectRef args; one task declaring 1000
    returns (reference: single_node many-args / many-returns cases)."""
    import ray_tpu as rt

    rt.init(num_cpus=4)
    try:
        @rt.remote
        def many_args(*args):
            return len(args)

        @rt.remote(num_returns=1000)
        def many_returns():
            return tuple(range(1000))

        args = [rt.put(i) for i in range(1000)]
        t0 = time.perf_counter()
        assert rt.get(many_args.remote(*args), timeout=300) == 1000
        args_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        vals = rt.get(list(many_returns.remote()), timeout=300)
        returns_s = time.perf_counter() - t0
        assert vals[-1] == 999
        return {
            "args_seconds": round(args_s, 3),
            "returns_seconds": round(returns_s, 3),
            "seconds": round(args_s + returns_s, 3),
        }
    finally:
        rt.shutdown()


def case_actors_1k_16_daemons() -> dict:
    """1000 zero-resource actors SPREAD across a 16-daemon in-box
    cluster, each created on a dedicated worker and pinged once
    (reference envelope: '10,000+ actors across 1,000 nodes' at
    in-box scale; actor-per-worker model of worker_pool.cc)."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 1.0})
    try:
        for _ in range(15):
            cluster.add_node(num_cpus=1.0)
        cluster.wait_for_nodes(16, timeout=120)
        rt.init(address=cluster.address)

        @rt.remote(num_cpus=0)
        class Slot:
            def ping(self):
                return os.getpid()

        n = 1000
        t0 = time.perf_counter()
        actors = [
            Slot.options(scheduling_strategy="SPREAD").remote()
            for _ in range(n)
        ]
        pids = rt.get(
            [a.ping.remote() for a in actors], timeout=CASE_TIMEOUT - 90
        )
        dt = time.perf_counter() - t0
        distinct = len(set(pids))
        assert distinct == n, f"expected {n} dedicated workers: {distinct}"
        return {
            "n": n,
            "nodes": 16,
            "seconds": round(dt, 1),
            "rate": round(n / dt, 1),
            "unit": "actors/s",
        }
    finally:
        rt.shutdown()
        cluster.shutdown()


def case_broadcast_256mb_8_daemons() -> dict:
    """One 256 MiB object pulled by a task on each of 8 daemons
    (reference envelope: '1 GiB broadcast to 50 nodes'; chunked
    windowed pulls with randomized source selection)."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 1.0})
    try:
        for _ in range(7):
            cluster.add_node(num_cpus=1.0)
        cluster.wait_for_nodes(8, timeout=120)
        rt.init(address=cluster.address)

        @rt.remote(num_cpus=1)
        def consume(x):
            return x.nbytes

        # Warm one worker per node first (tiny object): the case
        # measures the TRANSFER plane, and on a 1-core box the 8
        # fork-server templates booting concurrently would otherwise
        # dominate the number (reference: ray benchmarks warm the
        # cluster before timing broadcast too).
        rt.get(
            [
                consume.options(scheduling_strategy="SPREAD").remote(
                    rt.put(np.ones(8))
                )
                for _ in range(8)
            ],
            timeout=CASE_TIMEOUT - 200,
        )

        nbytes = 256 * 1024 * 1024
        blob = np.random.default_rng(0).random(nbytes // 8)
        assert blob.nbytes == nbytes
        ref = rt.put(blob)
        t0 = time.perf_counter()
        sizes = rt.get(
            [
                consume.options(scheduling_strategy="SPREAD").remote(ref)
                for _ in range(8)
            ],
            timeout=CASE_TIMEOUT - 90,
        )
        dt = time.perf_counter() - t0
        assert all(s == nbytes for s in sizes)
        return {
            "nbytes": nbytes,
            "nodes": 8,
            "seconds": round(dt, 1),
            "rate": round(8 * nbytes / dt / 1e9, 2),
            "unit": "GB/s aggregate",
        }
    finally:
        rt.shutdown()
        cluster.shutdown()


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return round(int(line.split()[1]) / 1024, 1)
    return 0.0


def case_tasks_1m_queue_one_daemon() -> dict:
    """1M nop tasks SUBMITTED AND QUEUED through one daemon
    (reference envelope: '1,000,000+ tasks queued on one node',
    release/benchmarks/README.md:32). Completion streams concurrently;
    the case asserts the head survives the full queue depth without
    OOM (RSS recorded) and that completions flow while the backlog is
    at full depth (first-wave sample get)."""
    import ray_tpu as rt

    rt.init(num_cpus=8)
    try:
        @rt.remote
        def nop():
            return None

        rt.get(nop.remote(), timeout=60)
        base_rss = _rss_mb()
        n = 1_000_000
        t0 = time.perf_counter()
        refs = [nop.remote() for _ in range(1000)]
        # Watch the FIRST wave from a side thread while the flood
        # continues: dispatch must interleave with batch ingestion,
        # so these complete while the other ~999k are still being
        # submitted (they once completed only AFTER the full 63.8s
        # submit loop — dispatch starvation under flood).
        import threading

        first_done = {}
        first_wave = list(refs)

        def _watch():
            rt.get(first_wave, timeout=CASE_TIMEOUT - 120)
            first_done["t"] = time.perf_counter() - t0

        watcher = threading.Thread(target=_watch, daemon=True)
        watcher.start()
        refs.extend(nop.remote() for _ in range(n - 1000))
        submit_s = time.perf_counter() - t0
        peak_rss = _rss_mb()
        watcher.join(120)
        alive_s = first_done.get("t")
        assert alive_s is not None, "first 1k never completed"
        assert alive_s < submit_s / 4, (
            f"dispatch starved under submit flood: first 1k done at "
            f"{alive_s:.1f}s vs {submit_s:.1f}s submit"
        )
        return {
            "n": n,
            "submit_seconds": round(submit_s, 1),
            "submit_rate": round(n / submit_s, 1),
            "first_1k_done_at_s": round(alive_s, 1),
            "rss_mb_before": base_rss,
            "rss_mb_at_full_queue": peak_rss,
            "seconds": round(submit_s, 1),
            "unit": "tasks submitted+queued/s",
        }
    finally:
        rt.shutdown()


def case_actors_10k_16_daemons() -> dict:
    """Toward 10k zero-resource actors across 16 daemons (reference
    envelope: '10,000+ actors', release/benchmarks/README.md:13),
    created in waves of 1000 with each wave pinged before the next.
    On this 1-core box the binding constraint is fork throughput under
    the box's own load (~25-50 spawns/s; 10k dedicated worker
    PROCESSES is several hundred seconds of pure forking), so the case
    reports the largest wave-complete count the time budget proves
    rather than failing on a wall-clock cliff. The earlier structural
    ceiling — thread-per-socket I/O collapsing the scheduler at ~20k
    threads — is gone (rpc.py SelectorHub); no OOM, head RSS
    recorded."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    # Deadline counts from PROCESS start (the window the
    # orchestrator's subprocess timeout measures — cluster boot
    # included), minus margin to print the result; measuring from a
    # post-boot t0 once produced a result that was computed but
    # SIGKILLed before it could be printed.
    deadline = _PROC_START + CASE_TIMEOUT_OVERRIDES[
        "actors_10k_16_daemons"
    ] - 60
    cluster = Cluster(head_resources={"CPU": 1.0})
    try:
        for _ in range(15):
            cluster.add_node(num_cpus=1.0)
        cluster.wait_for_nodes(16, timeout=120)
        rt.init(address=cluster.address)

        @rt.remote(num_cpus=0)
        class Slot:
            def ping(self):
                return os.getpid()

        target, wave = 10_000, 1_000
        pids = set()
        actors = []
        t0 = time.perf_counter()
        last_wave_s = 0.0
        while len(actors) < target:
            remaining = deadline - time.monotonic()
            # Don't start a wave the deadline can't absorb: leave the
            # slower of (observed wave time x1.3, 90s) in reserve.
            if actors and remaining < max(90.0, last_wave_s * 1.3):
                break  # report what the budget PROVED complete
            wave_t0 = time.monotonic()
            batch = [
                Slot.options(scheduling_strategy="SPREAD").remote()
                for _ in range(wave)
            ]
            try:
                got = rt.get(
                    [a.ping.remote() for a in batch],
                    timeout=max(30.0, remaining - 30.0),
                )
            except rt.exceptions.GetTimeoutError:
                break  # budget ran out mid-wave: report proven waves
            last_wave_s = time.monotonic() - wave_t0
            pids.update(got)
            actors.extend(batch)
        dt = time.perf_counter() - t0
        n = len(actors)
        assert len(pids) == n, (
            f"expected {n} dedicated workers: {len(pids)}"
        )
        result = {
            "n_target": target,
            "n_alive_and_pinged": n,
            "nodes": 16,
            "seconds": round(dt, 1),
            "rate": round(n / dt, 1),
            "rss_mb_head_process": _rss_mb(),
            "unit": "actors/s",
        }
        if os.environ.get("RT_SCALEBENCH_ORCH_PID") == str(os.getppid()):
            # Graceful teardown of up to 10k worker processes takes
            # minutes on one core — longer than the measurement
            # itself, and a case-timeout mid-teardown once leaked ~6k
            # processes. Under the orchestrator (which SIGKILLs this
            # case's process group after reading the result), print
            # and fast-exit instead.
            print(json.dumps(result), flush=True)
            os._exit(0)
        return result
    finally:
        # Worker-tree SIGKILL first and unconditionally: if
        # rt.shutdown() wedges (observed once under a saturated pid
        # table: thread creation fails mid-teardown), the orphaned 7k
        # workers must not outlive this process.
        try:
            cluster.shutdown()
        finally:
            try:
                rt.shutdown()
            except Exception:
                pass


def case_args_10k_one_task() -> dict:
    """One task taking 10,000 ObjectRef args (reference envelope:
    '10,000 args', release/benchmarks/README.md:27)."""
    import ray_tpu as rt

    rt.init(num_cpus=4)
    try:
        @rt.remote
        def many_args(*args):
            return len(args)

        refs = [rt.put(i) for i in range(10_000)]
        t0 = time.perf_counter()
        assert (
            rt.get(many_args.remote(*refs), timeout=CASE_TIMEOUT - 60)
            == 10_000
        )
        dt = time.perf_counter() - t0
        return {
            "n_args": 10_000,
            "seconds": round(dt, 2),
            "unit": "seconds for one 10k-arg task",
        }
    finally:
        rt.shutdown()


#: Cases that print their result and os._exit under the orchestrator
#: instead of gracefully tearing down thousands of workers; the
#: orchestrator reaps their process group.
FAST_EXIT_CASES = {"actors_10k_16_daemons"}

#: Light cases run FIRST: the 10k-actor monster ends in a SIGKILL
#: reap of thousands of processes whose aftermath (load spike, pid
#: churn) would otherwise pollute whatever runs next.
CASES = {
    "get_10k_objects": case_get_10k_objects,
    "args_and_returns_1k": case_args_and_returns_1k,
    "args_10k_one_task": case_args_10k_one_task,
    "tasks_100k_one_daemon": case_tasks_100k_one_daemon,
    "broadcast_256mb_8_daemons": case_broadcast_256mb_8_daemons,
    "actors_1k_16_daemons": case_actors_1k_16_daemons,
    "tasks_1m_queue_one_daemon": case_tasks_1m_queue_one_daemon,
    "actors_10k_16_daemons": case_actors_10k_16_daemons,
}


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def _run_case_subprocess(name: str) -> dict:
    case_timeout = CASE_TIMEOUT_OVERRIDES.get(name, CASE_TIMEOUT)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # runtime-bound: keep off the chip
    env["PALLAS_AXON_POOL_IPS"] = ""
    # Enables fast-exit teardowns — scoped to OUR direct children via
    # a ppid handshake, so a leaked env var can't make a hand-run
    # --case skip teardown with nobody to reap its tree.
    env["RT_SCALEBENCH_ORCH_PID"] = str(os.getpid())
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH", "")) if p
    )
    t0 = time.perf_counter()
    # Own session/process group: a case that times out has spawned an
    # entire runtime tree (daemons, fork-servers, up to 10k workers) —
    # killing only the direct child once leaked ~6k processes and
    # poisoned every later case's numbers. killpg reaps the tree.
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scalebench.py"),
         "--case", name],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        cwd=REPO,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=case_timeout)
    except subprocess.TimeoutExpired:
        # Child is still unreaped here, so its pid (= pgid) cannot
        # have been recycled.
        _reap_group(proc.pid)
        try:
            proc.communicate(timeout=30)
        except Exception:
            pass
        return {"ok": False, "error": f"timeout after {case_timeout}s"}
    if name in FAST_EXIT_CASES:
        # Fast-exit cases skip graceful teardown and leave their
        # worker tree for us to reap. Only for them: on the normal
        # path the child is already reaped, and a recycled pid could
        # otherwise aim SIGKILL at an innocent process group — but a
        # fast-exit case's tree keeps the group alive (pgid pinned)
        # until this kill.
        _reap_group(proc.pid)
    proc = subprocess.CompletedProcess(
        proc.args, proc.returncode, stdout, stderr
    )
    if proc.returncode != 0:
        return {
            "ok": False,
            "error": (proc.stderr or "")[-1500:],
            "seconds": round(time.perf_counter() - t0, 1),
        }
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            result = json.loads(line)
            result["ok"] = True
            return result
    return {"ok": False, "error": "no JSON line in case output"}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--case", choices=sorted(CASES))
    args = parser.parse_args()

    # This image sets PYTHONDONTWRITEBYTECODE=1, so without an
    # explicit compile pass every python process (each case
    # subprocess, each real daemon) re-compiles the whole package
    # from source (~0.3s of pure CPU each) — noise that lands in the
    # measured numbers. compileall writes pycs regardless of the
    # flag. Orchestrator-only: --case subprocesses inherit the fresh
    # cache instead of re-walking the tree 8 times.
    if not args.case:
        import compileall

        compileall.compile_dir(
            os.path.join(REPO, "ray_tpu"), quiet=2, workers=1
        )

    if args.case:
        print(json.dumps(CASES[args.case]()))
        return

    results: dict = {}
    for name in CASES:
        print(f"[scalebench] {name} ...", file=sys.stderr, flush=True)
        results[name] = _run_case_subprocess(name)
        print(f"[scalebench] {name}: {json.dumps(results[name])}",
              file=sys.stderr, flush=True)
        with open(os.path.join(REPO, "SCALEBENCH.json"), "w") as f:
            json.dump(results, f, indent=2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
