"""Typed wire schema + protocol versioning (reference behavior:
src/ray/protobuf/*.proto — typed messages per RPC, version-safe
peers)."""

import re

import pytest

from ray_tpu._private import wire
from ray_tpu._private.wire import (
    PROTOCOL_VERSION,
    ProtocolVersionError,
    decode_frame,
    encode_frame,
    validate,
)


def test_frame_roundtrip():
    msg = {
        "_method": "get_object", "_mid": 42, "oid": b"x" * 20,
        "nested": {"a": [1, 2, {"b": None}]},
    }
    out = decode_frame(encode_frame(dict(msg)))
    assert out == msg


def test_push_frame_roundtrip():
    msg = {"_mid": -1, "_push": "log_lines", "batches": [], "node": "n"}
    out = decode_frame(encode_frame(dict(msg)))
    assert out["_push"] == "log_lines"
    assert out["_mid"] == -1


def test_version_mismatch_rejected():
    import struct

    from ray_tpu._private.protocol_pb2 import Frame

    env = Frame(
        version=PROTOCOL_VERSION + 7, method="ping", mid=1
    ).SerializeToString()
    wire_bytes = struct.pack(">I", len(env)) + env
    with pytest.raises(ProtocolVersionError):
        decode_frame(wire_bytes)


def test_schema_registry_covers_every_registered_method():
    """Every method the daemon (and the worker's direct server)
    registers must have a schema — the registry cannot silently rot."""
    import os

    src = open(
        os.path.join(os.path.dirname(wire.__file__), "daemon.py")
    ).read()
    block = re.search(
        r"for name in \[(.*?)\]:\s*\n\s*self\.server\.register",
        src, re.S,
    ).group(1)
    methods = set(re.findall(r'"([a-z_]+)"', block))
    methods |= {"_disconnect", "execute_task", "execute_tasks", "ping"}
    missing = sorted(m for m in methods if m not in wire.SCHEMAS)
    assert not missing, f"methods without wire schema: {missing}"


def test_batch_submit_schemas_registered():
    """The batched task plane rides typed schemas (RT104 judges its
    call sites against these): `specs` is the flat-codec batch payload
    — ONE bytes blob, never a pickled list of dicts."""
    for method in ("submit_tasks", "execute_tasks"):
        assert wire.SCHEMAS[method]["specs"] is bytes
        assert wire.SCHEMAS[method]["count"] is int
    assert wire.SCHEMAS["get_objects"]["oids"] is list


def test_flat_codec_frame_kind_is_guarded():
    """The flat-codec frame kind byte is wire format: decode must
    refuse any other kind cleanly (SchemaError-class failure, not a
    struct unpack deep in a handler), and a codec-encoded spec always
    leads with it."""
    spec = {
        "task_id": b"T" * 16, "job_id": b"J" * 4, "kind": "normal",
        "name": "f", "function_key": "k", "args": [], "returns": [],
        "resources": {}, "max_retries": 0,
    }
    blob = wire.encode_spec(spec)
    assert blob[0] == wire.SPEC_MAGIC
    with pytest.raises(wire.SpecCodecError, match="magic"):
        wire.decode_spec(bytes([wire.SPEC_MAGIC ^ 0xFF]) + blob[1:])


def test_validate_types_and_required():
    assert validate("get_object", {"oid": b"x" * 20}) is None
    assert "missing required" in validate("get_object", {})
    assert "expects bytes" in validate("get_object", {"oid": "str!"})
    # optional fields may be absent but must type-check when present
    assert validate("pull_object", {"oid": b"x"}) is None
    err = validate("pull_object", {"oid": b"x", "offset": "zero"})
    assert "offset" in err and "int" in err
    # unknown methods pass through (completeness test guards the set)
    assert validate("no_such_method", {"anything": 1}) is None


def test_malformed_rpc_gets_clean_schema_error(rt_session):
    """End-to-end: a wrong-typed field comes back as a typed schema
    error, not a KeyError traceback from inside a handler."""
    from ray_tpu._private.rpc import RpcError
    from ray_tpu._private.worker import global_worker

    client = global_worker()._client
    with pytest.raises(RpcError, match="schema violation"):
        client.call("get_object", oid="not-bytes", timeout=10)
    # The connection survives schema rejections.
    assert client.call("ping", timeout=10).get("ok") is True


def test_codec_fuzz_roundtrip():
    """Randomized payload round-trips: the codec must be identity for
    every picklable shape the runtime sends."""
    import random

    rng = random.Random(7)

    def rand_value(depth=0):
        kinds = ["int", "bytes", "str", "none", "bool", "float"]
        if depth < 3:
            kinds += ["list", "dict"]
        k = rng.choice(kinds)
        if k == "int":
            return rng.randint(-(2**40), 2**40)
        if k == "bytes":
            return bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
        if k == "str":
            return "".join(chr(rng.randrange(32, 0x2FF)) for _ in range(rng.randrange(16)))
        if k == "none":
            return None
        if k == "bool":
            return rng.random() < 0.5
        if k == "float":
            return rng.uniform(-1e9, 1e9)
        if k == "list":
            return [rand_value(depth + 1) for _ in range(rng.randrange(4))]
        return {
            f"k{i}": rand_value(depth + 1) for i in range(rng.randrange(4))
        }

    for i in range(200):
        msg = {
            **{f"f{j}": rand_value() for j in range(rng.randrange(5))},
        }
        method = rng.choice(["", "get_object", "x" * 40])
        if method:
            msg["_method"] = method
        if rng.random() < 0.8:
            msg["_mid"] = rng.randint(-1, 2**31)
        if rng.random() < 0.3:
            msg["_mid"] = -1
            msg["_push"] = rng.choice(["log_lines", "ch" * 10])
        out = decode_frame(encode_frame(dict(msg)))
        expect = dict(msg)
        # Absent correlation id decodes as the notify default, 0.
        expect.setdefault("_mid", 0)
        if not method:
            assert "_method" not in out, (i, msg, out)
        assert out == expect, (i, msg, out)


def test_codec_rejects_garbage_without_crashing():
    """Corrupted frames raise cleanly (the HMAC layer normally rejects
    them first; this is the defense-in-depth behind it)."""
    import random

    rng = random.Random(11)
    good = encode_frame({"_method": "ping", "_mid": 3, "data": b"x" * 100})
    for _ in range(100):
        bad = bytearray(good)
        for _ in range(rng.randrange(1, 6)):
            bad[rng.randrange(len(bad))] = rng.randrange(256)
        try:
            decode_frame(bytes(bad))
        except Exception:
            pass  # any clean exception is fine; no hang, no segfault
    for cut in (0, 1, 3, 4, 7, len(good) - 1):
        try:
            decode_frame(good[:cut])
        except Exception:
            pass
