"""CI gate for the RL dataflow bench: `rlbench.py --smoke` must run
the decoupled dataflow (local AND engine-served policy) plus the
synchronous baseline on CPU in about a minute and emit one
well-formed JSON line (same pattern as test_servebench_smoke.py: a
broken bench is caught by the suite, not at measurement time)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# slow: ~90s of rollout+training + jit compiles on a loaded CI box.
@pytest.mark.slow
@pytest.mark.timeout(560)
def test_rlbench_smoke_emits_composite_json(tmp_path):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    out_path = str(tmp_path / "RLBENCH.json")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "rlbench.py"),
            "--smoke",
            "--out",
            out_path,
        ],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [
        ln for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ][-1]
    out = json.loads(line)
    with open(out_path) as f:
        assert json.load(f) == out  # file matches the stdout line

    assert out["smoke"] is True
    assert out["metric"] == "rlbench_env_steps_per_s"
    assert out["value"] > 0

    # Every point carries the full trajectory fields for all three
    # passes: baseline phases, decoupled rates, weight-sync latency,
    # queue occupancy/gate accounting.
    assert len(out["points"]) >= 2
    for point in out["points"]:
        base = point["baseline_sync"]
        assert base["env_steps_per_s"] > 0
        assert base["updates_per_s"] > 0
        for phase in ("sample", "update", "broadcast"):
            assert base["phases_ms"][phase] >= 0
        for mode in ("decoupled_local", "decoupled_engine"):
            dec = point[mode]
            assert dec["env_steps_per_s"] > 0
            assert dec["updates_per_s"] > 0
            assert dec["weight_sync_ms"]["p50"] > 0
            queue = dec["queue"]
            assert queue["capacity"] > 0
            assert queue["mean_depth"] >= 0
            for gate in ("rejected_full", "dropped_stale"):
                assert queue[gate] >= 0
        # Engine pass actually served batched policy traffic with
        # drainless pushes landing.
        engine = point["decoupled_engine"]["engine"]
        assert engine["policy_rows_served"] > 0
        assert engine["mean_batch_rows"] > 0
        assert engine["weight_version"] > 0

    # The doctor attributed the actor-vs-learner bottleneck from the
    # live rl_* series (acceptance: visible in doctor --json).
    doctors = [
        p["decoupled_local"].get("doctor_rl") for p in out["points"]
    ]
    assert any(
        d and d.get("bottleneck") in ("learner", "runners", "balanced")
        for d in doctors
    )
    # The learner-bound point's verdict must convict the LEARNER —
    # that is what the point constructs.
    assert out["points"][-1]["decoupled_local"]["doctor_rl"][
        "bottleneck"
    ] == "learner"

    # Queue/weight-lag/weight-version series render on the
    # Prometheus exposition (acceptance: visible on /metrics).
    visibility = out["metrics_visibility"]
    for series in (
        "rl_queue_depth",
        "rl_weight_lag",
        "rl_weight_version",
        "rl_weight_sync_ms",
        "rl_env_steps_total",
        "rl_learner_updates_total",
        "serve_engine_weight_version",
    ):
        assert visibility.get(series), (series, visibility)

    # The decoupled dataflow beats the synchronous baseline where
    # the architecture says it must: the learner-bound point (actors
    # keep sampling under bounded staleness instead of idling behind
    # the gather barrier). Smoke bar is deliberately under the full
    # bench's 2x: short windows on a loaded 1-core CI box.
    learner_bound = out["points"][-1]
    assert learner_bound["point"] == "learner_bound"
    assert learner_bound["speedup_env_steps"] > 1.1, learner_bound
