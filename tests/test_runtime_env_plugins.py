"""RuntimeEnvPlugin API: uv/conda built-ins (binary-gated) and
external plugins loaded via RT_RUNTIME_ENV_PLUGINS (reference:
runtime_env/plugin.py, uv.py, conda.py)."""

import os
import stat
import sys
import textwrap

import pytest

import ray_tpu as rt


FAKE_UV = textwrap.dedent(
    """\
    #!{python}
    import os, sys
    # mimic: uv pip install --quiet --python X --target DIR req...
    args = sys.argv[1:]
    target = args[args.index("--target") + 1]
    os.makedirs(target, exist_ok=True)
    with open(os.path.join(target, "fake_uv_pkg.py"), "w") as f:
        f.write("MAGIC = 'uv-ok'\\n")
    """
)

PLUGIN_MODULE = textwrap.dedent(
    """\
    import os
    from ray_tpu._private.runtime_env import RuntimeEnvPlugin

    class StampPlugin(RuntimeEnvPlugin):
        name = "stamp"
        priority = 7

        def validate(self, value, worker):
            # driver-side normalization is visible to the worker
            return {{"v": str(value).upper()}}

        def create(self, value, worker):
            # count create calls: memoization must make this once
            # per distinct value per worker process
            with open({counter!r}, "a") as f:
                f.write("create\\n")
            return value["v"]

        def modify_context(self, state, value, ctx):
            ctx.set_env("STAMP_ENV", state)
    """
)


def test_uv_rejected_without_binary(rt_session, tmp_path):
    """On an image without the uv binary the gate fails at submit,
    driver-side (simulated by pointing PATH at an empty dir — this
    image actually carries uv)."""
    rt = rt_session
    import ray_tpu.exceptions as exc

    @rt.remote(runtime_env={"uv": ["anything"]})
    def nope():
        return 1

    empty = tmp_path / "emptybin"
    empty.mkdir()
    old_path = os.environ.get("PATH", "")
    os.environ["PATH"] = str(empty)
    try:
        with pytest.raises(exc.RuntimeEnvSetupError, match="uv"):
            nope.remote()  # rt: noqa[RT106] — submit raises; no ref exists
    finally:
        os.environ["PATH"] = old_path


def _forge_wheel(tmp_path):
    """Tiny pure-python wheel, fully offline-installable (same forge
    as tests/test_runtime_env_pip.py)."""
    import zipfile

    dist = "uvpkg_rt-0.1.dist-info"
    path = tmp_path / "uvpkg_rt-0.1-py3-none-any.whl"
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("uvpkg_rt.py", "VALUE = 'real-uv'\n")
        zf.writestr(
            f"{dist}/METADATA",
            "Metadata-Version: 2.1\nName: uvpkg-rt\nVersion: 0.1\n",
        )
        zf.writestr(
            f"{dist}/WHEEL",
            "Wheel-Version: 1.0\nGenerator: forge\nRoot-Is-Purelib: "
            "true\nTag: py3-none-any\n",
        )
        zf.writestr(
            f"{dist}/RECORD",
            f"uvpkg_rt.py,,\n{dist}/METADATA,,\n{dist}/WHEEL,,\n"
            f"{dist}/RECORD,,\n",
        )
    return str(path)


def test_uv_real_binary_local_wheel(tmp_path):
    """This image ships uv: install a forged local wheel through the
    REAL uv binary, fully offline."""
    import shutil as _shutil

    if _shutil.which("uv") is None:
        pytest.skip("no uv binary on this image")
    wheel = _forge_wheel(tmp_path)
    rt.init(num_cpus=1)
    try:
        @rt.remote(runtime_env={"uv": [wheel]})
        def use():
            import uvpkg_rt

            return uvpkg_rt.VALUE

        assert rt.get(use.remote(), timeout=180) == "real-uv"
    finally:
        rt.shutdown()


def test_uv_fake_binary_end_to_end(tmp_path):
    """With a uv binary on PATH (faked here), runtime_env={'uv': ...}
    builds the package dir worker-side and the task imports from it —
    the full plugin path: driver validate -> worker create ->
    modify_context."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    uv = bindir / "uv"
    uv.write_text(FAKE_UV.format(python=sys.executable))
    uv.chmod(uv.stat().st_mode | stat.S_IEXEC)

    old_path = os.environ.get("PATH", "")
    os.environ["PATH"] = f"{bindir}{os.pathsep}{old_path}"
    try:
        rt.init(num_cpus=2)

        @rt.remote(runtime_env={"uv": ["somepkg==1.0"]})
        def use():
            import fake_uv_pkg

            return fake_uv_pkg.MAGIC

        assert rt.get(use.remote(), timeout=120) == "uv-ok"
    finally:
        os.environ["PATH"] = old_path
        rt.shutdown()


def test_external_plugin_lifecycle(tmp_path):
    """A plugin shipped via RT_RUNTIME_ENV_PLUGINS=/file.py:Class:
    driver-side validate transforms the value, worker-side create is
    memoized per value, modify_context applies through the context
    (and the env does NOT leak into tasks without the field)."""
    counter = tmp_path / "creates.txt"
    plugin_py = tmp_path / "stamp_plugin.py"
    plugin_py.write_text(
        PLUGIN_MODULE.format(counter=str(counter))
    )

    os.environ["RT_RUNTIME_ENV_PLUGINS"] = f"{plugin_py}:StampPlugin"
    import ray_tpu._private.runtime_env as renv

    renv._external_loaded = False  # re-read the env var
    try:
        rt.init(num_cpus=1)

        @rt.remote(runtime_env={"stamp": "hello"})
        def stamped():
            return os.environ.get("STAMP_ENV")

        @rt.remote
        def plain():
            return os.environ.get("STAMP_ENV")

        # validate() uppercased driver-side; modify_context applied.
        assert rt.get(stamped.remote(), timeout=60) == "HELLO"
        assert rt.get(stamped.remote(), timeout=60) == "HELLO"
        # restore: a task without the field sees a clean worker.
        assert rt.get(plain.remote(), timeout=60) is None
        # create() memoized: two applies of the same value, one build
        # (single worker: num_cpus=1 serializes onto one process).
        assert counter.read_text().count("create") == 1
    finally:
        os.environ.pop("RT_RUNTIME_ENV_PLUGINS", None)
        renv._external_loaded = False
        renv.unregister_plugin("stamp")
        rt.shutdown()


def test_register_plugin_validates_names():
    from ray_tpu._private.runtime_env import (
        RuntimeEnvPlugin,
        register_plugin,
    )

    class Bad(RuntimeEnvPlugin):
        name = "pip"  # shadows a built-in

    with pytest.raises(ValueError, match="shadows"):
        register_plugin(Bad())

    class Empty(RuntimeEnvPlugin):
        name = ""

    with pytest.raises(ValueError):
        register_plugin(Empty())
