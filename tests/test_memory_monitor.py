"""Memory monitor / OOM defense tests (reference test model:
memory-monitor unit tests + OOM killing policy tests)."""

import time

import pytest


def test_victim_policy_prefers_retriable_then_largest():
    from ray_tpu._private.memory_monitor import pick_victim

    candidates = [
        {"pid": 1, "retriable": False, "rss": 900},
        {"pid": 2, "retriable": True, "rss": 100},
        {"pid": 3, "retriable": True, "rss": 500},
    ]
    assert pick_victim(candidates)["pid"] == 3  # retriable, biggest
    assert pick_victim([candidates[0]])["pid"] == 1
    assert pick_victim([]) is None


def test_monitor_tick_thresholds():
    from ray_tpu._private.memory_monitor import MemoryMonitor

    killed = []
    usage = {"value": 0.5}
    monitor = MemoryMonitor(
        usage_threshold=0.9,
        refresh_interval_s=10,
        get_candidates=lambda: [
            {"pid": 42, "retriable": True, "rss": 1}
        ],
        kill_worker=lambda v: killed.append(v["pid"]),
        usage_fn=lambda: usage["value"],
        min_kill_interval_s=0.0,
    )
    assert monitor.tick() is False  # below threshold
    usage["value"] = 0.95
    assert monitor.tick() is True
    assert killed == [42]


def test_node_usage_fraction_sane():
    from ray_tpu._private.memory_monitor import (
        node_memory_usage_fraction,
        process_rss,
    )
    import os

    frac = node_memory_usage_fraction()
    assert 0.0 < frac < 1.0
    assert process_rss(os.getpid()) > 1024 * 1024


def test_oom_kill_end_to_end():
    """threshold=0 makes every sample an OOM: the running task's
    worker is killed and the task fails as a worker crash (retries
    exhausted)."""
    import ray_tpu as rt
    import ray_tpu.exceptions as exc

    rt.init(
        num_cpus=2,
        _system_config={
            "memory_monitor_refresh_ms": 50,
            "memory_usage_threshold": 0.0,
        },
    )
    try:

        @rt.remote(max_retries=0)
        def hog():
            time.sleep(30)
            return "survived"

        with pytest.raises(exc.WorkerCrashedError):
            rt.get(hog.remote(), timeout=30)
    finally:
        rt.shutdown()


def test_oom_retry_then_success():
    """A retriable task killed once can still finish after the memory
    pressure clears (monitor's min-kill-interval gives it room)."""
    import ray_tpu as rt

    rt.init(
        num_cpus=2,
        _system_config={
            "memory_monitor_refresh_ms": 200,
            "memory_usage_threshold": 1.01,  # never triggers
        },
    )
    try:

        @rt.remote(max_retries=2)
        def quick():
            return "done"

        assert rt.get(quick.remote(), timeout=30) == "done"
    finally:
        rt.shutdown()
