"""Data-plane observability tests (ISSUE 20): get-path provenance,
the head's per-(job, src_node, dst_node) transfer matrix, the
object-location index, and the doctor's locality verdict.

Reference behavior model: ray's object-store metrics + the locality
half of `ray memory` — here the classification happens at the get
resolution site (inline / local arena / remote pull / spill restore),
rides the existing metrics pipe (never a per-get RPC), and the head
folds it into the MemoryLedger's bounded flow matrix.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MB = 1024 * 1024


# -- ledger units (no cluster) ----------------------------------------


def _ledger():
    from ray_tpu._private.memory_ledger import MemoryLedger

    return MemoryLedger()


def test_transfer_matrix_folds_and_sorts():
    led = _ledger()
    led.record_transfer("jobA", "n1", "n2", "pull", 8 * MB, ms=10.0)
    led.record_transfer("jobA", "n1", "n2", "pull", 2 * MB, ms=5.0)
    led.record_transfer("jobB", "n2", "n2", "restore", MB, ms=1.0)
    s = led.transfer_summary()
    assert s["flows"][0]["bytes"] == 10 * MB  # bytes-descending
    top = s["flows"][0]
    assert (top["job"], top["src"], top["dst"]) == ("jobA", "n1", "n2")
    assert top["cross_node"] is True
    assert top["pulls"] == 2
    assert top["mb_per_s"] > 0
    restore = s["flows"][1]
    assert restore["cross_node"] is False
    assert restore["restores"] == 1
    assert restore["restored_bytes"] == MB


def test_aborted_pull_counted_never_billed_as_bytes():
    """The chaos contract: a pull that dies mid-flight bumps the
    flow's aborted count and NOTHING else — the retry that succeeds
    bills the bytes exactly once."""
    led = _ledger()
    led.record_transfer("j", "n1", "n2", "aborted", 8 * MB, ms=3.0)
    row = led.transfer_summary()["flows"][0]
    assert row["aborted"] == 1
    assert row["bytes"] == 0
    assert row["pulls"] == 0
    led.record_transfer("j", "n1", "n2", "pull", 8 * MB, ms=12.0)
    row = led.transfer_summary()["flows"][0]
    assert row["bytes"] == 8 * MB  # billed once, by the success
    assert row["aborted"] == 1


def test_flow_table_bounded():
    from ray_tpu._private.memory_ledger import _MAX_FLOWS

    led = _ledger()
    for i in range(_MAX_FLOWS + 50):
        led.record_transfer("j", f"src{i}", "dst", "pull", i + 1)
    flows = led.transfer_summary()["flows"]
    assert len(flows) <= _MAX_FLOWS
    # Smallest-bytes flows were the evictees: the hot flows survive.
    assert flows[0]["bytes"] == _MAX_FLOWS + 50


def test_record_gets_provenance_locality_and_task_attribution():
    led = _ledger()
    led.record_gets("j", "local", "", "n1", "t", 3, 3 * MB)
    led.record_gets("j", "pull", "n9", "n1", "t", 1, 8 * MB, ms=5.0)
    led.record_gets("j", "restore_local", "", "n1", "t", 1, MB, ms=2.0)
    led.record_gets("j", "bogus", "", "n1", "t", 9, 9 * MB)  # dropped
    s = led.transfer_summary()
    prov = s["provenance"]["j"]
    assert set(prov) == {"local", "pull", "restore_local"}
    assert prov["pull"] == {"gets": 1, "bytes": 8 * MB, "wait_ms": 5.0}
    # inline/local are hits; pull and BOTH restore classes are misses
    # (a restore means the working set left the arena).
    assert s["locality"]["j"]["hits"] == 3
    assert s["locality"]["j"]["misses"] == 2
    task = s["tasks"][0]
    assert task["task"] == "t"
    assert task["remote_bytes"] == 8 * MB  # pull/restore_remote only
    assert task["local_bytes"] == 4 * MB
    assert task["by_src"] == {"n9": 8 * MB}


def test_metric_entries_expose_transfer_series():
    led = _ledger()
    led.record_transfer("j", "n1", "n2", "pull", 4 * MB, ms=8.0)
    led.record_gets("j", "pull", "n1", "n2", "t", 2, 4 * MB, ms=8.0)
    led.record_gets("j", "local", "", "n2", "t", 6, MB)
    entries = led.metric_entries()
    xfer = entries["rt_object_transfer_bytes_total"]
    assert xfer["kind"] == "counter"
    assert xfer["total"] == 4 * MB
    (tag_key,) = xfer["by_tags"]
    # src/dst at NODE granularity as SEPARATE labels — the only
    # identity shape lint rule RT010 permits on these series.
    assert "src_node=n1" in tag_key and "dst_node=n2" in tag_key
    assert "job=j" in tag_key
    assert "rt_object_pull_ms" in entries
    hits = entries["rt_job_locality_hits_total"]
    misses = entries["rt_job_locality_misses_total"]
    assert hits["by_tags"]["job=j"]["total"] == 6
    assert misses["by_tags"]["job=j"]["total"] == 2


def test_build_node_report_and_jobs_carry_per_job_spill_ops():
    from ray_tpu._private.memory_ledger import build_node_report

    report = build_node_report(
        "n1",
        [],
        {"used": 0, "capacity": 1 << 30, "num_objects": 0},
        job_spill_ops={"j": 3},
        job_restore_ops={"j": 1},
    )
    assert report["job_spill_ops"] == {"j": 3}
    assert report["job_restore_ops"] == {"j": 1}
    led = _ledger()
    led.fold(report)
    jobs = led.jobs()
    assert jobs["j"]["spill_ops"] == 3
    assert jobs["j"]["restore_ops"] == 1
    s = led.transfer_summary()
    assert s["job_spill_ops"] == {"j": 3}
    assert s["job_restore_ops"] == {"j": 1}


def test_data_verdict_convicts_misplaced_task_only_with_capacity():
    led = _ledger()
    # 8 MB pulled remotely, 100% of the task's get bytes: over the
    # 1 MB floor and the 0.5 miss threshold.
    led.record_gets(
        "j", "pull", "n9", "n1", "consume", 4, 8 * MB, ms=40.0
    )
    v = led.data_verdict(node_has_capacity=lambda node: True)
    assert len(v["misplaced_tasks"]) == 1
    row = v["misplaced_tasks"][0]
    assert row["task"] == "consume"
    assert row["src"] == "n9"
    assert row["remote_fraction"] == 1.0
    assert "consume" in row["detail"]
    # Same evidence, but the copy-holding node was full: no conviction
    # (the task could not have run there anyway).
    v2 = led.data_verdict(node_has_capacity=lambda node: False)
    assert v2["misplaced_tasks"] == []


def test_data_verdict_classifies_pull_vs_restore_dominated():
    led = _ledger()
    led.record_transfer("pullers", "n1", "n2", "pull", 16 * MB, ms=9.0)
    led.record_transfer("pagers", "n2", "n2", "restore", 8 * MB)
    led.record_transfer("pagers", "n2", "n2", "restore", 8 * MB)
    v = led.data_verdict()
    assert v["jobs"]["pullers"]["classification"] == "pull_dominated"
    assert v["jobs"]["pagers"]["classification"] == "restore_dominated"
    # Hottest flow: the largest CROSS-node flow (restores are local).
    assert v["hottest_flow"]["job"] == "pullers"
    assert v["hottest_flow"]["src"] == "n1"


def test_data_verdict_ignores_small_remote_pulls():
    led = _ledger()
    led.record_gets("j", "pull", "n9", "n1", "tiny", 50, 512 * 1024)
    v = led.data_verdict(node_has_capacity=lambda node: True)
    assert v["misplaced_tasks"] == []  # under the 1 MB evidence floor


# -- single-node session end-to-end -----------------------------------


def test_list_objects_gains_node_copies_source_columns(rt_session):
    rt = rt_session
    from ray_tpu.util import state

    ref = rt.put(np.zeros(MB // 8, dtype=np.float64))  # 1 MB, not inline
    assert rt.get(ref) is not None
    rows = state.list_objects()
    assert rows, "object table empty after a 1 MB put"
    big = rows[0]  # size-descending: the put is the biggest thing here
    assert {"node", "copies", "source"} <= set(big)
    assert big["copies"] >= 1
    assert big["source"] in ("local", "inline")
    assert big["node"], "a sealed copy must name its holder node"


def test_object_locations_index(rt_session):
    rt = rt_session
    from ray_tpu.util import state

    ref = rt.put(np.ones(MB // 4, dtype=np.float64))  # 2 MB
    assert float(rt.get(ref).sum()) == MB // 4
    oid = ref.hex()
    rows = state.object_locations(object_ids=[oid])
    assert len(rows) == 1
    row = rows[0]
    assert row["object_id"] == oid
    assert row["size"] >= 2 * MB
    assert row["nodes"], "the driver node holds the copy"
    assert row["spilled"] is False
    # Unfiltered: size-descending, our 2 MB object near the top.
    all_rows = state.object_locations()
    assert all_rows[0]["size"] >= all_rows[-1]["size"]
    assert oid in {r["object_id"] for r in all_rows}


def test_driver_get_provenance_reaches_transfer_summary(rt_session):
    rt = rt_session
    from ray_tpu.util import metrics, state

    job = rt.get_runtime_context().get_job_id()
    ref = rt.put(np.zeros(MB // 2, dtype=np.float64))  # 4 MB shm path
    assert rt.get(ref) is not None
    deadline = time.time() + 20
    prov = {}
    while time.time() < deadline:
        metrics.flush()
        prov = state.transfer_summary()["provenance"].get(job, {})
        if prov.get("local", {}).get("bytes", 0) >= 4 * MB:
            break
        time.sleep(0.3)
    assert prov.get("local", {}).get("bytes", 0) >= 4 * MB, prov
    # Locality: a driver-local arena hit counts as a hit.
    loc = state.transfer_summary()["locality"][job]
    assert loc["hits"] >= 1


def test_get_provenance_instrument_under_one_percent_of_smoke_step(
    rt_session,
):
    """The hard bar from ISSUE 20: the per-get classify+fold must cost
    <1% of a --smoke train step, measured against the same
    conservative 20 ms floor the compile-watch and lock-witness bars
    use, so the test doesn't flake under CI load."""
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    n = 5000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            worker._record_get("local", "", 4096, 0.05)
        best = min(best, (time.perf_counter() - t0) / n)
    overhead_ms = best * 1e3
    smoke_step_floor_ms = 20.0
    assert overhead_ms < 0.01 * smoke_step_floor_ms, (
        f"get-provenance instrument costs {overhead_ms:.4f} ms per "
        f"get — over 1% of a {smoke_step_floor_ms} ms smoke step"
    )


def test_transfer_summary_reports_disabled_when_gated(rt_session):
    """transfer_report_interval_s <= 0 turns the whole instrument off;
    the summary says so instead of serving silently-empty tables."""
    rt = rt_session
    from ray_tpu.util import state

    daemon = rt.api._session.daemon
    old = daemon.config.transfer_report_interval_s
    daemon.config.transfer_report_interval_s = 0.0
    try:
        assert state.transfer_summary().get("disabled") is True
    finally:
        daemon.config.transfer_report_interval_s = old
    assert state.transfer_summary().get("disabled") is not True


# -- two-node smoke + chaos (slow) -------------------------------------


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_two_node_matrix_prometheus_and_misplaced_doctor(tmp_path):
    """CI smoke (satellite): producer pinned to the worker node,
    consumer pinned to the head — every consume get crosses the wire.
    The transfer matrix must account >=95% of the measured cross-node
    bytes, the same flows must surface on /metrics and /api/transfers,
    and `ray_tpu doctor --json` (a separate process, like an operator
    would run it) must exit 1 naming the flow and the misplaced
    consumer."""
    import urllib.request

    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dashboard import start_dashboard

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)

    c = Cluster(
        initialize_head=True,
        head_resources={"CPU": 2.0, "head_node": 4.0},
        # Fast report/drain ticks so the matrix fills within the
        # test's patience.
        system_config={
            "memory_report_interval_s": 0.2,
            "transfer_report_interval_s": 0.1,
        },
    )
    c.add_node(num_cpus=2, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:

        @rt.remote(resources={"remote_node": 1.0})
        def produce():
            return np.arange(MB, dtype=np.uint64)  # 8 MB payload

        @rt.remote(resources={"head_node": 1.0})
        def consume(refs):
            # Explicit get INSIDE the task: the get classifies under
            # the task's name, which is what the misplacement verdict
            # convicts.
            return float(rt.get(refs[0]).sum())

        total_payload = 0
        for _ in range(3):
            ref = produce.remote()
            assert rt.get(consume.remote([ref]), timeout=120) > 0
            total_payload += 8 * MB

        from ray_tpu.util import metrics, state

        deadline = time.time() + 60
        cross_bytes, summary = 0, {}
        while time.time() < deadline:
            metrics.flush()
            summary = state.transfer_summary()
            cross_bytes = sum(
                f["bytes"]
                for f in summary["flows"]
                if f["cross_node"]
            )
            tasks_seen = {
                t["task"]
                for t in summary["tasks"]
                if t["remote_bytes"] >= 8 * MB
            }
            if (
                cross_bytes >= int(0.95 * total_payload)
                and "consume" in tasks_seen
            ):
                break
            time.sleep(0.5)
        # The >=95% accounting bar: every measured cross-node byte of
        # the 3 x 8 MB payloads shows up in the matrix.
        assert cross_bytes >= int(0.95 * total_payload), summary
        top = max(
            (f for f in summary["flows"] if f["cross_node"]),
            key=lambda f: f["bytes"],
        )
        assert top["pulls"] >= 3

        # Prometheus + dashboard surfaces serve the same matrix.
        dash = start_dashboard(port=0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/metrics", timeout=30
            ) as resp:
                text = resp.read().decode()
            assert (
                "# TYPE rt_object_transfer_bytes_total counter" in text
            )
            assert 'src_node="' in text and 'dst_node="' in text
            assert "rt_job_locality_misses_total" in text
            with urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/api/transfers",
                timeout=30,
            ) as resp:
                api = json.loads(resp.read().decode())
            assert any(f["cross_node"] for f in api["flows"])
            assert api["tasks"], api
        finally:
            dash.stop()

        # The operator's view: doctor exits 1 and names the flow and
        # the misplaced consumer (head had the pulls, the worker node
        # had both the bytes and idle CPU).
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "doctor",
                "--json",
                "--no-stacks",
                "--address",
                c.address,
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 1, out.stdout + out.stderr
        verdict = json.loads(out.stdout)
        data = verdict["data"]
        assert data["hottest_flow"]["cross_node"] is True
        misplaced = [
            p
            for p in verdict["problems"]
            if p["kind"] == "misplaced_task"
        ]
        assert any(p["task"] == "consume" for p in misplaced), (
            verdict["problems"]
        )
        assert any(
            s["task"] == "consume" for s in data["misplaced_tasks"]
        )
    finally:
        rt.shutdown()
        c.shutdown()


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_kill_holder_mid_pull_counts_abort_never_bills_bytes():
    """Chaos (satellite): the only copy-holder dies while the driver
    node is pulling (chaos-dropped chunk RPCs hold the pull in its
    retry loop across the kill). The get must error (nothing was
    spilled, so no restore path exists), the aborted attempts must be
    counted — rt_object_pulls_aborted_total and the flow's aborted
    column — and the flow must bill ZERO transferred bytes: a dead
    pull is never double-billed as moved data."""
    import ray_tpu as rt
    from ray_tpu._private.rpc import configure_chaos
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        initialize_head=True,
        head_resources={"CPU": 2.0},
        system_config={
            "memory_report_interval_s": 0.2,
            "transfer_report_interval_s": 0.1,
            # The native arenas of two same-host daemons take the
            # mmap fast path, which never issues the chunk RPCs chaos
            # targets; the py store forces the socket pull path a
            # real cross-host cluster uses.
            "use_native_object_store": False,
        },
    )
    node = c.add_node(num_cpus=2, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:

        @rt.remote(resources={"remote_node": 1.0})
        def produce():
            return np.arange(MB, dtype=np.uint64)  # 8 MB, holder-only

        ref = produce.remote()
        # Wait via the head's location index, NOT rt.wait: wait()
        # resolves the object locally, which would complete the pull
        # before chaos is armed.
        from ray_tpu.util import state

        deadline = time.time() + 60
        holders = []
        while time.time() < deadline and not holders:
            rows = state.object_locations(object_ids=[ref.hex()])
            holders = rows[0]["nodes"] if rows else []
            time.sleep(0.2)
        assert holders, "producer never sealed its result"

        # Drop every pull chunk: the pull dies mid-flight, retries,
        # and keeps dying — the window in which we kill the holder.
        # The budget must outlast the retry storm (each re-armed pull
        # burns a window of chunk tokens, thousands per second).
        configure_chaos("pull_object=100000000")
        try:
            with pytest.raises(Exception):
                rt.get(ref, timeout=8)
            c.remove_node(node)  # the only copy is gone for good
            with pytest.raises(Exception):
                rt.get(ref, timeout=8)
        finally:
            configure_chaos("")

        from ray_tpu.util import metrics

        deadline = time.time() + 30
        aborted, flows = 0, []
        while time.time() < deadline:
            metrics.flush()
            flows = state.transfer_summary()["flows"]
            aborted = sum(f["aborted"] for f in flows)
            if aborted >= 1:
                break
            time.sleep(0.5)
        assert aborted >= 1, flows
        # Never double-billed: no cross-node flow carries bytes (the
        # payload never completed a pull).
        assert all(
            f["bytes"] == 0 for f in flows if f["cross_node"]
        ), flows
        summary = metrics.metrics_summary()
        assert (
            summary.get("rt_object_pulls_aborted_total", {}).get(
                "total", 0
            )
            >= 1
        ), {k: v for k, v in summary.items() if "abort" in k}
    finally:
        rt.shutdown()
        c.shutdown()
