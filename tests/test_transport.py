"""Cross-host transport tests (reference: gRPC transport,
src/ray/rpc/grpc_server.h; object transfer object_manager.h).

Covers the TCP wire directly (framing, HMAC auth, address parsing),
and the headline scenario of VERDICT round-1 item 1: head and worker
daemons in SEPARATE PROCESSES with SEPARATE SESSION DIRS joined over
TCP loopback, where a multi-megabyte object produced on the worker
node reaches the driver through chunked pulls over the socket — no
shared shm namespace between the node stores."""

import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from ray_tpu._private.rpc import (
    ConnectionLost,
    RpcClient,
    RpcError,
    RpcServer,
    parse_address,
)


def test_parse_address():
    assert parse_address("/tmp/x/hostd.sock") == ("unix", "/tmp/x/hostd.sock")
    assert parse_address("unix:///a/b") == ("unix", "/a/b")
    assert parse_address("tcp://10.0.0.1:6379") == ("tcp", "10.0.0.1", 6379)
    assert parse_address("127.0.0.1:8000") == ("tcp", "127.0.0.1", 8000)
    with pytest.raises(ValueError):
        parse_address("nonsense")


def test_tcp_rpc_roundtrip():
    server = RpcServer("tcp://127.0.0.1:0")
    try:
        assert server.address.startswith("tcp://127.0.0.1:")
        server.register("echo", lambda conn, msg: {"out": msg["x"] * 2})
        server.start()
        client = RpcClient(server.address)
        try:
            assert client.call("echo", x=21)["out"] == 42
            # Payloads with numpy arrays survive the authed frame.
            server.register("sum", lambda conn, msg: {
                "s": float(np.asarray(msg["arr"]).sum())
            })
            arr = np.arange(100_000, dtype=np.float64)
            assert client.call("sum", arr=arr)["s"] == float(arr.sum())
        finally:
            client.close()
    finally:
        server.close()


def test_dual_listener_unix_and_tcp(tmp_path):
    """One server, one handler table, two transports — workers ride
    the Unix socket while remote daemons ride TCP."""
    server = RpcServer(str(tmp_path / "s.sock"))
    tcp_addr = server.add_listener("tcp://127.0.0.1:0")
    server.register("who", lambda conn, msg: {"ok": True})
    server.start()
    try:
        for addr in (str(tmp_path / "s.sock"), tcp_addr):
            c = RpcClient(addr)
            try:
                assert c.call("who")["ok"]
            finally:
                c.close()
    finally:
        server.close()


def test_wrong_auth_key_rejected():
    """Frames that fail HMAC verification never reach pickle; the
    connection dies and the client surfaces a transport error."""
    server = RpcServer("tcp://127.0.0.1:0", auth_key=b"right-key")
    server.register("op", lambda conn, msg: {"ok": True})
    server.start()
    try:
        bad = RpcClient(server.address, auth_key=b"wrong-key")
        try:
            with pytest.raises((RpcError, ConnectionLost)):
                bad.call("op", timeout=5)
        finally:
            bad.close()
        good = RpcClient(server.address, auth_key=b"right-key")
        try:
            assert good.call("op", timeout=5)["ok"]
        finally:
            good.close()
    finally:
        server.close()


_HEAD_SCRIPT = textwrap.dedent("""
    import json, signal, sys, time
    sys.path.insert(0, {repo!r})
    from ray_tpu._private.config import Config
    from ray_tpu._private.daemon import NodeDaemon

    daemon = NodeDaemon(
        {session!r},
        {{"CPU": 2.0, "memory": float(2**32)}},
        Config.from_env(None),
        is_head=True,
        listen_host="127.0.0.1",
    )
    daemon.start()
    with open({info!r}, "w") as f:
        json.dump({{"address": daemon.address}}, f)
    signal.pause()
""")

_NODE_SCRIPT = textwrap.dedent("""
    import signal, sys
    sys.path.insert(0, {repo!r})
    from ray_tpu._private.config import Config
    from ray_tpu._private.daemon import NodeDaemon

    daemon = NodeDaemon(
        {session!r},
        {{"CPU": 2.0, "memory": float(2**32), "remote_only": 2.0}},
        Config.from_env(None),
        is_head=False,
        head_address={head!r},
        listen_host="127.0.0.1",
    )
    daemon.start()
    print("node up", flush=True)
    signal.pause()
""")


def test_two_processes_separate_sessions_tcp(tmp_path):
    """Two daemon processes, two session dirs, TCP-only peering: a
    ~4 MB array produced on the worker node must cross the socket via
    chunked pull (distinct node store namespaces — nothing to attach)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    info_path = str(tmp_path / "info.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = []
    try:
        head = subprocess.Popen(
            [sys.executable, "-c", _HEAD_SCRIPT.format(
                repo=repo, session=str(tmp_path / "head"), info=info_path
            )],
            env=env,
        )
        procs.append(head)
        deadline = time.time() + 30
        while not os.path.exists(info_path):
            assert time.time() < deadline, "head did not come up"
            assert head.poll() is None, "head daemon died"
            time.sleep(0.1)
        import json

        with open(info_path) as f:
            head_addr = json.load(f)["address"]
        assert head_addr.startswith("tcp://")

        node = subprocess.Popen(
            [sys.executable, "-c", _NODE_SCRIPT.format(
                repo=repo, session=str(tmp_path / "node"), head=head_addr
            )],
            env=env,
        )
        procs.append(node)

        import ray_tpu as rt

        rt.init(address=head_addr)
        try:
            deadline = time.time() + 30
            while len([n for n in rt.nodes() if n["alive"]]) < 2:
                assert time.time() < deadline, "node never joined"
                time.sleep(0.2)

            @rt.remote(resources={"remote_only": 1.0})
            def produce():
                return np.arange(500_000, dtype=np.float64)  # ~4 MB

            arr = rt.get(produce.remote(), timeout=60)
            assert arr.shape == (500_000,)
            assert float(arr[424_242]) == 424_242.0

            # Driver-side large arg consumed on the remote node: bytes
            # travel the other direction too.
            big = np.full(300_000, 7.0)

            @rt.remote(resources={"remote_only": 1.0})
            def total(x):
                return float(x.sum())

            assert rt.get(total.remote(big), timeout=60) == 7.0 * 300_000
        finally:
            rt.shutdown()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
