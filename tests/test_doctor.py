"""Stall-doctor tests (reference test model: observability e2e tests
over ray's state API + dashboard profiling relay).

Covers the three diagnosis sources end to end: step telemetry
(straggler detection + gang skew), per-worker in-flight inspection
(hung tasks, with the offender's stack auto-captured through the
profile relay), and the flight-recorder rings — plus the
`ray_tpu doctor --json` CLI exit-code contract (0 healthy, 1 problems
found; same shape as lint/check) on a 2-node cluster with one
artificially delayed worker.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _emit_steps(rank: int, step_ms: float, steps: int = 5) -> None:
    from ray_tpu.train import telemetry

    for step in range(1, steps + 1):
        telemetry.report_step(
            step, rank=rank, step_ms=step_ms, wall_ms=step_ms + 10.0
        )


def test_diagnose_healthy_cluster(rt_session):
    rt = rt_session
    verdict = rt.diagnose(capture_stacks=False)
    assert verdict["healthy"] is True
    assert verdict["problems"] == []
    assert verdict["nodes"]["alive"] >= 1
    assert "params" in verdict
    assert verdict["params"]["leak_age_s"] == 300.0
    # verdict.memory rides every diagnosis; a healthy cluster has no
    # memory findings.
    memory = verdict["memory"]
    assert memory["leak_suspects"] == []
    assert memory["near_capacity"] == []
    assert memory["spill_thrash"] == []


def test_diagnose_flags_straggler_rank(rt_session):
    """Per-step records from two ranks, one 4x slower: the verdict
    names the slow rank, its ratio, and the gang skew it causes."""
    rt = rt_session
    _emit_steps(rank=0, step_ms=100.0)
    _emit_steps(rank=1, step_ms=400.0)
    verdict = rt.diagnose(
        straggler_threshold=1.5, capture_stacks=False
    )
    stragglers = [
        p for p in verdict["problems"] if p["kind"] == "straggler"
    ]
    assert len(stragglers) == 1
    assert stragglers[0]["rank"] == 1
    assert stragglers[0]["ratio"] == pytest.approx(4.0)
    assert verdict["steps"]["max_skew_ms"] == pytest.approx(300.0)
    # Both ranks' per-worker stats are in the verdict for context.
    assert set(verdict["steps"]["workers"]) == {0, 1}


def test_step_summary_round_trip(rt_session):
    from ray_tpu.train import telemetry

    _emit_steps(rank=0, step_ms=50.0, steps=3)
    summary = telemetry.step_summary()
    assert summary["steps_observed"] == 3
    assert summary["workers"][0]["p50_step_ms"] == pytest.approx(50.0)
    records = telemetry.step_records()
    assert len(records) == 3
    assert {r["step"] for r in records} == {1, 2, 3}
    # wall - step = the 10 ms of waits _emit_steps bakes in.
    assert records[0]["wall_ms"] == pytest.approx(60.0)


def test_step_summary_isolates_jobs():
    """Straggler/skew stats must never be computed over a mixture of
    jobs: an older job's slow steps in the ring would otherwise fake
    a straggler in (or hide one from) the current run."""
    from ray_tpu._private.daemon import _summarize_steps

    old = [
        {"step": s, "rank": 0, "step_ms": 500.0,
         "time": 100.0 + s, "job": "a"}
        for s in range(1, 6)
    ]
    new = [
        {"step": s, "rank": 0, "step_ms": 100.0,
         "time": 200.0 + s, "job": "b"}
        for s in range(1, 6)
    ]
    summary = _summarize_steps(old + new)
    assert summary["jobs_observed"] == 2
    # Only the newest job's records feed the stats.
    assert summary["workers"][0]["steps"] == 5
    assert summary["workers"][0]["p50_step_ms"] == pytest.approx(
        100.0
    )


def test_session_report_emits_step_telemetry(rt_session):
    """The tentpole's always-on path: a train session's report() is
    the step boundary — each one emits a (step, rank) record through
    the metrics pipe carrying the wait phases the data layer
    accumulated (here: a real Dataset.iter_batches drive), and the
    head's summary shows both ranks."""
    rt = rt_session

    @rt.remote
    def run_gang_member(rank):
        import time as _time

        import ray_tpu.data as rtd
        from ray_tpu.train.session import (
            TrainContext,
            clear_session,
            init_session,
            report,
        )
        from ray_tpu.util import metrics

        dataset = rtd.range(12)
        init_session(TrainContext(world_rank=rank, world_size=2))
        try:
            for _ in dataset.iter_batches(batch_size=4):
                _time.sleep(0.01 * (1 + rank))  # the "step"
                report({"loss": 1.0})
        finally:
            clear_session()
        metrics.flush()
        return rank

    assert rt.get(
        [run_gang_member.remote(r) for r in range(2)], timeout=120
    ) == [0, 1]
    from ray_tpu.train import telemetry

    deadline = time.time() + 15
    summary = {}
    while time.time() < deadline:
        summary = telemetry.step_summary()
        if set(summary.get("workers", {})) == {0, 1}:
            break
        time.sleep(0.3)
    assert set(summary["workers"]) == {0, 1}
    assert summary["steps_observed"] == 3
    records = telemetry.step_records()
    # Every record carries the data plane's consumer-visible stall
    # and a non-negative step residual.
    assert all("data_wait_ms" in r for r in records)
    assert all(r["step_ms"] >= 0.0 for r in records)
    assert all(r["wall_ms"] > 0.0 for r in records)


def test_diagnose_hung_task_captures_stack(rt_session):
    """A task sleeping past the deadline is reported hung, and the
    verdict carries the worker's auto-captured stack showing the
    offending frame (acceptance criterion b)."""
    rt = rt_session

    @rt.remote
    def hang_forever():
        time.sleep(300)

    ref = hang_forever.remote()
    try:
        deadline = time.time() + 60
        hung = []
        while time.time() < deadline and not hung:
            verdict = rt.diagnose(hung_task_s=0.5)
            hung = [
                p
                for p in verdict["problems"]
                if p["kind"] == "hung_task"
            ]
            if not hung:
                time.sleep(0.3)
        assert hung, "hung task never detected"
        assert hung[0]["name"] == "hang_forever"
        assert hung[0]["age_s"] > 0.5
        assert "hang_forever" in hung[0].get("stack", ""), (
            "stack dump should show the hung frame: "
            f"{hung[0].get('stack', hung[0].get('stack_error'))!r}"
        )
    finally:
        rt.cancel(ref, force=True)


def test_diagnose_exempts_progressing_train_task(rt_session):
    """A long-lived in-flight task whose worker reports step
    telemetry within the deadline is a train loop making progress,
    not a hang — gang fit tasks run ONE task for the whole job, and
    a doctor that flagged every healthy training run would bury the
    real signal (and break the exit-0-when-healthy contract)."""
    rt = rt_session

    @rt.remote
    def fit(total_s):
        import time as _time

        from ray_tpu.train import telemetry
        from ray_tpu.util import metrics

        t_end = _time.time() + total_s
        step = 0
        while _time.time() < t_end:
            step += 1
            telemetry.report_step(
                step, rank=0, step_ms=50.0, wall_ms=60.0
            )
            metrics.flush()
            _time.sleep(0.2)
        return step

    ref = fit.remote(12.0)
    # Wait until the fit task's telemetry is actually flowing (worker
    # spawn + first-iteration jax import can eat seconds), THEN let it
    # run past the 0.5s deadline: what's under test is the exemption
    # of a PROGRESSING task, not spawn latency.
    from ray_tpu.train import telemetry

    deadline = time.time() + 30.0
    while not telemetry.step_records(limit=1):
        assert time.time() < deadline, "fit never reported a step"
        time.sleep(0.1)
    time.sleep(1.0)  # now in flight well past the 0.5s hung deadline
    try:
        verdict = rt.diagnose(hung_task_s=0.5, capture_stacks=False)
        hung = [
            p
            for p in verdict["problems"]
            if p["kind"] == "hung_task"
        ]
        assert hung == [], hung
    finally:
        assert rt.get(ref, timeout=60) > 0


def test_flight_recorder_rings_pull_lazily(rt_session):
    """Rings exist per process and are pulled over RPC on demand:
    the head's ring shows server-side handling, the driver's shows
    client latencies, and a worker's (routed by pid) shows task
    begin/end records."""
    rt = rt_session

    @rt.remote
    def work(x):
        return x * 2

    assert rt.get([work.remote(i) for i in range(3)], timeout=60) == [
        0,
        2,
        4,
    ]
    from ray_tpu._private.flight_recorder import recorder
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    head = worker.call("flight_recorder")
    assert any(r["kind"] == "rpc.server" for r in head["records"])
    assert any(
        k.startswith("rpc.server:") for k in head["summary"]
    )
    # The driver records its own outbound calls locally — no RPC
    # needed to read your own ring.
    own = recorder().snapshot(kinds=["rpc.client"])
    assert own and all(r["kind"] == "rpc.client" for r in own)
    # Worker rings route by pid and carry task events.
    rows = worker.call("worker_inspect")["workers"]
    task_records = []
    for row in rows:
        if row.get("error"):
            continue
        reply = worker.call("flight_recorder", pid=row["pid"])
        task_records.extend(
            r
            for r in reply["records"]
            if r["kind"] == "task" and r["name"] == "work"
        )
    assert len(task_records) == 3
    assert all(r["dur_ms"] >= 0.0 for r in task_records)


def test_flight_recorder_disabled_is_inert():
    from ray_tpu._private.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=64, enabled=False)
    rec.record("rpc.client", "x", 1.0)
    assert rec.snapshot() == []
    rec.enabled = True
    rec.record("rpc.client", "x", 1.0, {"error": True})
    assert rec.summary()["rpc.client:x"]["errors"] == 1


def test_flight_recorder_env_kill_switch_survives_configure():
    """RT_flight_recorder_enabled=0 is the documented PER-PROCESS
    kill-switch: applying the cluster config at registration must not
    re-enable a ring this process's env disabled."""
    from ray_tpu._private import flight_recorder
    from ray_tpu._private.config import Config

    rec = flight_recorder.recorder()
    prev_enabled = rec.enabled
    prev_env = os.environ.get("RT_flight_recorder_enabled")
    try:
        os.environ["RT_flight_recorder_enabled"] = "0"
        flight_recorder.configure(
            Config(flight_recorder_enabled=True)
        )
        assert rec.enabled is False
        del os.environ["RT_flight_recorder_enabled"]
        flight_recorder.configure(
            Config(flight_recorder_enabled=True)
        )
        assert rec.enabled is True
    finally:
        if prev_env is None:
            os.environ.pop("RT_flight_recorder_enabled", None)
        else:
            os.environ["RT_flight_recorder_enabled"] = prev_env
        rec.enabled = prev_enabled


def test_flight_recorder_ring_is_bounded():
    from ray_tpu._private.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=32)
    for i in range(100):
        rec.record("task", f"t{i}", 1.0)
    snap = rec.snapshot()
    assert len(snap) == 32
    assert snap[-1]["name"] == "t99"  # newest kept, oldest evicted


@pytest.mark.slow
def test_doctor_cli_smoke_two_nodes_one_delayed_worker(tmp_path):
    """CI smoke (satellite): a 2-node cluster where one gang worker is
    artificially delayed per step; `ray_tpu doctor --json` (a separate
    process, like an operator would run it) must exit 1 and name the
    straggler rank; on a freshly quiet cluster it must exit 0.
    `--trace` writes a merged chrome trace containing step phases."""
    from ray_tpu.cluster_utils import Cluster

    import ray_tpu as rt

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)

    c = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    c.add_node(num_cpus=2, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:

        @rt.remote
        def gang_member(rank, delay_s):
            from ray_tpu.train import telemetry
            from ray_tpu.util import metrics

            for step in range(1, 6):
                t0 = time.monotonic()
                time.sleep(delay_s)  # the "step"
                telemetry.report_step(
                    step,
                    rank=rank,
                    wall_ms=(time.monotonic() - t0) * 1e3,
                )
            metrics.flush()
            return rank

        fast = gang_member.options(
            resources={"remote_node": 1.0}
        ).remote(0, 0.01)
        slow = gang_member.remote(1, 0.2)  # the delayed worker
        assert rt.get([fast, slow], timeout=120) == [0, 1]

        trace_out = tmp_path / "doctor_trace.json"
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "doctor",
                "--json",
                "--address",
                c.address,
                "--straggler-threshold",
                "3.0",
                "--no-stacks",
                "--trace",
                str(trace_out),
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 1, out.stdout + out.stderr
        verdict = json.loads(out.stdout)
        stragglers = [
            p
            for p in verdict["problems"]
            if p["kind"] == "straggler"
        ]
        assert [p["rank"] for p in stragglers] == [1], verdict[
            "problems"
        ]
        assert verdict["steps"]["max_skew_ms"] > 0
        # The merged chrome trace has the per-rank step phases.
        trace = json.loads(trace_out.read_text())
        step_rows = {
            e["tid"] for e in trace if e.get("cat") == "step"
        }
        assert {"rank 0", "rank 1"} <= step_rows
    finally:
        rt.shutdown()
        c.shutdown()

    # Exit-code contract, healthy side: a quiet fresh cluster -> 0.
    c2 = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "doctor",
                "--json",
                "--address",
                c2.address,
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert json.loads(out.stdout)["healthy"] is True
    finally:
        c2.shutdown()
