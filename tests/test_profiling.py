"""On-demand profiler tests.

Reference test model: dashboard reporter profiling endpoints
(py-spy/memray attach) — here the profilers run in-process
(_private/profiling.py), so the unit layer needs no cluster; the
integration layer drives the dashboard /api/profile route through a
live session.
"""

import threading
import time

from ray_tpu._private import profiling


def test_dump_stacks_contains_this_function():
    text = profiling.dump_stacks()
    assert "test_dump_stacks_contains_this_function" in text
    assert "thread" in text


def test_sample_cpu_catches_hot_function():
    stop = threading.Event()

    def spin_hot_loop():
        while not stop.is_set():
            sum(i * i for i in range(200))

    thread = threading.Thread(target=spin_hot_loop, daemon=True)
    thread.start()
    try:
        result = profiling.sample_cpu(duration_s=0.6, hz=200)
    finally:
        stop.set()
        thread.join(timeout=5)
    assert result["samples"] > 10
    assert "spin_hot_loop" in result["folded"]
    # Folded format: "frame;frame;... N" per line.
    hot_lines = [
        line
        for line in result["folded"].splitlines()
        if "spin_hot_loop" in line
    ]
    assert hot_lines
    count = int(hot_lines[0].rsplit(" ", 1)[1])
    assert count > 0


def test_sample_cpu_excludes_profiler_thread():
    result = profiling.sample_cpu(duration_s=0.2, hz=100)
    assert "sample_cpu" not in result["folded"]


def test_memory_profile_sees_allocations():
    allocations = []

    def churn():
        deadline = time.monotonic() + 0.4
        while time.monotonic() < deadline:
            allocations.append(bytearray(64 * 1024))
            time.sleep(0.01)

    thread = threading.Thread(target=churn, daemon=True)
    thread.start()
    result = profiling.profile_memory(duration_s=0.5, top=10)
    thread.join(timeout=5)
    assert result["top"], "no allocation sites recorded"
    formatted = "\n".join(
        line
        for entry in result["top"]
        for line in entry["traceback"]
    )
    # format() prints file/line + source text (not function names):
    # the churn allocation site is the bytearray line in this file.
    assert "test_profiling.py" in formatted
    assert "bytearray(64 * 1024)" in formatted
    del allocations


def test_profile_live_worker_via_state_api(rt_session):
    """Driver -> daemon -> worker direct endpoint: cpu profile of a
    busy actor shows its hot method; stack dump works; memory profile
    returns allocation sites."""
    rt = rt_session
    from ray_tpu.util import state

    @rt.remote
    class Busy:
        def pid(self):
            import os

            return os.getpid()

        def spin(self, seconds):
            deadline = time.monotonic() + seconds
            total = 0
            while time.monotonic() < deadline:
                total += sum(i * i for i in range(300))
            return total

    actor = Busy.remote()
    pid = rt.get(actor.pid.remote())
    spin_ref = actor.spin.remote(3.0)

    result = state.profile_worker(
        pid, kind="cpu", duration_s=1.0, hz=200
    )
    assert result["samples"] > 20
    assert "spin" in result["folded"]

    stacks = state.profile_worker(pid, kind="stack")
    assert "stacks" in stacks

    memory = state.profile_worker(
        pid, kind="memory", duration_s=0.3
    )
    assert "top" in memory
    rt.get(spin_ref)


def test_profile_via_dashboard_route(rt_session):
    rt = rt_session
    import json as json_mod
    import urllib.request

    from ray_tpu.dashboard import start_dashboard

    @rt.remote
    class Busy:
        def pid(self):
            import os

            return os.getpid()

        def spin(self, seconds):
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                sum(i * i for i in range(300))

    actor = Busy.remote()
    pid = rt.get(actor.pid.remote())
    spin_ref = actor.spin.remote(2.0)
    dashboard = start_dashboard(port=0)
    try:
        url = (
            f"http://127.0.0.1:{dashboard.port}/api/profile"
            f"?pid={pid}&kind=cpu&duration_s=0.5&hz=100"
        )
        with urllib.request.urlopen(url, timeout=60) as resp:
            payload = json_mod.loads(resp.read())
        assert payload["samples"] > 5
        assert "spin" in payload["folded"]
    finally:
        dashboard.stop()
    rt.get(spin_ref)


def test_run_profile_dispatch():
    assert "stacks" in profiling.run_profile("stack")
    cpu = profiling.run_profile("cpu", duration_s=0.05, hz=50)
    assert "folded" in cpu
    try:
        profiling.run_profile("nope")
        raise AssertionError("expected ValueError")
    except ValueError:
        pass
