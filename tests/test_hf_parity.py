"""Numerical parity of the flagship Llama against transformers'
reference implementation (torch CPU): same weights, same tokens, same
logits. This is the strongest correctness check the model stack has —
it pins RoPE convention, RMSNorm accumulation, SwiGLU gate order, GQA
repeat, attention masking, and every weight-layout transpose in
hf_convert.py at once."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from ray_tpu.models.hf_convert import config_from_hf, convert_hf_llama  # noqa: E402
from ray_tpu.models.llama import forward  # noqa: E402


def _tiny_hf_llama(n_heads=4, n_kv_heads=4, seed=0):
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(seed)
    hf_cfg = HFConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = LlamaForCausalLM(hf_cfg)
    model.eval()
    return model


def _compare(model, tokens_np, atol=2e-4):
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens_np)).logits.numpy()
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    ours = np.asarray(
        forward(params, jax.numpy.asarray(tokens_np), cfg)
    )
    diff = np.max(np.abs(ours - ref))
    assert diff < atol, f"logit mismatch: max abs diff {diff}"
    # Same argmax continuation everywhere (the check users feel).
    assert (ours.argmax(-1) == ref.argmax(-1)).all()


def test_logits_match_transformers_mha():
    model = _tiny_hf_llama(n_heads=4, n_kv_heads=4)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, (2, 33), dtype=np.int64)
    _compare(model, tokens)


def test_logits_match_transformers_gqa():
    """Grouped-query attention: kv heads < query heads exercises
    repeat_kv and the [d, kv_heads*hd] projection layout."""
    model = _tiny_hf_llama(n_heads=8, n_kv_heads=2, seed=1)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 128, (1, 48), dtype=np.int64)
    _compare(model, tokens)


def test_llama2_style_eps_respected():
    """rms_norm_eps=1e-5 (what Llama-2 ships) must map through —
    hardcoding 1e-6 converts real checkpoints into subtly different
    models."""
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(3)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = LlamaForCausalLM(hf_cfg)
    model.eval()
    cfg = config_from_hf(model.config)
    assert cfg.norm_eps == 1e-5
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 128, (1, 24), dtype=np.int64)
    _compare(model, tokens)


def test_unsupported_checkpoint_features_fail_loudly():
    from transformers import LlamaConfig as HFConfig

    scaled = HFConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2,
        num_key_value_heads=2,
        rope_scaling={"rope_type": "yarn", "factor": 2.0},
    )
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(scaled)

    class FakeConfig:
        model_type = "gpt_bigcode"
        rope_scaling = None

    with pytest.raises(NotImplementedError, match="model_type"):
        config_from_hf(FakeConfig())


def _tiny_hf_qwen2(n_heads=4, n_kv_heads=4, seed=0, tied=False):
    """Qwen2: same skeleton as Llama plus QKV projection biases — the
    second HF architecture (VERDICT r3 item 10), proving the converter
    isn't Llama-shape-hardcoded."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(seed)
    hf_cfg = Qwen2Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=tied,
        use_sliding_window=False,
        attn_implementation="eager",
    )
    model = Qwen2ForCausalLM(hf_cfg)
    model.eval()
    return model


def test_qwen2_logits_match_transformers_mha():
    model = _tiny_hf_qwen2(n_heads=4, n_kv_heads=4, seed=7)
    cfg = config_from_hf(model.config)
    assert cfg.attn_bias  # qwen2 always carries QKV biases
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 128, (2, 33), dtype=np.int64)
    _compare(model, tokens)


def test_qwen2_logits_match_transformers_gqa_tied():
    """GQA + tied embeddings (how small Qwen2 checkpoints ship)."""
    model = _tiny_hf_qwen2(n_heads=8, n_kv_heads=2, seed=8, tied=True)
    rng = np.random.default_rng(8)
    tokens = rng.integers(0, 128, (1, 48), dtype=np.int64)
    _compare(model, tokens)


def test_qwen2_greedy_decode_matches_transformers_generate():
    """The KV-cache serving path applies the biases too."""
    from ray_tpu.models.generate import generate

    model = _tiny_hf_qwen2(n_heads=4, n_kv_heads=2, seed=9)
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, 128, (2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt),
            max_new_tokens=10,
            do_sample=False,
            pad_token_id=0,
            eos_token_id=None,
        )[:, prompt.shape[1]:].numpy()
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    ours, _lengths = generate(
        params,
        jax.numpy.asarray(prompt),
        jax.numpy.asarray(np.full(2, prompt.shape[1], np.int32)),
        cfg,
        max_new_tokens=10,
        temperature=0.0,
    )
    assert np.asarray(ours).tolist() == ref.tolist()


def test_biased_llama_rejected_loudly():
    """Llama attention_bias=True biases ALL FOUR projections (incl.
    o_proj) — no slot here, so it must fail at config time, not
    convert into a numerically different model. (QKV-only biases are
    the supported biased layout — the Qwen2 tests above.)"""
    from transformers import LlamaConfig as HFConfig

    biased = HFConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2,
        num_key_value_heads=2, attention_bias=True,
        tie_word_embeddings=False,
    )
    with pytest.raises(NotImplementedError, match="attention_bias"):
        config_from_hf(biased)


def test_flash_attention_matches_hf_reference():
    """The Pallas-interpret flash path agrees with HF too (slightly
    looser: online-softmax accumulation order differs)."""
    import dataclasses

    model = _tiny_hf_llama(n_heads=4, n_kv_heads=4, seed=2)
    cfg = config_from_hf(model.config)
    cfg = dataclasses.replace(cfg, attention="flash")
    params = convert_hf_llama(model.state_dict(), cfg)
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 128, (1, 32), dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        forward(params, jax.numpy.asarray(tokens), cfg)
    )
    assert np.max(np.abs(ours - ref)) < 2e-3


def test_greedy_decode_matches_transformers_generate():
    """Greedy decode through OUR KV-cache prefill+step loop produces
    the same continuation transformers.generate does — pins the cache
    write indices, rotary offsets, and last-position logit selection of
    the serving path, not just the training forward."""
    from ray_tpu.models.generate import generate

    model = _tiny_hf_llama(n_heads=4, n_kv_heads=4, seed=5)
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, 128, (2, 12), dtype=np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt),
            max_new_tokens=10,
            do_sample=False,
            pad_token_id=0,
            # Ours runs the full budget (eos_token=-1 default); HF
            # must not stop early at its default eos_token_id=2, or a
            # lucky token-2 emission zero-pads only one side.
            eos_token_id=None,
        )[:, prompt.shape[1]:].numpy()
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    ours, lengths = generate(
        params,
        jax.numpy.asarray(prompt),
        jax.numpy.asarray(np.full(2, prompt.shape[1], np.int32)),
        cfg,
        max_new_tokens=10,
        temperature=0.0,
    )
    assert np.asarray(ours).tolist() == ref.tolist()


def test_llama31_rope_scaling_parity():
    """Llama-3.1 'llama3' rope_scaling converts and matches HF's
    piecewise frequency scaling bit-for-bit at the logit level
    (VERDICT r4 weak #5: every Llama-3.1+ checkpoint used to be
    rejected by the NotImplementedError guard)."""
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(5)
    hf_cfg = HFConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=500000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = LlamaForCausalLM(hf_cfg)
    model.eval()
    cfg = config_from_hf(model.config)
    assert cfg.rope_scaling == ("llama3", 8.0, 1.0, 4.0, 32)
    rng = np.random.default_rng(5)
    # Positions beyond original_max exercise the scaled-frequency band.
    tokens = rng.integers(0, 128, (1, 80), dtype=np.int64)
    _compare(model, tokens)


def test_linear_rope_scaling_parity():
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(6)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=128,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        tie_word_embeddings=False, attn_implementation="eager",
    )
    model = LlamaForCausalLM(hf_cfg)
    model.eval()
    rng = np.random.default_rng(6)
    tokens = rng.integers(0, 128, (1, 64), dtype=np.int64)
    _compare(model, tokens)


@pytest.mark.slow
def test_parity_at_depth_gqa_bf16():
    """Parity at realistic depth/width in bf16 (VERDICT r4 weak #5:
    tiny 2-layer configs never exercised the regime where 'subtly
    wrong logits' live): 24 layers, hidden 1024, GQA 16q/4kv heads,
    real Llama-3 rope theta, bf16 weights and activations on BOTH
    sides. Asserts bounded logit divergence (bf16 accumulation noise
    only) and token-identical greedy continuation at every position."""
    import jax.numpy as jnp

    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(7)
    hf_cfg = HFConfig(
        vocab_size=2048,
        hidden_size=1024,
        intermediate_size=2816,
        num_hidden_layers=24,
        num_attention_heads=16,
        num_key_value_heads=4,
        max_position_embeddings=256,
        rope_theta=500000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = LlamaForCausalLM(hf_cfg)
    model.eval()
    model = model.to(torch.bfloat16)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 2048, (1, 96), dtype=np.int64)

    with torch.no_grad():
        ref = (
            model(torch.from_numpy(tokens))
            .logits.to(torch.float32)
            .numpy()
        )
    cfg = config_from_hf(model.config)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": jnp.bfloat16})
    params = convert_hf_llama(model.state_dict(), cfg)
    ours = np.asarray(
        forward(params, jax.numpy.asarray(tokens), cfg),
        dtype=np.float32,
    )
    diff = np.max(np.abs(ours - ref))
    # bf16 noise across 24 layers; measured headroom documented in the
    # assert so a regression is visible as a number, not just a fail.
    assert diff < 0.5, f"bf16 depth-parity drifted: max abs diff {diff}"
    # Greedy continuation: token-identical wherever the decision is
    # numerically decidable. Random-init logits sit near zero, so a
    # handful of positions have top-2 margins inside bf16 noise —
    # those flip on EITHER side's summation order (trained checkpoints
    # have wide margins); requiring them equal would test tie-breaking,
    # not correctness. Decidable = ref top-2 margin > 2x the measured
    # logit divergence.
    top2 = np.partition(ref, -2, axis=-1)
    margin = top2[..., -1] - top2[..., -2]
    decidable = margin > 2 * diff
    agree = ours.argmax(-1) == ref.argmax(-1)
    # Random-init logits cluster near zero, so only ~60% of positions
    # have decisive margins (trained checkpoints: nearly all).
    assert decidable.mean() > 0.4, (
        "test lost its power: almost every position is a near-tie"
    )
    assert agree[decidable].all(), (
        "greedy continuation diverged at decidable positions: "
        f"{(~agree & decidable).sum()} of {decidable.sum()}"
    )


def _tiny_hf_mistral(n_heads=4, n_kv_heads=2, seed=0,
                     sliding_window=None):
    """Mistral: third HF architecture — Llama skeleton, no biases,
    GQA by default; converts only with the sliding window disabled
    (how v0.3+ checkpoints ship)."""
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(seed)
    hf_cfg = MistralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
        sliding_window=sliding_window,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = MistralForCausalLM(hf_cfg)
    model.eval()
    return model


def test_mistral_logits_match_transformers_gqa():
    model = _tiny_hf_mistral(n_heads=4, n_kv_heads=2, seed=11)
    cfg = config_from_hf(model.config)
    assert not cfg.attn_bias  # mistral carries no projection biases
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 128, (2, 33), dtype=np.int64)
    _compare(model, tokens)


def test_mistral_active_sliding_window_rejected():
    """v0.1-style checkpoints (sliding_window=4096) must fail loudly:
    converting would silently drop the window and change long-context
    numerics."""
    model = _tiny_hf_mistral(sliding_window=32)
    with pytest.raises(NotImplementedError, match="sliding-window"):
        config_from_hf(model.config)


def _tiny_hf_gemma(n_heads=4, n_kv_heads=1, head_dim=32, seed=0):
    """Gemma: fourth HF architecture — GeGLU gate, (1+w) RMSNorm,
    sqrt(dim) embedding scale, head_dim decoupled from dim/n_heads,
    always-tied lm_head. The tiny config uses head_dim != dim/n_heads
    on purpose (Gemma-2B ships 8 heads x 256 on dim 2048)."""
    from transformers import GemmaConfig, GemmaForCausalLM

    torch.manual_seed(seed)
    hf_cfg = GemmaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        head_dim=head_dim,
        max_position_embeddings=64,
        rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    model = GemmaForCausalLM(hf_cfg)
    model.eval()
    return model


def test_gemma_logits_match_transformers():
    model = _tiny_hf_gemma(seed=13)
    cfg = config_from_hf(model.config)
    assert cfg.custom_head_dim == 32  # decoupled: 4 heads x 32 on dim 64
    assert cfg.act == "gelu_tanh" and cfg.norm_offset and cfg.embed_scale
    rng = np.random.default_rng(13)
    tokens = rng.integers(0, 128, (2, 33), dtype=np.int64)
    _compare(model, tokens, atol=5e-4)


def test_gemma_greedy_decode_matches_transformers_generate():
    """The KV-cache serving layer applies the Gemma conventions too
    (shared model_norm/model_glu/embed_tokens helpers)."""
    from ray_tpu.models.generate import generate

    model = _tiny_hf_gemma(seed=14)
    rng = np.random.default_rng(14)
    prompt = rng.integers(1, 128, (2, 9), dtype=np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt),
            max_new_tokens=10,
            do_sample=False,
            pad_token_id=0,
            eos_token_id=None,
        )[:, prompt.shape[1]:].numpy()
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    ours, _lengths = generate(
        params,
        jax.numpy.asarray(prompt),
        jax.numpy.asarray(np.full(2, prompt.shape[1], np.int32)),
        cfg,
        max_new_tokens=10,
        temperature=0.0,
    )
    assert np.asarray(ours).tolist() == ref.tolist()


def _tiny_hf_phi3(n_heads=4, n_kv_heads=2, seed=0):
    """Phi-3: fifth HF architecture — Llama skeleton with FUSED
    qkv_proj and gate_up_proj projections the converter must split."""
    from transformers import Phi3Config, Phi3ForCausalLM

    torch.manual_seed(seed)
    hf_cfg = Phi3Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        pad_token_id=0,
        eos_token_id=1,
        bos_token_id=2,
        attn_implementation="eager",
    )
    model = Phi3ForCausalLM(hf_cfg)
    model.eval()
    return model


def test_phi3_logits_match_transformers():
    model = _tiny_hf_phi3(seed=17)
    cfg = config_from_hf(model.config)
    assert not cfg.attn_bias and cfg.act == "silu"
    rng = np.random.default_rng(17)
    tokens = rng.integers(0, 128, (2, 33), dtype=np.int64)
    _compare(model, tokens)


def test_phi3_greedy_decode_matches_transformers_generate():
    """The split fused projections feed the KV-cache serving path
    identically."""
    from ray_tpu.models.generate import generate

    model = _tiny_hf_phi3(seed=18)
    rng = np.random.default_rng(18)
    prompt = rng.integers(3, 128, (2, 11), dtype=np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt),
            max_new_tokens=10,
            do_sample=False,
            pad_token_id=0,
            eos_token_id=None,
        )[:, prompt.shape[1]:].numpy()
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    ours, _lengths = generate(
        params,
        jax.numpy.asarray(prompt),
        jax.numpy.asarray(np.full(2, prompt.shape[1], np.int32)),
        cfg,
        max_new_tokens=10,
        temperature=0.0,
    )
    assert np.asarray(ours).tolist() == ref.tolist()


def _tiny_hf_qwen3(n_heads=4, n_kv_heads=2, head_dim=16, seed=0):
    """Qwen3: sixth HF architecture — Llama skeleton plus per-head
    RMSNorm on q and k before RoPE (q_norm/k_norm), no biases, and a
    decoupled head_dim."""
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(seed)
    hf_cfg = Qwen3Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        head_dim=head_dim,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = Qwen3ForCausalLM(hf_cfg)
    model.eval()
    return model


def test_qwen3_logits_match_transformers():
    # head_dim=32 with 4 heads on dim 64: genuinely decoupled
    # (4 x 32 != 64), like real Qwen3 checkpoints.
    model = _tiny_hf_qwen3(head_dim=32, seed=21)
    cfg = config_from_hf(model.config)
    assert cfg.qk_norm and not cfg.attn_bias
    assert cfg.custom_head_dim == 32
    rng = np.random.default_rng(21)
    tokens = rng.integers(0, 128, (2, 33), dtype=np.int64)
    _compare(model, tokens)


def test_qwen3_greedy_decode_matches_transformers_generate():
    """QK-norm applies identically on the KV-cache serving path
    (shared project_qkv)."""
    from ray_tpu.models.generate import generate

    model = _tiny_hf_qwen3(seed=22)
    rng = np.random.default_rng(22)
    prompt = rng.integers(1, 128, (2, 9), dtype=np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt),
            max_new_tokens=10,
            do_sample=False,
            pad_token_id=0,
            eos_token_id=None,
        )[:, prompt.shape[1]:].numpy()
    cfg = config_from_hf(model.config)
    params = convert_hf_llama(model.state_dict(), cfg)
    ours, _lengths = generate(
        params,
        jax.numpy.asarray(prompt),
        jax.numpy.asarray(np.full(2, prompt.shape[1], np.int32)),
        cfg,
        max_new_tokens=10,
        temperature=0.0,
    )
    assert np.asarray(ours).tolist() == ref.tolist()
