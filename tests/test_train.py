"""Training-stack tests: sharded train step (dp+fsdp+tp on the virtual
mesh), JaxTrainer fit, sessions, checkpointing, worker gangs."""

import os
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    param_annotations,
)
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.train import (
    CheckpointManager,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
    default_optimizer,
    make_train_step,
    report,
    restore_checkpoint,
    save_checkpoint,
    shard_batch,
)


def _tiny_cfg():
    return LlamaConfig.tiny()


def _mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return MeshSpec(dp=2, fsdp=2, tp=2).build()


class TestTrainStep:
    def test_loss_decreases_sharded(self):
        mesh = _mesh()
        cfg = _tiny_cfg()
        opt = default_optimizer(learning_rate=1e-2, total_steps=50)
        init_fn, step_fn = make_train_step(
            lambda p, t, y: loss_fn(p, t, y, cfg),
            opt,
            mesh,
            param_annotations(cfg),
        )
        state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg))
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size
        )
        toks = shard_batch(toks, mesh, logical_axes=("batch", None))
        inp, tgt = toks[:, :-1], toks[:, 1:]
        first = None
        for _ in range(10):
            state, metrics = step_fn(state, inp, tgt)
            if first is None:
                first = float(metrics["loss"])
        last = float(metrics["loss"])
        assert last < first, (first, last)
        assert int(state.step) == 10

    def test_params_are_sharded(self):
        mesh = _mesh()
        cfg = _tiny_cfg()
        opt = default_optimizer(total_steps=10)
        init_fn, _ = make_train_step(
            lambda p, t, y: loss_fn(p, t, y, cfg),
            opt,
            mesh,
            param_annotations(cfg),
        )
        state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg))
        # w1 [L, embed(dim), mlp] must be sharded over fsdp and tp.
        spec = state.params["layers"]["w1"].sharding.spec
        assert tuple(spec) == (None, "fsdp", "tp")
        # Optimizer state inherits the same layout (ZeRO-3 analog).
        adam_mu = jax.tree.leaves(state.opt_state)
        assert any(
            getattr(leaf, "sharding", None) is not None
            and leaf.sharding.spec == state.params["layers"]["w1"].sharding.spec
            for leaf in adam_mu
            if hasattr(leaf, "shape")
            and leaf.shape == state.params["layers"]["w1"].shape
        )

    def test_sp_ring_attention_training(self):
        """Sequence parallelism end-to-end: loss under ring attention
        on an sp-sharded mesh matches the reference-attention loss."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from functools import partial

        from jax import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = MeshSpec(sp=4).build(jax.devices()[:4])
        cfg_ring = LlamaConfig.tiny(attention="ring")
        cfg_ref = LlamaConfig.tiny(attention="reference")
        params = init_params(jax.random.PRNGKey(0), cfg_ref)
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg_ref.vocab_size
        )
        inp, tgt = toks[:, :-1], toks[:, 1:]  # seq 63... need divisible
        inp, tgt = toks[:, :64][:, :-4], toks[:, 1:61]  # len 60 -> /4
        ref_loss = float(loss_fn(params, inp, tgt, cfg_ref))

        def sp_loss(params, inp, tgt):
            b, t = inp.shape
            positions = jnp.broadcast_to(jnp.arange(t), (b, t))

            def local(params, inp, tgt, positions):
                return loss_fn(
                    params, inp, tgt, cfg_ring,
                    positions=positions, sp_axis="sp",
                )[None]

            losses = shard_map(
                local,
                mesh=mesh,
                in_specs=(P(), P(None, "sp"), P(None, "sp"), P(None, "sp")),
                out_specs=P("sp"),
                check_vma=False,
            )(params, inp, tgt, positions)
            # Each shard's mean is over its local tokens; all tokens
            # unmasked and shards equal-sized, so the mean of means is
            # the global mean.
            return jnp.mean(losses)

        ring_loss = float(sp_loss(params, inp, tgt))
        np.testing.assert_allclose(ring_loss, ref_loss, rtol=2e-4)


class TestJaxTrainer:
    def test_fit_local_reports(self):
        cfg = _tiny_cfg()

        def train_loop(config):
            mesh = MeshSpec(fsdp=1).build(jax.devices()[:1])
            opt = default_optimizer(learning_rate=1e-2, total_steps=20)
            init_fn, step_fn = make_train_step(
                lambda p, t, y: loss_fn(p, t, y, cfg),
                opt, mesh, param_annotations(cfg),
            )
            state = init_fn(
                jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
            )
            toks = jax.random.randint(
                jax.random.PRNGKey(1), (2, 33), 0, cfg.vocab_size
            )
            for step in range(config["steps"]):
                state, metrics = step_fn(state, toks[:, :-1], toks[:, 1:])
                report({"loss": float(metrics["loss"]), "step": step})

        trainer = JaxTrainer(
            train_loop,
            train_loop_config={"steps": 3},
            scaling_config=ScalingConfig(num_workers=1),
        )
        result = trainer.fit()
        assert isinstance(result, Result)
        assert result.error is None
        assert len(result.metrics_history) == 3
        assert result.metrics["step"] == 2

    def test_fit_failure_captured(self):
        def bad_loop():
            raise RuntimeError("train loop exploded")

        trainer = JaxTrainer(bad_loop)
        result = trainer.fit()
        assert result.error is not None
        assert "exploded" in str(result.error)

    def test_fit_retry_resumes_from_checkpoint(self, tmp_path):
        """A retried attempt must restore from the previous attempt's
        latest checkpoint, not restart from scratch (reference:
        backend_executor._restart:759)."""
        from ray_tpu.train import FailureConfig, get_checkpoint

        marker = tmp_path / "attempts"
        marker.write_text("0")

        def loop():
            attempt = int(marker.read_text())
            marker.write_text(str(attempt + 1))
            ckpt = get_checkpoint()
            start = 0
            if ckpt is not None:
                start = int(
                    (pathlib.Path(ckpt) / "step").read_text()
                )
            assert not (attempt > 0 and start == 0), (
                "retry did not see the previous attempt's checkpoint"
            )
            for step in range(start, 5):
                d = tmp_path / f"ck{step}"
                d.mkdir(exist_ok=True)
                (d / "step").write_text(str(step + 1))
                report({"step": step}, checkpoint=str(d))
                if step == 2 and attempt == 0:
                    raise RuntimeError("boom at step 2")

        trainer = JaxTrainer(
            loop,
            run_config=RunConfig(
                storage_path=str(tmp_path / "storage"),
                failure_config=FailureConfig(max_failures=1),
            ),
        )
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["step"] == 4
        # Second attempt resumed at step 3 → reported only steps 3, 4.
        assert [m["step"] for m in result.metrics_history] == [3, 4]


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        state = {
            "w": jnp.arange(16.0).reshape(4, 4),
            "step": jnp.int32(7),
        }
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state, {"note": "test"})
        restored = restore_checkpoint(
            path, jax.tree.map(jnp.zeros_like, state)
        )
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(state["w"])
        )
        assert int(restored["step"]) == 7

    def test_manager_retention(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), num_to_keep=2)
        for step in [1, 2, 3]:
            mgr.save(step, {"x": jnp.float32(step)})
        dirs = sorted(os.listdir(tmp_path))
        assert dirs == ["checkpoint_00000002", "checkpoint_00000003"]
        assert mgr.latest().endswith("checkpoint_00000003")


class TestAsyncCheckpoint:
    def test_async_save_restore_roundtrip(self, tmp_path):
        from ray_tpu.train import load_metadata

        state = {
            "w": jnp.arange(16.0).reshape(4, 4),
            "step": jnp.int32(7),
        }
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state, {"note": "async"}, async_save=True)
        # restore_checkpoint waits for the in-flight write internally.
        restored = restore_checkpoint(
            path, jax.tree.map(jnp.zeros_like, state)
        )
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(state["w"])
        )
        assert int(restored["step"]) == 7
        assert load_metadata(path)["note"] == "async"

    def test_step_n_plus_1_runs_while_save_n_persists(
        self, tmp_path, monkeypatch
    ):
        """The overlap proof: gate the background write on an event,
        run (and finish) training compute while the writer is
        provably still inside the save, then release it and assert
        the barrier delivers a durable checkpoint."""
        import threading
        import time

        from ray_tpu.train import checkpoint as ck

        write_started = threading.Event()
        release_write = threading.Event()
        real_write = ck._write_payload

        def gated_write(path, state, metadata):
            write_started.set()
            assert release_write.wait(timeout=30), "writer never released"
            real_write(path, state, metadata)

        monkeypatch.setattr(ck, "_write_payload", gated_write)

        state = {"w": jnp.arange(64.0)}
        path = str(tmp_path / "ck0")
        t0 = time.perf_counter()
        save_checkpoint(state=state, path=path, metadata={"step": 0},
                        async_save=True)
        # save N returned without waiting on the (gated) disk write.
        assert time.perf_counter() - t0 < 5.0
        assert write_started.wait(timeout=10)

        # Step N+1: real jitted compute, completed to a host value
        # while the save is still persisting.
        step = jax.jit(lambda x: jnp.sum(x * x))
        result = float(step(jnp.arange(1000.0)))
        assert result > 0
        assert ck.pending_checkpoints() == [path], (
            "save must still be in flight when step N+1 retires"
        )

        release_write.set()
        ck.wait_for_checkpoints()
        assert ck.pending_checkpoints() == []
        assert (tmp_path / "ck0" / "metadata.json").exists()

    def test_fit_exit_barrier_makes_final_checkpoint_durable(
        self, tmp_path, monkeypatch
    ):
        """fit() must not return while an async save is still in
        flight: the loop issues a slow async save as its final act,
        and the checkpoint must be fully on disk (metadata.json is
        written last) the moment fit() hands back."""
        import time

        from ray_tpu.train import checkpoint as ck

        real_write = ck._write_payload

        def slow_write(path, state, metadata):
            time.sleep(0.8)
            real_write(path, state, metadata)

        monkeypatch.setattr(ck, "_write_payload", slow_write)
        ckpt_dir = str(tmp_path / "final_ck")

        def loop():
            save_checkpoint(
                ckpt_dir,
                {"w": jnp.ones(8)},
                {"step": 1},
                async_save=True,
            )
            report({"step": 1}, checkpoint=ckpt_dir)

        result = JaxTrainer(loop).fit()
        assert result.error is None
        assert result.checkpoint_path == ckpt_dir
        assert ck.pending_checkpoints() == []
        assert os.path.exists(os.path.join(ckpt_dir, "metadata.json"))

    def test_write_error_surfaces_at_barrier(self, tmp_path, monkeypatch):
        from ray_tpu.train import checkpoint as ck

        def boom(path, state, metadata):
            raise RuntimeError("disk full")

        monkeypatch.setattr(ck, "_write_payload", boom)
        save_checkpoint(
            str(tmp_path / "x"), {"w": jnp.ones(2)}, async_save=True
        )
        with pytest.raises(RuntimeError, match="disk full"):
            ck.wait_for_checkpoints()
        assert ck.pending_checkpoints() == []

    def test_manager_async_retention(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), num_to_keep=2)
        for step in [1, 2, 3]:
            mgr.save(step, {"x": jnp.float32(step)}, async_save=True)
        mgr.wait()
        dirs = sorted(
            d
            for d in os.listdir(tmp_path)
            if d.startswith("checkpoint_")
        )
        assert dirs == ["checkpoint_00000002", "checkpoint_00000003"]
        assert mgr.latest().endswith("checkpoint_00000003")


@pytest.mark.slow
def test_ckpt_every_10_steps_overhead_under_5pct():
    """Regression: async checkpointing every 10 steps on the fake
    (CPU) backend must cost <5% wall time vs no checkpointing. Runs
    `bench.py --mode ckpt` in a subprocess with a clean JAX config
    (the pytest process forces 8 host devices, which makes the CPU
    SPMD step pathologically slow and measures nothing real). One
    retry absorbs a burst of box contention; a real regression (e.g.
    a save sneaking back onto the critical path) fails both runs."""
    import json
    import subprocess
    import sys

    repo = os.path.join(os.path.dirname(__file__), "..")

    def run_once() -> dict:
        env = {
            k: v for k, v in os.environ.items() if k != "XLA_FLAGS"
        }
        env["JAX_PLATFORMS"] = "cpu"
        env["RT_BENCH_CKPT_STEPS"] = "30"
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--mode", "ckpt"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=repo,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    out = run_once()
    if out["ckpt_overhead_pct"] >= 5.0:
        out = run_once()
    assert out["every"] == 10
    assert out["ckpt_overhead_pct"] < 5.0, out


class TestDeviceBatchPrefetch:
    def test_prefetch_to_device_order_and_residency(self):
        from ray_tpu.train import prefetch_to_device

        mesh = MeshSpec(fsdp=1).build(jax.devices()[:1])
        host = [
            {"id": np.full((4,), i, dtype=np.int32)} for i in range(7)
        ]
        out = list(
            prefetch_to_device(
                iter(host), mesh, buffer_size=2, logical_axes=("batch",)
            )
        )
        assert len(out) == 7
        for i, batch in enumerate(out):
            assert isinstance(batch["id"], jax.Array)  # on device
            np.testing.assert_array_equal(
                np.asarray(batch["id"]), np.full((4,), i)
            )

    def test_trainer_device_batches_end_to_end(self):
        """datasets= -> get_device_batches: the whole overlapped input
        path (host prefetch thread + device double buffer) feeds a
        train loop and covers every row exactly once."""
        from ray_tpu import data
        from ray_tpu.train import get_device_batches

        import ray_tpu as rt

        rt.init(num_cpus=4, ignore_reinit_error=True)
        try:
            ds = data.range(96, parallelism=4)

            def loop(config):
                mesh = MeshSpec(fsdp=1).build(jax.devices()[:1])
                total, count = 0, 0
                for batch in get_device_batches(
                    "train",
                    mesh=mesh,
                    batch_size=32,
                    prefetch_batches=2,
                    buffer_size=2,
                ):
                    assert isinstance(batch["id"], jax.Array)
                    total += int(jnp.sum(batch["id"]))
                    count += int(batch["id"].shape[0])
                report({"total": total, "count": count})

            result = JaxTrainer(
                loop, train_loop_config={}, datasets={"train": ds}
            ).fit()
            assert result.error is None
            assert result.metrics["count"] == 96
            assert result.metrics["total"] == sum(range(96))
        finally:
            rt.shutdown()


class TestWorkerGroup:
    def test_gang_ranks(self):
        import ray_tpu as rt

        rt.init(num_cpus=4, ignore_reinit_error=True)
        try:
            from ray_tpu.train.worker_group import WorkerGroup

            group = WorkerGroup(num_workers=2)

            def whoami(tag):
                return tag

            outs = group.run_per_rank(
                whoami, lambda rank: (f"worker-{rank}",)
            )
            assert outs == ["worker-0", "worker-1"]

            def loop():
                from ray_tpu.train.session import get_context, report

                context = get_context()
                report({"rank": context.world_rank})
                return context.world_size

            results = group.run_train_loop(loop)
            assert [r["result"] for r in results] == [2, 2]
            assert results[0]["reported"] == [{"rank": 0}]
            assert results[1]["reported"] == [{"rank": 1}]
            group.shutdown()
        finally:
            rt.shutdown()


class TestMultiSlice:
    def test_two_slice_gang_hybrid_mesh_matches_single_slice(self):
        """VERDICT r3 item 2: a 2-worker gang (distinct processes,
        REAL jax.distributed rendezvous over a coordinator) where each
        worker models one 4-device slice. The flagship train step runs
        over the hybrid mesh (outer dcn_dp=2 over DCN, fsdp=4 inside
        each slice) and its losses must match the single-process flat
        fsdp=8 mesh — cross-slice pure-dp is mathematically invisible
        (reference analog: dp over the multi-node NCCL world,
        train/torch/config.py:66-116)."""
        import socket

        import ray_tpu as rt

        rt.init(num_cpus=4, ignore_reinit_error=True)
        try:
            from ray_tpu.train.backend import JaxBackend
            from ray_tpu.train.worker_group import WorkerGroup

            group = WorkerGroup(num_workers=2)

            # Stage 1 (before any jax import in the workers): each
            # worker becomes a virtual 4-device "slice".
            def setup_env():
                import os

                os.environ["XLA_FLAGS"] = (
                    "--xla_force_host_platform_device_count=4"
                )
                os.environ["JAX_PLATFORMS"] = "cpu"
                return os.getpid()

            pids = group.run_all(setup_env)
            assert pids[0] != pids[1], "gang must span processes"

            # Stage 2: one jax.distributed world across both slices.
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            JaxBackend().on_start(
                group,
                {
                    "coordinator_address": f"127.0.0.1:{port}",
                    "slices": 2,
                },
            )

            def train_two_steps():
                import os

                import jax

                from ray_tpu.models.llama import (
                    LlamaConfig,
                    init_params,
                    loss_fn,
                    param_annotations,
                )
                from ray_tpu.parallel.mesh import MeshSpec
                from ray_tpu.train.train_step import (
                    default_optimizer,
                    make_train_step,
                    shard_batch,
                )

                assert jax.device_count() == 8
                assert os.environ["RT_SLICE_ID"] in ("0", "1")
                cfg = LlamaConfig.tiny()
                mesh = MeshSpec(dcn_dp=2, fsdp=4).build()
                init_fn, step_fn = make_train_step(
                    lambda p, t, y: loss_fn(p, t, y, cfg),
                    default_optimizer(learning_rate=1e-2, total_steps=50),
                    mesh,
                    param_annotations(cfg),
                )
                state = init_fn(
                    jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
                )
                toks = jax.random.randint(
                    jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size
                )
                toks = shard_batch(
                    toks, mesh, logical_axes=("batch", None)
                )
                losses = []
                for _ in range(2):
                    state, metrics = step_fn(
                        state, toks[:, :-1], toks[:, 1:]
                    )
                    losses.append(float(metrics["loss"]))
                return losses

            gang_losses = group.run_all(train_two_steps)
            assert gang_losses[0] == pytest.approx(gang_losses[1])
            group.shutdown()

            # Single-process flat fsdp=8 reference on this process's
            # own 8 virtual devices: same seeds -> same math.
            cfg = _tiny_cfg()
            mesh = MeshSpec(fsdp=8).build()
            init_fn, step_fn = make_train_step(
                lambda p, t, y: loss_fn(p, t, y, cfg),
                default_optimizer(learning_rate=1e-2, total_steps=50),
                mesh,
                param_annotations(cfg),
            )
            state = init_fn(
                jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
            )
            toks = jax.random.randint(
                jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size
            )
            toks = shard_batch(toks, mesh, logical_axes=("batch", None))
            flat_losses = []
            for _ in range(2):
                state, metrics = step_fn(state, toks[:, :-1], toks[:, 1:])
                flat_losses.append(float(metrics["loss"]))
            assert gang_losses[0] == pytest.approx(
                flat_losses, abs=2e-3
            ), f"hybrid {gang_losses[0]} vs flat {flat_losses}"
        finally:
            rt.shutdown()
