"""Sanitizer passes over the native arena store (SURVEY §5.2 — the
reference ships ASAN/UBSAN/TSAN build modes and sanitizer CI for its
C++ core; here the C++ surface is store.cc):

* ASan + UBSan: the Python binding's full API sweep runs in a
  subprocess with the sanitizer runtime preloaded (memory errors,
  UB).
* TSan (slow-marked — the instrumented build+run costs real time):
  a standalone instrumented binary (_native/tsan_exerciser.cc)
  hammers one arena from many threads and forked processes —
  concurrent create/seal/pin/evict/delete against the process-shared
  robust mutex. TSan cannot be preloaded into an uninstrumented
  python, hence the dedicated main(). Skips cleanly where the
  toolchain lacks -fsanitize=thread.
"""

import os
import subprocess
import sys

import pytest

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "ray_tpu", "_native",
)
# Keyed to this checkout so parallel worktrees/users never share (or
# fight over) one binary.
import hashlib as _hashlib

_SAN_SO = "/tmp/rt_store_sanitized_{}.so".format(
    _hashlib.sha1(_NATIVE_DIR.encode()).hexdigest()[:10]
)

# The exercise runs in a subprocess (the sanitizer runtime must be
# preloaded before python starts) and sweeps the arena API: create /
# seal / pin / read / delete, LRU eviction under pressure, delete-vs-
# pin deferral, crash-reaping of a dead child's pins, reopen.
_EXERCISE = r"""
import ctypes, os, sys
sys.path.insert(0, %(repo)r)
from ray_tpu._native import NativeArena

path = "/dev/shm/rt_asan_test_%%d" %% os.getpid()
arena = NativeArena(path, 1 << 20, create=True)  # 1 MiB
oid = lambda i: bytes([i %% 256]) * 20

# fill beyond capacity -> LRU eviction
for i in range(40):
    view, evicted = arena.create(oid(i), 40_000)
    view[:5] = b"hello"
    arena.seal(oid(i))
assert arena.stats()["used"] <= arena.stats()["capacity"]

# pinned reads survive delete (deferred free) and release cleanly
pin = arena.try_pin(oid(39))
assert pin is not None
index, view = pin
assert bytes(view[:5]) == b"hello"
arena.delete(oid(39))
assert bytes(view[:5]) == b"hello"  # still mapped while pinned
arena.unpin_idx(index)

# a child process pins and dies without releasing; the parent reaps
child = os.fork()
if child == 0:
    a2 = NativeArena(path, 1 << 20, create=False)
    a2.try_pin(oid(38))
    os._exit(0)  # dies holding the pin
os.waitpid(child, 0)
reaped = arena.reap_dead_pins()
assert reaped >= 1, reaped

# delete/recreate same oid (ABA) and reopen the arena
arena.delete(oid(38))
v, _ = arena.create(oid(38), 128)
v[:3] = b"new"
arena.seal(oid(38))
arena.close(unlink=False)
arena = NativeArena(path, 1 << 20, create=False)
p = arena.try_pin(oid(38))
assert p is not None and bytes(p[1][:3]) == b"new"
arena.unpin_idx(p[0])
arena.close(unlink=True)
# SPSC ring channel ops (rts_chan_put/get): wrap-around boundaries,
# odd record sizes, cross-process ping-pong, close-while-blocked.
from ray_tpu.dag.channels import (
    ShmChannel, ChannelClosedError, ChannelTimeoutError, _CHAN_NATIVE,
)
assert _CHAN_NATIVE is not None  # sanitized .so must expose the ops

chan = ShmChannel(4096)
for size in (0, 1, 7, 8, 9, 1000, 4000):  # 4000+8 < 4096: fits alone
    payload = bytes(size %% 256 for _ in range(size))
    chan.put_bytes(payload, timeout=5)
    assert chan.get_bytes(timeout=5) == payload
# force many wrap-arounds with back-to-back odd-sized records
for i in range(200):
    chan.put_bytes(b"x" * (i %% 517), timeout=5)
    assert len(chan.get_bytes(timeout=5)) == i %% 517
try:
    chan.put_bytes(b"y" * 5000, timeout=1)
    raise AssertionError("oversized record accepted")
except ValueError:
    pass
try:
    chan.get_bytes(timeout=0.05)
    raise AssertionError("empty get returned")
except ChannelTimeoutError:
    pass

# cross-process ping-pong + remote close observed by a blocked reader
pong = ShmChannel(4096)
child = os.fork()
if child == 0:
    for _ in range(300):
        pong.put_bytes(chan.get_bytes(timeout=10), timeout=10)
    chan.close()  # shared flag: parent's next get must raise
    os._exit(0)
for i in range(300):
    chan.put_bytes(b"p" * (i %% 97), timeout=10)
    assert len(pong.get_bytes(timeout=10)) == i %% 97
os.waitpid(child, 0)
try:
    chan.put_bytes(b"z", timeout=5)
    raise AssertionError("put on closed channel succeeded")
except ChannelClosedError:
    pass
pong.close(); pong.unlink()
chan.unlink()

print("SANITIZED-SWEEP-OK")
"""


@pytest.fixture(scope="module")
def sanitized_so():
    src = os.path.join(_NATIVE_DIR, "store.cc")
    if (
        not os.path.exists(_SAN_SO)
        or os.path.getmtime(_SAN_SO) < os.path.getmtime(src)
    ):
        build = subprocess.run(
            [
                "g++", "-O1", "-g", "-fPIC", "-std=c++17", "-shared",
                "-fsanitize=address,undefined",
                "-fno-sanitize-recover=all",
                src, "-o", _SAN_SO, "-lpthread",
            ],
            capture_output=True, text=True, timeout=180,
        )
        assert build.returncode == 0, build.stderr[-2000:]
    return _SAN_SO


def _libasan() -> str:
    out = subprocess.run(
        ["g++", "-print-file-name=libasan.so"],
        capture_output=True, text=True,
    ).stdout.strip()
    if not out or not os.path.exists(out):
        pytest.skip("libasan runtime not found")
    return out


_TSAN_EXE = "/tmp/rt_store_tsan_{}".format(
    _hashlib.sha1(_NATIVE_DIR.encode()).hexdigest()[:10]
)


@pytest.fixture(scope="module")
def tsan_exe():
    """Build the instrumented exerciser once per checkout; skip when
    the toolchain can't produce -fsanitize=thread binaries."""
    store = os.path.join(_NATIVE_DIR, "store.cc")
    exerciser = os.path.join(_NATIVE_DIR, "tsan_exerciser.cc")
    newest_src = max(os.path.getmtime(store), os.path.getmtime(exerciser))
    if (
        not os.path.exists(_TSAN_EXE)
        or os.path.getmtime(_TSAN_EXE) < newest_src
    ):
        try:
            build = subprocess.run(
                [
                    "g++", "-O1", "-g", "-std=c++17",
                    "-fsanitize=thread",
                    store, exerciser, "-o", _TSAN_EXE, "-lpthread",
                ],
                capture_output=True, text=True, timeout=180,
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            pytest.skip(f"cannot build TSan exerciser: {e}")
        if build.returncode != 0:
            pytest.skip(
                "toolchain lacks -fsanitize=thread: "
                + build.stderr[-500:]
            )
    return _TSAN_EXE


@pytest.mark.slow
def test_store_concurrency_under_tsan(tsan_exe, tmp_path):
    """Concurrent create/seal/pin/evict/delete from 3 processes x 6
    threads against ONE arena must be race-clean: any report from the
    instrumented build (data race, mutex misuse, deadlock) fails the
    run (halt_on_error) and the exit code."""
    arena = str(tmp_path / "tsan_arena")
    proc = subprocess.run(
        [tsan_exe, arena, "6", "4000", "2"],
        capture_output=True, text=True, timeout=420,
        env=dict(
            os.environ,
            TSAN_OPTIONS="halt_on_error=1 second_deadlock_stack=1",
        ),
    )
    output = proc.stdout + proc.stderr
    assert proc.returncode == 0, output[-4000:]
    assert "TSAN-SWEEP-OK" in output, output[-4000:]
    assert "WARNING: ThreadSanitizer" not in output, output[-4000:]


def test_arena_sweep_under_asan_ubsan(sanitized_so):
    repo = os.path.dirname(_NATIVE_DIR.rstrip(os.sep))
    repo = os.path.dirname(repo)
    env = dict(
        os.environ,
        RT_NATIVE_SO=sanitized_so,
        LD_PRELOAD=_libasan(),
        # Python itself leaks at exit by design; the arena file is a
        # persistent resource. Halt on real errors only.
        ASAN_OPTIONS="detect_leaks=0,abort_on_error=1",
        UBSAN_OPTIONS="halt_on_error=1,print_stacktrace=1",
    )
    proc = subprocess.run(
        [sys.executable, "-c", _EXERCISE % {"repo": repo}],
        capture_output=True, text=True, timeout=300, env=env,
    )
    output = proc.stdout + proc.stderr
    assert proc.returncode == 0, output[-4000:]
    assert "SANITIZED-SWEEP-OK" in output, output[-4000:]
    for marker in ("AddressSanitizer", "runtime error", "SUMMARY:"):
        assert marker not in output, output[-4000:]
