"""Decoupled RL dataflow tests (ISSUE 13): rollout-queue gates,
versioned weight sync, the engine's policy batch path, drainless
weight pushes (token-exact in-flight streams), and chaos — a killed
env runner never stalls the queue, a dead engine fails fast with
EngineDead, never a hang."""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


# ---------------------------------------------------------------------
# rollout queue gates (pure bookkeeping, no cluster)
# ---------------------------------------------------------------------

def test_rollout_queue_capacity_backpressure():
    from ray_tpu.rl.rollout_queue import RolloutQueue

    q = RolloutQueue(capacity=2, max_weight_lag=4)
    meta = {"weight_version": 0, "env_steps": 8}
    assert q.put({"ref": ["a"]}, meta) == "ok"
    assert q.put({"ref": ["b"]}, meta) == "ok"
    assert q.put({"ref": ["c"]}, meta) == "full"  # learner behind
    assert q.depth() == 2
    got = q.get_batch(8)
    assert [f["item"]["ref"][0] for f in got] == ["a", "b"]  # FIFO
    assert q.put({"ref": ["c"]}, meta) == "ok"
    stats = q.stats()
    assert stats["rejected_full"] == 1
    assert stats["puts"] == 3
    assert stats["env_steps_in"] == 24


def test_rollout_queue_weight_lag_gates():
    """Both staleness gates: a put too far behind the learner version
    is refused ("throttle"), and a fragment that AGED while queued is
    dropped at get — stale data never trains."""
    from ray_tpu.rl.rollout_queue import RolloutQueue

    q = RolloutQueue(capacity=8, max_weight_lag=1)
    assert q.put({"ref": ["v0"]}, {"weight_version": 0}) == "ok"
    q.set_learner_version(2)
    # 2 - 0 > 1: the queued fragment is now stale; a NEW v0 put is
    # throttled at the door.
    assert q.put({"ref": ["v0b"]}, {"weight_version": 0}) == "throttle"
    assert q.put({"ref": ["v2"]}, {"weight_version": 2}) == "ok"
    got = q.get_batch(8)
    assert [f["item"]["ref"][0] for f in got] == ["v2"]
    stats = q.stats()
    assert stats["dropped_stale"] == 1
    assert stats["rejected_stale"] == 1
    # Learner version is monotonic: a late lower set is a no-op.
    assert q.set_learner_version(1) == 2


def test_weight_store_versioning():
    from ray_tpu.rl.weight_sync import WeightStore

    store = WeightStore()
    assert store.latest_version() == 0
    assert store.get() == (0, None)
    assert store.publish(["ref1"], 1) == 1
    assert store.publish(["stale"], 1) == 1  # late retry ignored
    assert store.publish(["ref2"], 3) == 3
    version, item = store.get()
    assert (version, item) == (3, ["ref2"])
    assert store.stats()["publishes"] == 2


# ---------------------------------------------------------------------
# engine policy path (in-process, no cluster)
# ---------------------------------------------------------------------

def _policy_engine(params, obs_size=4, **cfg_kw):
    from ray_tpu.llm.engine import EngineConfig, InferenceEngine
    from ray_tpu.rl.dataflow import PolicyProgram

    return InferenceEngine(
        params,
        None,
        EngineConfig(**cfg_kw),
        family="rl-test",
        program=PolicyProgram(obs_size),
    )


@pytest.fixture(scope="module")
def policy_params():
    from ray_tpu.rl.models import init_policy_params

    return init_policy_params(jax.random.PRNGKey(0), 4, 2)


def test_policy_requests_batch_into_one_forward(policy_params):
    """Ragged concurrent submits coalesce: N threads' rows come back
    row-exact (each ticket gets ITS slice) and the engine serves them
    in far fewer program steps than requests."""
    eng = _policy_engine(policy_params)
    try:
        results = {}

        def worker(i, rows):
            obs = np.full((rows, 4), float(i), np.float32)
            ticket = eng.submit_policy(obs)
            results[i] = (rows, ticket.result(timeout=30))

        threads = [
            threading.Thread(target=worker, args=(i, 1 + i % 3))
            for i in range(12)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 12
        for i, (rows, out) in results.items():
            assert out["actions"].shape == (rows,)
            assert out["logp"].shape == (rows,)
            assert out["values"].shape == (rows,)
            assert np.isfinite(out["logp"]).all()
        stats = eng.stats()
        assert stats["policy_rows_served"] == sum(
            1 + i % 3 for i in range(12)
        )
        assert stats["policy_steps"] < 12  # batching happened
    finally:
        eng.close()


def test_policy_reply_matches_local_program(policy_params):
    """Engine-served and runner-local inference run the SAME batch
    program: identical params + obs + key -> identical outputs (the
    two dataflow modes differ only in where the forward runs)."""
    from ray_tpu.rl.dataflow import PolicyProgram

    eng = _policy_engine(policy_params)
    try:
        obs = np.linspace(-1, 1, 8, dtype=np.float32).reshape(2, 4)
        ticket = eng.submit_policy(obs)
        out = ticket.result(timeout=30)
        assert ticket.version == 0
        # Deterministic heads must agree exactly; the sampled head
        # depends on the engine's key schedule, so compare the
        # deterministic ones.
        program = PolicyProgram(4)
        ref = program.run(
            policy_params, obs, jax.random.PRNGKey(0)
        )
        np.testing.assert_array_equal(
            out["greedy"], np.asarray(ref["greedy"])
        )
        np.testing.assert_allclose(
            out["values"], np.asarray(ref["values"]), rtol=1e-6
        )
    finally:
        eng.close()


def test_engine_death_fails_policy_requests_fast(policy_params):
    """Chaos: pending policy tickets get EngineDead when the loop
    dies — within seconds, never a hang — and later submits latch
    rejected."""
    from ray_tpu.llm.engine import EngineDead

    eng = _policy_engine(policy_params)

    # Break the program so the NEXT batch kills the loop.
    def boom(params, inputs, key):
        raise RuntimeError("injected program failure")

    eng._program.run = boom
    ticket = eng.submit_policy(np.zeros((2, 4), np.float32))
    t0 = time.monotonic()
    with pytest.raises((EngineDead, RuntimeError)):
        ticket.result(timeout=30)
    assert time.monotonic() - t0 < 10  # fast, not a timeout crawl
    deadline = time.monotonic() + 10
    while not eng.stats()["dead"] and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(EngineDead):
        eng.submit_policy(np.zeros((1, 4), np.float32))


def test_policy_path_serves_through_weight_pushes(policy_params):
    """Drainless sync on the policy path: continuous submits from a
    side thread while weights are pushed repeatedly — every ticket
    succeeds (zero errors attributable to the pushes) and observed
    versions are monotonic."""
    from ray_tpu.rl.models import init_policy_params

    eng = _policy_engine(policy_params)
    try:
        errors = []
        versions = []
        stop = threading.Event()

        def submitter():
            while not stop.is_set():
                try:
                    ticket = eng.submit_policy(
                        np.zeros((2, 4), np.float32)
                    )
                    ticket.result(timeout=30)
                    versions.append(ticket.version)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        thread = threading.Thread(target=submitter)
        thread.start()
        for v in range(1, 4):
            eng.update_weights(
                init_policy_params(jax.random.PRNGKey(v), 4, 2),
                version=v,
            )
            # Wait until a ticket is actually SERVED at >= v before
            # the next push (the first batch may still be jitting),
            # so every generation demonstrably served traffic.
            deadline = time.monotonic() + 30
            while (
                (not versions or versions[-1] < v)
                and not errors
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
        stop.set()
        thread.join(timeout=30)
        assert not errors, errors
        assert versions, "no policy requests served"
        assert versions == sorted(versions)  # monotonic
        assert versions[-1] >= 1  # pushes actually took effect
    finally:
        eng.close()


# ---------------------------------------------------------------------
# drainless weight sync on the LLM path (acceptance criterion)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_llm():
    from ray_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        intermediate=128, max_seq_len=128, dtype=jnp.float32,
        attention="reference",
    )
    old = init_params(jax.random.PRNGKey(0), cfg)
    new = init_params(jax.random.PRNGKey(99), cfg)
    return cfg, old, new


def test_weight_push_mid_decode_token_exact(tiny_llm):
    """THE drainless-sync acceptance test: a weight push lands while
    a stream decodes. The engine serves continuously (no shed, no
    error, no drain): the in-flight stream finishes TOKEN-EXACT on
    the old weights, a stream admitted after the push is token-exact
    on the new weights, both decode CONCURRENTLY through the mixed-
    generation window, and the old generation is dropped once its
    last request retires."""
    from ray_tpu.llm import EngineConfig, InferenceEngine
    from ray_tpu.models.generate import generate

    cfg, p_old, p_new = tiny_llm
    eng = InferenceEngine(
        p_old, cfg,
        EngineConfig(slots=2, max_len=48, prefill_chunk=8,
                     max_new_tokens=16),
        family="drainless",
    )
    try:
        rng = np.random.default_rng(3)
        prompt = rng.integers(1, 128, size=6).tolist()
        stream_old = eng.submit(prompt, max_new_tokens=16)
        it = iter(stream_old)
        out_old = [next(it), next(it)]  # provably mid-decode
        assert eng.update_weights(p_new) == 1
        stream_new = eng.submit(prompt, max_new_tokens=16)
        out_old.extend(it)  # finishes while stream_new decodes
        out_new = list(stream_new)
        assert stream_old.finish_reason == "length"  # no error/shed
        assert stream_new.finish_reason == "length"

        def ref(params):
            toks, _ = generate(
                params,
                jnp.asarray([prompt], jnp.int32),
                jnp.asarray([len(prompt)], jnp.int32),
                cfg,
                max_new_tokens=16,
                temperature=0.0,
            )
            return np.asarray(toks)[0].tolist()

        assert out_old == ref(p_old)  # token-exact on OLD weights
        assert out_new == ref(p_new)  # next admission on NEW weights
        stats = eng.stats()
        assert stats["weight_version"] == 1
        assert stats["weight_gens"] == 1  # old generation dropped
        assert stats["requests_done"] == 2
    finally:
        eng.close()


def test_weight_push_rejects_stale_version(tiny_llm):
    from ray_tpu.llm import EngineConfig, InferenceEngine

    cfg, p_old, p_new = tiny_llm
    eng = InferenceEngine(
        p_old, cfg, EngineConfig(slots=1, max_len=48, prefill_chunk=8),
        family="ver",
    )
    try:
        assert eng.update_weights(p_new, version=5) == 5
        with pytest.raises(ValueError):
            eng.update_weights(p_old, version=5)
    finally:
        eng.close()


# ---------------------------------------------------------------------
# live dataflow chaos (cluster)
# ---------------------------------------------------------------------

def _small_flow(policy, **kw):
    from ray_tpu.rl import PPOConfig

    knobs = dict(queue_capacity=8, max_weight_lag=4)
    knobs.update(kw)
    return (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=2,
            num_envs_per_env_runner=4,
            rollout_fragment_length=8,
        )
        .dataflow(policy=policy, **knobs)
        .debugging(seed=0)
        .build()
    )


def test_runner_kill_mid_rollout_queue_keeps_flowing(rt_session):
    """Chaos: rt.kill of an env runner mid-rollout costs its
    fragment(s), never the flow — updates keep landing, the slot is
    respawned + re-synced, and the fleet is back to full strength."""
    import ray_tpu as rt

    algo = _small_flow("local")
    try:
        algo.train()
        before = algo.flow.stats()["fragments_by_runner"].get(0, 0)
        rt.kill(algo.flow.runner_handle(0))
        for _ in range(3):  # flows THROUGH the death + restore
            result = algo.train()
        stats = algo.flow.stats()
        assert stats["runner_failures"] >= 1
        assert stats["fragments_dropped"] >= 1
        assert result["weight_version"] == 4  # every update landed
        # Restored-slot proof: slot 0's RESPAWNED actor produces
        # fragments again. (Not a ping: runner mailboxes legitimately
        # queue deep behind in-flight sample calls, so liveness is
        # shown by output, bounded by a few more updates.)
        deadline = time.monotonic() + 60
        while (
            algo.flow.stats()["fragments_by_runner"].get(0, 0)
            <= before
            and time.monotonic() < deadline
        ):
            algo.train()
        assert (
            algo.flow.stats()["fragments_by_runner"].get(0, 0)
            > before
        ), algo.flow.stats()
    finally:
        algo.stop()


def test_engine_actor_death_fails_fast(rt_session):
    """Chaos: the policy engine's step loop dying must surface as
    EngineDead at the driver within the call timeout — pending act()
    callers error fast, the learner loop never hangs."""
    import ray_tpu as rt
    from ray_tpu.llm.engine import EngineDead

    algo = _small_flow("engine")
    try:
        algo.train()
        rt.get(algo.flow._engine.die.remote(), timeout=30)
        t0 = time.monotonic()
        with pytest.raises(EngineDead):
            algo.train()
        assert time.monotonic() - t0 < 90  # fast, never a hang
    finally:
        algo.stop()


def test_queue_backpressure_throttles_runners_live(rt_session):
    """With a 1-deep queue and no learner consuming, runner puts hit
    the capacity gate ('full' waits) and depth never exceeds the
    bound — the backpressure contract, live."""
    algo = _small_flow("local")
    try:
        flow = algo.flow
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            flow._pump()
            time.sleep(0.05)
            stats = flow.queue_stats()
            if stats["rejected_full"] > 0:
                break
        stats = flow.queue_stats()
        assert stats["rejected_full"] > 0
        assert stats["depth"] <= stats["capacity"]
        algo.train()  # the learner drains it and training proceeds
    finally:
        algo.stop()


def test_decoupled_ppo_engine_mode_trains(rt_session):
    """Engine-served policy inference end to end: a few iterations
    train, versions advance, the engine batches rows from both
    runners, and weight pushes land drainlessly (no failed
    requests)."""
    algo = _small_flow("engine")
    try:
        for _ in range(2):
            result = algo.train()
        assert np.isfinite(result["episode_return_mean"])
        assert result["weight_version"] == 2
        engine_stats = algo.flow.engine_stats()
        assert engine_stats["policy_rows_served"] > 0
        assert engine_stats["weight_version"] == 2
        assert not engine_stats["dead"]
        stats = algo.flow.stats()
        assert stats["fragments_ok"] >= 2
        assert stats["runner_failures"] == 0
    finally:
        algo.stop()


def test_sync_interval_beyond_lag_bound_never_deadlocks(rt_session):
    """Regression (review finding): with
    sync_interval_updates > max_weight_lag + 1 the queue's staleness
    gates must compare against the last PUBLISHED version — the
    freshest weights a runner can fetch — not the learner's private
    update count, or every put throttles against weights that don't
    exist yet and the flow deadlocks."""
    algo = _small_flow(
        "local", max_weight_lag=1, sync_interval_updates=5
    )
    try:
        for _ in range(3):  # crosses non-publish updates
            result = algo.train()
        assert result["weight_version"] == 3
        stats = algo.flow.queue_stats()
        # Runners were never mass-throttled into a stall.
        assert stats["gets"] > 0
    finally:
        algo.stop()


def test_decoupled_dqn_trains(rt_session):
    from ray_tpu.rl import DQNConfig

    cfg = DQNConfig().environment("CartPole-v1").debugging(seed=0)
    cfg.rollout_length = 8
    cfg.num_envs = 4
    cfg.learning_starts = 32
    cfg.num_updates_per_iteration = 4
    algo = cfg.dataflow(
        policy="local", num_env_runners=2, queue_capacity=8
    ).build()
    try:
        r1 = algo.train()
        r2 = algo.train()
        assert r2["num_updates"] > r1["num_updates"] or (
            r2["num_updates"] >= 4
        )
        assert r2["epsilon"] < 1.0
        assert np.isfinite(r2["td_loss"])
    finally:
        algo.stop()


def test_decoupled_ppo_save_restore(rt_session, tmp_path):
    algo = _small_flow("local")
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
    finally:
        algo.stop()
    algo2 = _small_flow("local")
    try:
        algo2.restore(path)
        assert algo2.iteration == 1
        result = algo2.train()
        assert result["training_iteration"] == 2
    finally:
        algo2.stop()


@pytest.mark.slow
def test_decoupled_ppo_learns_cartpole(rt_session):
    """Learning regression: the decoupled dataflow must not trade
    correctness for overlap — near-on-policy settings (lag bound 2,
    shallow queue) clear the same CartPole bar as synchronous PPO."""
    from ray_tpu.rl import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .dataflow(policy="local", queue_capacity=4, max_weight_lag=2)
        .debugging(seed=0)
        .build()
    )
    try:
        best = 0.0
        for _ in range(30):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 100.0:
                break
        assert best >= 100.0, f"decoupled PPO plateaued at {best}"
    finally:
        algo.stop()
