"""Mesh/sharding/collective tests on a virtual 8-device CPU mesh."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ray_tpu.parallel import (
    ACT_RULES,
    PARAM_RULES,
    MeshSpec,
    annotate,
    collective as col,
    shard_tree,
    spec_for,
)


@pytest.fixture(scope="module")
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices (XLA_FLAGS host device count)")
    return devs


def test_mesh_spec_build(devices8):
    mesh = MeshSpec(fsdp=4, tp=2).build()
    assert mesh.shape["fsdp"] == 4
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 1


def test_mesh_auto(devices8):
    spec = MeshSpec.auto(8, tp=2)
    assert spec.fsdp == 4
    assert spec.num_devices() == 8


def test_mesh_too_many_devices():
    with pytest.raises(ValueError):
        MeshSpec(fsdp=1024).build()


def test_spec_for_rules():
    assert spec_for(("batch", "seq", None), ACT_RULES) == P(
        ("dcn_dp", "dp", "fsdp"), "sp", None
    )
    assert spec_for(("embed", "mlp"), PARAM_RULES) == P("fsdp", "tp")


def test_shard_tree(devices8):
    mesh = MeshSpec(fsdp=8).build()
    params = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
    ann = {"w": annotate("embed", "mlp"), "b": annotate("mlp")}
    sharded = shard_tree(mesh, params, ann, PARAM_RULES)
    # w's first dim is sharded 8-ways over fsdp.
    assert sharded["w"].sharding.spec == P("fsdp", "tp")
    np.testing.assert_array_equal(np.asarray(sharded["w"]), np.ones((16, 4)))


class TestCollectives:
    def _run(self, fn, mesh, x, in_spec=P("fsdp"), out_spec=P("fsdp")):
        return shard_map(
            fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
            check_vma=False,
        )(x)

    def test_allreduce_sum(self, devices8):
        mesh = MeshSpec(fsdp=8).build()
        x = jnp.arange(8.0)
        out = self._run(lambda v: col.allreduce(v, "fsdp"), mesh, x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_allreduce_mean_max(self, devices8):
        mesh = MeshSpec(fsdp=8).build()
        x = jnp.arange(8.0)
        mean = self._run(lambda v: col.allreduce(v, "fsdp", op="mean"), mesh, x)
        np.testing.assert_allclose(np.asarray(mean), np.full(8, 3.5))
        mx = self._run(lambda v: col.allreduce(v, "fsdp", op="max"), mesh, x)
        np.testing.assert_allclose(np.asarray(mx), np.full(8, 7.0))

    def test_allgather(self, devices8):
        mesh = MeshSpec(fsdp=8).build()
        x = jnp.arange(8.0)
        out = self._run(
            lambda v: col.allgather(v, "fsdp"),
            mesh,
            x,
            out_spec=P(None),
        )
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))

    def test_reducescatter(self, devices8):
        mesh = MeshSpec(fsdp=8).build()
        x = jnp.ones((8, 8))
        out = self._run(
            lambda v: col.reducescatter(v, "fsdp", scatter_axis=0),
            mesh,
            x,
            in_spec=P(None, None),
            out_spec=P("fsdp", None),
        )
        np.testing.assert_allclose(np.asarray(out), np.full((8, 8), 8.0))

    def test_broadcast(self, devices8):
        mesh = MeshSpec(fsdp=8).build()
        x = jnp.arange(8.0)
        out = self._run(lambda v: col.broadcast(v, "fsdp", root=3), mesh, x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

    def test_ring_send_recv(self, devices8):
        mesh = MeshSpec(sp=8).build()
        x = jnp.arange(8.0)
        out = shard_map(
            lambda v: col.send_recv(v, "sp", shift=1),
            mesh=mesh,
            in_specs=P("sp"),
            out_specs=P("sp"),
        )(x)
        # member i receives from i-1: [7, 0, 1, ..., 6]
        np.testing.assert_allclose(
            np.asarray(out), np.roll(np.arange(8.0), 1)
        )

    def test_all_to_all_ulysses_reshard(self, devices8):
        # Ulysses: seq-sharded → head-sharded. 8 heads, seq 8.
        mesh = MeshSpec(sp=8).build()
        x = jnp.arange(8 * 8 * 4.0).reshape(8, 8, 4)  # [seq, heads, dim]
        out = shard_map(
            lambda v: col.all_to_all(v, "sp", split_axis=1, concat_axis=0),
            mesh=mesh,
            in_specs=P("sp", None, None),
            out_specs=P(None, "sp", None),
        )(x)
        assert out.shape == (8, 8, 4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_collectives_inside_jit_grad(self, devices8):
        # The data-parallel training pattern: per-shard loss, psum'd
        # gradient — must be jit/grad composable.
        mesh = MeshSpec(fsdp=8).build()

        @jax.jit
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P("fsdp")),
            out_specs=P(None),
            check_vma=False,
        )
        def grad_norm(w, x):
            def loss(w):
                return jnp.sum((x * w) ** 2) / x.size

            g = jax.grad(loss)(w)
            g = col.allreduce(g, "fsdp", op="mean")
            return jnp.sum(g * g)[None]

        w = jnp.ones(())
        x = jnp.arange(16.0)
        out = grad_norm(w, x)
        assert np.isfinite(np.asarray(out)).all()


def test_hybrid_mesh_dcn_dp(devices8):
    """dcn_dp>1 builds the hybrid layout: outer axis = slices (virtual
    contiguous blocks off-hardware), inner axes within one slice."""
    import jax

    mesh = MeshSpec(dcn_dp=2, fsdp=2, tp=2).build()
    assert mesh.shape["dcn_dp"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tp"] == 2
    # Slice 0 owns the first 4 devices, slice 1 the last 4.
    grid = mesh.devices
    first = {d.id for d in grid[0].flatten()}
    second = {d.id for d in grid[1].flatten()}
    assert first == {d.id for d in jax.devices()[:4]}
    assert second == {d.id for d in jax.devices()[4:8]}


def test_hybrid_mesh_too_few_devices():
    # Trips build()'s generic device-count check before hybrid layout.
    with pytest.raises(ValueError):
        MeshSpec(dcn_dp=4, fsdp=1024).build()


def test_hybrid_mesh_uneven_slices_rejected():
    """Real multi-slice topology with too few slices for dcn_dp, and
    slices that can't cover per-slice demand, both fail loudly."""

    class FakeDev:
        def __init__(self, slice_index, id):
            self.slice_index = slice_index
            self.id = id

    from ray_tpu.parallel.mesh import group_by_slice

    devs = [FakeDev(0, 0), FakeDev(0, 1), FakeDev(1, 2)]
    groups = group_by_slice(devs)
    assert [len(g) for g in groups] == [2, 1]
    spec = MeshSpec(dcn_dp=3, fsdp=1)
    with pytest.raises(ValueError, match="slices"):
        spec._build_hybrid(devs)  # 2 slices < dcn_dp=3
    spec = MeshSpec(dcn_dp=2, fsdp=2)
    with pytest.raises(ValueError, match="per slice"):
        # slice 1 contributes 1 device, need 2.
        spec._build_hybrid(devs + [FakeDev(0, 3)])
