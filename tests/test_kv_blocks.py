"""Paged-KV block allocator invariants (ISSUE 11): alloc/free/
refcount, double-free detection, prefix pin/register/LRU-evict, and
the engine-level memory contracts — a request the pool can never hold
is SHED at submit, and a block-starved admission WAITS (FIFO, no
crash, no skip-ahead) until running requests release their pages."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm.kv_slots import (
    BlockAllocator,
    BlocksExhausted,
    PagedKVCache,
    default_block_len,
)


# ---------------------------------------------------------------------
# allocator invariants (pure bookkeeping, no jax)
# ---------------------------------------------------------------------

def test_reserve_release_roundtrip():
    alloc = BlockAllocator(9)  # 8 usable + reserved null block
    assert alloc.capacity() == 8
    assert alloc.available() == 8
    blocks = alloc.reserve(5)
    assert len(set(blocks)) == 5
    assert 0 not in blocks  # the null block is never handed out
    assert alloc.used() == 5
    assert alloc.available() == 3
    alloc.release(blocks)
    assert alloc.used() == 0
    assert alloc.available() == 8


def test_oom_raises_and_grants_nothing_partial():
    alloc = BlockAllocator(5)
    alloc.reserve(3)
    avail = alloc.available()
    with pytest.raises(BlocksExhausted):
        alloc.reserve(avail + 1)
    assert alloc.available() == avail  # all-or-nothing


def test_double_free_raises():
    alloc = BlockAllocator(4)
    blocks = alloc.reserve(1)
    alloc.release(blocks)
    with pytest.raises(ValueError):
        alloc.release(blocks)


def test_refcount_shared_prefix_block():
    alloc = BlockAllocator(8)
    [block] = alloc.reserve(1)
    alloc.register(block, ("p",))
    # A second request pins the same prefix block.
    assert alloc.match_prefix([("p",)]) == [block]
    alloc.release([block])  # first owner done
    assert alloc.used() == 1  # still pinned by the second
    alloc.release([block])  # second owner done
    assert alloc.used() == 0
    assert alloc.cached() == 1  # refcount 0 but reusable
    # Still matchable from the cached-free state (re-pins it).
    assert alloc.match_prefix([("p",)]) == [block]
    alloc.release([block])


def test_eviction_is_lru_and_drops_prefix_entry():
    alloc = BlockAllocator(3)  # 2 usable
    a, b = alloc.reserve(2)
    alloc.register(a, ("a",))
    alloc.register(b, ("b",))
    alloc.release([a])  # a becomes cached-free first (older)
    alloc.release([b])
    [evicted] = alloc.reserve(1)
    assert evicted == a  # oldest cached-free evicts first
    assert alloc.peek_prefix([("a",)]) == 0  # its prefix entry is gone
    assert alloc.peek_prefix([("b",)]) == 1  # the newer one survives


def test_match_pins_block_out_of_eviction():
    alloc = BlockAllocator(3)
    a, b = alloc.reserve(2)
    alloc.register(a, ("a",))
    alloc.register(b, ("b",))
    alloc.release([a])
    alloc.release([b])
    assert alloc.match_prefix([("a",)]) == [a]  # pin a
    [evicted] = alloc.reserve(1)
    assert evicted == b  # the reservation cannot steal the pinned hit
    alloc.release([a])


def test_register_first_writer_wins_and_requires_pin():
    alloc = BlockAllocator(4)
    a, b = alloc.reserve(2)
    assert alloc.register(a, ("k",)) is True
    assert alloc.register(b, ("k",)) is False  # prefix taken: no-op
    assert alloc.match_prefix([("k",)]) == [a]
    alloc.release([a])
    with pytest.raises(ValueError):
        alloc.register(99, ("other",))  # unpinned block


def test_peek_prefix_stops_at_first_gap():
    alloc = BlockAllocator(8)
    a, b = alloc.reserve(2)
    alloc.register(a, ("p1",))
    alloc.register(b, ("p3",))
    assert alloc.peek_prefix([("p1",), ("p2",), ("p3",)]) == 1
    assert alloc.match_prefix([("p1",), ("p2",), ("p3",)]) == [a]
    alloc.release([a])  # the match pin
    alloc.release([a, b])  # the original reservations


# ---------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------

def test_default_block_len_divides_chunk():
    assert default_block_len(32) == 16
    assert default_block_len(8) == 8
    assert default_block_len(24) == 12
    assert default_block_len(7) == 7
    for chunk in (7, 8, 16, 24, 32, 48):
        assert chunk % default_block_len(chunk) == 0


def test_paged_cache_geometry_validation():
    from ray_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=32, dim=16, n_layers=1, n_heads=2, n_kv_heads=1,
        intermediate=32, max_seq_len=64, dtype=jnp.float32,
        attention="reference",
    )
    with pytest.raises(ValueError):  # block doesn't divide chunk
        PagedKVCache(cfg, 8, 16, 64, prefill_chunk=8)
    with pytest.raises(ValueError):  # max_len not a block multiple
        PagedKVCache(cfg, 8, 8, 60, prefill_chunk=8)
    kv = PagedKVCache(cfg, 8, 8, 64, prefill_chunk=8)
    assert kv.max_blocks == 8
    assert kv.blocks_for(1) == 1
    assert kv.blocks_for(8) == 1
    assert kv.blocks_for(9) == 2


def test_prefix_keys_cover_only_full_blocks_and_bind_whole_prefix():
    from ray_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=32, dim=16, n_layers=1, n_heads=2, n_kv_heads=1,
        intermediate=32, max_seq_len=64, dtype=jnp.float32,
        attention="reference",
    )
    kv = PagedKVCache(cfg, 8, 8, 64, prefill_chunk=8)
    prompt = list(range(20))  # 2 full blocks + 4-token partial
    keys = kv.prefix_keys(prompt)
    assert len(keys) == 2  # the partial block never gets a key
    # Deterministic, and equal prefixes produce equal keys.
    assert keys == kv.prefix_keys(prompt[:17])
    # The chain binds the WHOLE prefix: same second block behind a
    # different first block must yield a different second key.
    other = kv.prefix_keys([99] + list(range(1, 20)))
    assert other[0] != keys[0]
    assert other[1] != keys[1]
    # Shared first block, divergent second.
    branch = kv.prefix_keys(list(range(8)) + [77] * 8)
    assert branch[0] == keys[0]
    assert branch[1] != keys[1]


# ---------------------------------------------------------------------
# engine-level memory contracts (tiny model)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    from ray_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        intermediate=128, max_seq_len=128, dtype=jnp.float32,
        attention="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_engine_pool_oom_sheds_at_submit(tiny_model):
    """A request that could NEVER get its pages (bigger than the whole
    pool) is shed at submit with EngineOverloaded; the engine stays
    alive and keeps serving pool-sized requests."""
    from ray_tpu.llm import (
        EngineConfig, EngineOverloaded, InferenceEngine,
    )

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(
            slots=2, max_len=48, prefill_chunk=8, kv_blocks=4,
            max_new_tokens=8,
        ),
        family="tiny",
    )
    try:
        # 29-token prompt + 8 budget = 37 tokens = 5 blocks of 8, but
        # the pool only holds 3 usable blocks.
        with pytest.raises(EngineOverloaded):
            eng.submit(list(range(1, 30)), max_new_tokens=8)
        out = list(eng.submit([1, 2, 3], max_new_tokens=4))
        assert len(out) == 4
        assert eng.stats()["dead"] is False
    finally:
        eng.close()


def test_block_starved_admission_waits_then_serves(tiny_model):
    """Two requests that each need more than half the pool: slots are
    free but blocks are not, so the second request WAITS (gated FIFO
    admission) and is served after the first releases its pages —
    never a reserve failure that would kill the loop."""
    from ray_tpu.llm import EngineConfig, InferenceEngine

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(
            slots=2, max_len=48, prefill_chunk=8, kv_blocks=7,
            max_new_tokens=16, prefix_cache=False,
        ),
        family="tiny",
    )
    try:
        # Each needs ceil((16 + 16) / 8) = 4 of the 6 usable blocks.
        first = eng.submit(list(range(1, 17)), max_new_tokens=16)
        second = eng.submit(list(range(101, 117)), max_new_tokens=16)
        assert len(list(first)) == 16
        assert len(list(second)) == 16
        stats = eng.stats()
        assert stats["dead"] is False
        assert stats["kv_blocks_used"] == 0  # everything released
    finally:
        eng.close()


def test_peek_cached_distinguishes_live_pins_from_cached_free():
    alloc = BlockAllocator(8)
    a, b = alloc.reserve(2)
    alloc.register(a, ("p1",))
    alloc.register(b, ("p2",))
    alloc.release([b])  # b cached-free; a stays live-pinned
    assert alloc.peek_cached([("p1",), ("p2",)], 2) == 1
    assert alloc.peek_cached([("p1",), ("p2",)], 1) == 0  # a is live
    alloc.release([a])


def test_sharing_live_prefix_relaxes_admission(tiny_model):
    """Review-caught gate bug: hit blocks pinned by a LIVE request
    cost no availability to share, so a prefix-sharing request must
    fit in a pool the naive `available >= total` arithmetic says is
    full — both requests decode CONCURRENTLY."""
    import threading

    from ray_tpu.llm import EngineConfig, InferenceEngine

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(
            slots=2, max_len=48, prefill_chunk=8, kv_blocks=8,
            max_new_tokens=8, prefix_cache=True,
        ),
        family="tiny",
    )
    try:
        shared = list(range(1, 17))  # 2 full blocks
        # A: 5 of the 7 usable blocks (16 prompt + 24 budget).
        first = eng.submit(shared, max_new_tokens=24)
        consumed = []
        consumer = threading.Thread(
            target=lambda: consumed.extend(first), daemon=True
        )
        consumer.start()
        deadline = time.time() + 30
        while time.time() < deadline and not consumed:
            time.sleep(0.005)  # A is decoding (prefix registered)
        # B: identical prompt, 3 total blocks, skip 1 shared block ->
        # needs 2 fresh of the 2 still available. Old gate demanded 3.
        second = eng.submit(shared, max_new_tokens=8)
        concurrent = False
        while time.time() < deadline:
            stats = eng.stats()
            if stats["slots_used"] == 2:
                concurrent = True
                break
            time.sleep(0.005)
        assert concurrent, "prefix-sharing request was not admitted " \
            "while the prefix owner was still decoding"
        assert len(list(second)) == 8
        consumer.join(timeout=30)
        assert len(consumed) == 24
        assert eng.stats()["prefix_hits"] >= 1
    finally:
        eng.close()


def test_engine_block_accounting_in_stats(tiny_model):
    from ray_tpu.llm import EngineConfig, InferenceEngine

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(slots=2, max_len=48, prefill_chunk=8,
                     max_new_tokens=4),
        family="tiny",
    )
    try:
        stats = eng.stats()
        assert stats["kv_block_len"] == 8
        assert stats["kv_blocks_total"] == 2 * (48 // 8)
        assert stats["kv_blocks_used"] == 0
        list(eng.submit([5, 6, 7], max_new_tokens=4))
        stats = eng.stats()
        assert stats["kv_blocks_used"] == 0
        # The full prompt had no full block (3 tokens < 8), so
        # nothing registers in the prefix cache either.
        assert stats["kv_blocks_cached"] == 0
    finally:
        eng.close()
