"""Flagship Llama training under pipeline parallelism composed with
sequence (ring attention) and expert (MoE) parallelism — the SURVEY
§2.4 PP/EP rows exercised through the real model, not a toy stage
(r2 verdict weak #7)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn  # noqa: E402
from ray_tpu.train.pipeline_step import make_pp_train_step  # noqa: E402
from ray_tpu.train.train_step import default_optimizer  # noqa: E402


def _mesh(pp, sp, ep):
    devs = np.array(jax.devices()[: pp * sp * ep]).reshape(pp, sp, ep)
    return Mesh(devs, ("pp", "sp", "ep"))


def _jax_version() -> tuple:
    return tuple(
        int(part) for part in jax.__version__.split(".")[:2]
    )


#: jax 0.4.x shard_map mis-transposes the pp x ep MoE compose (the
#: grad of the ppermute/all-to-all sandwich; CHANGES.md PR 12 — the
#: 2 tests below are the documented known-failing pair on 0.4.37).
#: Version-gated, NOT xfailed: on jax >= 0.6 the checker is back on
#: and a regression here must fail loudly.
_SHARD_MAP_TRANSPOSE_BUG = pytest.mark.skipif(
    _jax_version() < (0, 6),
    reason=(
        "jax < 0.6 shard_map transpose bug breaks the pp x ep MoE "
        "compose (documented known-failing on 0.4.37; see "
        "CHANGES.md PR 12)"
    ),
)


def _run_steps(cfg, mesh, batch, seq, steps=3, num_mb=2):
    init_fn, step_fn = make_pp_train_step(
        cfg, mesh, default_optimizer(learning_rate=1e-2, total_steps=10),
        num_microbatches=num_mb,
    )
    state = init_fn(
        jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size
    )
    losses = []
    for _ in range(steps):
        state, metrics = step_fn(state, tokens[:, :-1], tokens[:, 1:])
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    return losses


def test_pp_sp_dense_loss_decreases():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=4, n_heads=4, n_kv_heads=4,
        intermediate=128, max_seq_len=64, dtype=jnp.float32,
        attention="ring",
    )
    mesh = _mesh(pp=2, sp=2, ep=1)
    losses = _run_steps(cfg, mesh, batch=4, seq=65)
    assert losses[-1] < losses[0], losses


@_SHARD_MAP_TRANSPOSE_BUG
def test_pp_ep_moe_loss_decreases():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
        intermediate=128, max_seq_len=64, dtype=jnp.float32,
        attention="reference", moe_experts=4,
    )
    mesh = _mesh(pp=2, sp=1, ep=2)
    losses = _run_steps(cfg, mesh, batch=8, seq=33)
    assert losses[-1] < losses[0], losses


@_SHARD_MAP_TRANSPOSE_BUG
def test_pp_sp_ep_full_compose():
    """The full pp x sp x ep stack in one program (8 devices)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
        intermediate=128, max_seq_len=64, dtype=jnp.float32,
        attention="ring", moe_experts=4,
    )
    mesh = _mesh(pp=2, sp=2, ep=2)
    losses = _run_steps(cfg, mesh, batch=8, seq=65)
    assert losses[-1] < losses[0], losses


def test_pp_loss_matches_nonpp():
    """The GPipe schedule computes the SAME loss as the plain stacked
    forward at identical params — pins microbatch ordering, stage
    masking, and gradient scaling (a reordering/double-count bug would
    still show a decreasing loss)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=4, n_heads=4, n_kv_heads=4,
        intermediate=128, max_seq_len=64, dtype=jnp.float32,
        attention="reference",
    )
    mesh = _mesh(pp=2, sp=1, ep=1)
    init_fn, step_fn = make_pp_train_step(
        cfg, mesh, default_optimizer(total_steps=10), num_microbatches=2
    )
    state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size
    )
    _, metrics = step_fn(state, tokens[:, :-1], tokens[:, 1:])
    pp_loss = float(metrics["loss"])
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref = float(loss_fn(params, tokens[:, :-1], tokens[:, 1:], cfg))
    assert abs(pp_loss - ref) < 1e-4, (pp_loss, ref)


def test_moe_dense_matches_shapes_single_device():
    """MoE Llama runs single-device (dense fallback path) through the
    standard loss_fn, aux loss included."""
    cfg = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
        intermediate=64, max_seq_len=32, dtype=jnp.float32,
        attention="reference", moe_experts=4,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size
    )
    loss = jax.jit(
        lambda p, t, y: loss_fn(p, t, y, cfg)
    )(params, tokens[:, :-1], tokens[:, 1:])
    assert np.isfinite(float(loss))
    grads = jax.grad(
        lambda p: loss_fn(p, tokens[:, :-1], tokens[:, 1:], cfg)
    )(params)
    total = jax.tree.reduce(
        lambda a, b: a + float(jnp.sum(jnp.abs(b))), grads, 0.0
    )
    assert np.isfinite(total) and total > 0


def test_pp_loss_matches_nonpp_gemma_conventions():
    """Regression: the pipeline forward once bypassed the shared
    family helpers — a Gemma config (sqrt(dim) embed scale, (1+w)
    final norm, GeGLU, decoupled head_dim) silently computed different
    numerics under pp than the plain forward."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=4, n_heads=4, n_kv_heads=2,
        custom_head_dim=32, act="gelu_tanh", norm_offset=True,
        embed_scale=True, intermediate=128, max_seq_len=64,
        dtype=jnp.float32, attention="reference",
    )
    mesh = _mesh(pp=2, sp=1, ep=1)
    init_fn, step_fn = make_pp_train_step(
        cfg, mesh, default_optimizer(total_steps=10), num_microbatches=2
    )
    state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size
    )
    _, metrics = step_fn(state, tokens[:, :-1], tokens[:, 1:])
    pp_loss = float(metrics["loss"])
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref = float(loss_fn(params, tokens[:, :-1], tokens[:, 1:], cfg))
    assert abs(pp_loss - ref) < 1e-4, (pp_loss, ref)
