"""MPMD pipeline-parallel training (train/mpmd_pipeline.py +
parallel/schedule.py): 1F1B/interleaved schedule invariants, loss/grad
parity of the multi-process step against the single-program baselines,
checkpoint compose, per-edge doctor visibility, and stage-death chaos
(clean error, never a hang)."""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from ray_tpu.models.llama import (  # noqa: E402
    LlamaConfig,
    init_params,
    loss_fn,
)
from ray_tpu.parallel.schedule import (  # noqa: E402
    interleaved_1f1b,
    max_stash_depth,
    one_f_one_b,
    partition_layers,
    simulate_schedule,
    theoretical_efficiency,
    validate_schedule,
)


def _tiny_cfg(**kw):
    defaults = dict(
        vocab_size=64, dim=32, n_layers=4, n_heads=2, n_kv_heads=2,
        intermediate=64, max_seq_len=32, dtype=jnp.float32,
        attention="reference",
    )
    defaults.update(kw)
    return LlamaConfig(**defaults)


# ---------------------------------------------------------------------------
# schedule invariants (pure, no cluster)
# ---------------------------------------------------------------------------

class TestSchedule:
    @pytest.mark.parametrize(
        "n,m", [(2, 2), (2, 8), (3, 7), (4, 16), (4, 3), (8, 2)]
    )
    def test_1f1b_complete_and_deadlock_free(self, n, m):
        schedules = one_f_one_b(n, m)
        validate_schedule(schedules, n, m)

    @pytest.mark.parametrize(
        "n,m", [(2, 4), (2, 8), (4, 8), (4, 16)]
    )
    def test_1f1b_stash_depth_bounded_by_stages(self, n, m):
        """THE 1F1B property: activation stash stays O(n_stages),
        not O(num_microbatches) like GPipe."""
        for ops in one_f_one_b(n, m):
            assert max_stash_depth(ops) <= n
        # GPipe (all-F-then-all-B) would stash m per stage — prove
        # the schedule is actually better when m > n.
        if m > n:
            gpipe_stage0 = [("F", 0, i) for i in range(m)] + [
                ("B", 0, i) for i in range(m)
            ]
            assert max_stash_depth(gpipe_stage0) == m

    @pytest.mark.parametrize("n,m", [(4, 1), (4, 2), (8, 3)])
    def test_no_deadlock_when_fewer_microbatches_than_stages(
        self, n, m
    ):
        schedules = one_f_one_b(n, m)
        validate_schedule(schedules, n, m)

    @pytest.mark.parametrize(
        "n,m,v", [(2, 4, 2), (2, 8, 3), (4, 8, 2), (3, 5, 2)]
    )
    def test_interleaved_complete_and_deadlock_free(self, n, m, v):
        schedules = interleaved_1f1b(n, m, v)
        validate_schedule(schedules, n, m, v)

    def test_interleaved_v1_degenerates_to_1f1b(self):
        assert interleaved_1f1b(4, 8, 1) == one_f_one_b(4, 8)

    def test_validator_rejects_deadlock_and_duplicates(self):
        good = one_f_one_b(2, 2)
        bad = [list(ops) for ops in good]
        # Stage 1 demanding mb 1's forward before mb 0's backward
        # breaks FIFO order on the boundary edge.
        bad[1] = [bad[1][1], bad[1][0]] + bad[1][2:]
        with pytest.raises(ValueError):
            validate_schedule(bad, 2, 2)
        dup = [list(ops) for ops in good]
        dup[0][1] = dup[0][0]
        with pytest.raises(ValueError):
            validate_schedule(dup, 2, 2)

    def test_bounded_depth_deadlock_dies_at_validation(self):
        """Deep interleaving + shallow channels is a REAL deadlock
        (every stage blocked in a put/get cycle) — the bounded-edge
        validation must reject it at build time, not let the gang
        hang until hop-timeout. Shipped geometries stay valid at the
        default depth, and plain 1F1B is safe even at depth 1."""
        deep = interleaved_1f1b(2, 16, 5)
        with pytest.raises(ValueError, match="channel_depth"):
            validate_schedule(deep, 2, 16, 5, channel_depth=4)
        validate_schedule(deep, 2, 16, 5, channel_depth=8)
        for n, m, v in [(2, 8, 1), (4, 16, 1), (2, 8, 2)]:
            validate_schedule(
                interleaved_1f1b(n, m, v), n, m, v, channel_depth=4
            )
        validate_schedule(
            one_f_one_b(4, 8), 4, 8, channel_depth=1
        )

    def test_driver_rejects_undeep_channels_at_construction(self):
        """MPMDPipeline refuses to build (no actors, no channels)
        when the schedule cannot execute under the configured
        channel depth."""
        from ray_tpu.train.mpmd_pipeline import MPMDPipeline

        cfg = _tiny_cfg(n_layers=10)
        with pytest.raises(ValueError, match="channel_depth"):
            MPMDPipeline(
                cfg, 2, num_microbatches=16, microbatch_size=2,
                seq_len=16, chunks_per_stage=5, channel_depth=4,
            )

    def test_replay_matches_theoretical_bound_at_uniform_cost(self):
        for n, m in [(2, 8), (4, 16), (3, 9)]:
            sim = simulate_schedule(
                one_f_one_b(n, m), lambda k, c, mb: 1.0
            )
            bound = theoretical_efficiency(n, m)
            assert sim["efficiency"] == pytest.approx(
                bound, rel=1e-9
            )

    def test_partition_balances_asymmetric_ends(self):
        # A heavy lm_head/loss end must shed layers from the last
        # chunk; a uniform stack splits evenly.
        assert partition_layers(8, 2) == [(0, 4), (4, 8)]
        bounds = partition_layers(8, 2, head_ms=3.0)
        assert bounds[1][1] - bounds[1][0] < 4
        costs = [1, 1, 4, 1, 1, 1]
        bounds = partition_layers(6, 2, costs)
        spans = [sum(costs[lo:hi]) for lo, hi in bounds]
        assert max(spans) <= 6  # the 4-cost layer isolated sensibly


# ---------------------------------------------------------------------------
# the MPMD step against the single-program truths
# ---------------------------------------------------------------------------

def _build_pipe(rt, cfg, n, m, mb, seq, **kw):
    from ray_tpu.train.mpmd_pipeline import MPMDPipeline

    kw.setdefault("hop_timeout_s", 60)
    kw.setdefault("step_timeout_s", 120)
    return MPMDPipeline(
        cfg, n, num_microbatches=m, microbatch_size=mb,
        seq_len=seq, **kw
    )


def _batch(cfg, B, T, seed=1):
    tokens = np.asarray(
        jax.random.randint(
            jax.random.PRNGKey(seed), (B, T + 1), 0, cfg.vocab_size
        )
    )
    return tokens[:, :-1], tokens[:, 1:]


def test_mpmd_loss_and_grad_parity_vs_single_program(rt_session):
    """Loss AND gradients of the 1F1B multi-process step equal the
    plain single-program forward at the same init — grads pinned via
    one SGD update (params' = params - lr * grad leaf-for-leaf)."""
    rt = rt_session
    cfg = _tiny_cfg()
    B, T, m = 4, 16, 2
    pipe = _build_pipe(
        rt, cfg, 2, m, B // m, T,
        optimizer_factory=lambda: optax.sgd(0.1),
    )
    try:
        inp, tgt = _batch(cfg, B, T)
        out = pipe.step(inp, tgt)
        params = init_params(jax.random.PRNGKey(0), cfg)
        ref_loss = float(loss_fn(params, inp, tgt, cfg))
        assert out["loss"] == pytest.approx(ref_loss, abs=2e-5)
        # Per-stage telemetry fields the bench's efficiency
        # accounting and the doctor both read.
        for stage in out["stages"]:
            assert stage["stash_peak"] <= pipe.stash_bound <= 2
            assert stage["busy_ms"] > 0
            assert isinstance(stage["edges"], list)
        grads = jax.grad(
            lambda p: loss_fn(p, inp, tgt, cfg)
        )(params)
        want = jax.tree.map(
            lambda p, g: np.asarray(p) - 0.1 * np.asarray(g),
            params, grads,
        )
        got = pipe.collect_params()
        for key in ("embed", "final_norm", "lm_head"):
            np.testing.assert_allclose(
                got[key], want[key], rtol=2e-4, atol=2e-5,
                err_msg=key,
            )
        for key in want["layers"]:
            np.testing.assert_allclose(
                got["layers"][key],
                np.asarray(want["layers"][key]),
                rtol=2e-4, atol=2e-5, err_msg=key,
            )
    finally:
        pipe.shutdown()


def test_mpmd_matches_single_program_gpipe_baseline(rt_session):
    """Same loss as train/pipeline_step.py's in-one-jitted-program
    GPipe at identical geometry — the two pipeline modes must agree
    on the numbers before their tokens/s may be compared."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    from jax.sharding import Mesh

    from ray_tpu.train.pipeline_step import make_pp_train_step
    from ray_tpu.train.train_step import default_optimizer

    rt = rt_session
    cfg = _tiny_cfg()
    B, T, m = 4, 16, 2
    pipe = _build_pipe(rt, cfg, 2, m, B // m, T)
    try:
        inp, tgt = _batch(cfg, B, T)
        mpmd_loss = pipe.step(inp, tgt)["loss"]
    finally:
        pipe.shutdown()
    mesh = Mesh(
        np.array(jax.devices()[:2]).reshape(2, 1, 1),
        ("pp", "sp", "ep"),
    )
    init_fn, step_fn = make_pp_train_step(
        cfg, mesh, default_optimizer(total_steps=10),
        num_microbatches=m,
    )
    state = init_fn(
        jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
    )
    _, metrics = step_fn(state, jnp.asarray(inp), jnp.asarray(tgt))
    assert mpmd_loss == pytest.approx(
        float(metrics["loss"]), abs=2e-4
    )


def test_mpmd_interleaved_parity_and_multistep(rt_session):
    """Interleaved (virtual-stage) schedule computes the same first
    loss, and repeated steps with an optimizer decrease it (channel
    edges are REUSED across steps — any per-step rewiring bug shows
    up as a desync here)."""
    rt = rt_session
    cfg = _tiny_cfg()
    B, T, m = 4, 16, 4
    pipe = _build_pipe(
        rt, cfg, 2, m, B // m, T, chunks_per_stage=2,
        optimizer_factory=lambda: optax.adamw(5e-3),
    )
    try:
        assert pipe.V == 4 and len(pipe.bounds) == 4
        inp, tgt = _batch(cfg, B, T)
        params = init_params(jax.random.PRNGKey(0), cfg)
        ref_loss = float(loss_fn(params, inp, tgt, cfg))
        losses = [pipe.step(inp, tgt)["loss"] for _ in range(4)]
        assert losses[0] == pytest.approx(ref_loss, abs=2e-5)
        assert losses[-1] < losses[0]
    finally:
        pipe.shutdown()


def test_mpmd_checkpoint_roundtrip_async_barrier(
    rt_session, tmp_path
):
    """save_checkpoint(async) + wait_for_checkpoints (the PR 4
    durability barrier) + restore: params survive byte-exact across
    further training."""
    rt = rt_session
    cfg = _tiny_cfg(n_layers=2)
    B, T, m = 4, 16, 2
    pipe = _build_pipe(
        rt, cfg, 2, m, B // m, T,
        optimizer_factory=lambda: optax.sgd(0.1),
    )
    try:
        inp, tgt = _batch(cfg, B, T)
        pipe.step(inp, tgt)
        snap = pipe.collect_params()
        save_step = pipe._step_index
        root = str(tmp_path / "ckpt")
        pipe.save_checkpoint(root, async_save=True)
        # Keep training while the save persists in the background…
        pipe.step(inp, tgt)
        pipe.wait_for_checkpoints()  # durability barrier
        drifted = pipe.collect_params()
        assert not np.allclose(
            drifted["lm_head"], snap["lm_head"]
        )
        pipe.restore_checkpoint(root, save_step)
        restored = pipe.collect_params()
        np.testing.assert_array_equal(
            restored["lm_head"], snap["lm_head"]
        )
        np.testing.assert_array_equal(
            restored["layers"]["wq"], snap["layers"]["wq"]
        )
    finally:
        pipe.shutdown()


def test_mpmd_edges_visible_in_doctor(rt_session):
    """Per-edge channel counters (dag/edges.py) reach the head and
    fold into the doctor verdict — a straggler stage is nameable."""
    rt = rt_session
    cfg = _tiny_cfg(n_layers=2)
    B, T, m = 4, 16, 4
    pipe = _build_pipe(rt, cfg, 2, m, B // m, T)
    try:
        inp, tgt = _batch(cfg, B, T)
        for _ in range(2):
            pipe.step(inp, tgt)
        # Histograms and counter deltas can land in different metric
        # flush ticks — poll until the edge row carries its hops.
        deadline = time.monotonic() + 20
        dag = {}
        while time.monotonic() < deadline:
            dag = rt.diagnose(capture_stacks=False).get("dag", {})
            row = dag.get("edges", {}).get("s0->s1:b0", {})
            if row.get("hops", 0) >= 2 * m:
                break
            time.sleep(0.5)
        edges = dag.get("edges", {})
        assert "s0->s1:b0" in edges and "s1->s0:b0" in edges
        row = edges["s0->s1:b0"]
        # m forwards per step x 2 steps hopped this edge (counted at
        # both endpoints).
        assert row["hops"] >= 2 * m
        assert row["bytes"] > 0
        assert "recv_wait_ms" in row or "send_wait_ms" in row
    finally:
        pipe.shutdown()


def test_mpmd_stage_death_fails_step_cleanly(rt_session):
    """Chaos: killing a stage gang worker mid-step fails the step
    with MPMDPipelineError — the surviving stage unblocks via edge
    closure instead of hanging on its channel peer."""
    from ray_tpu.train.mpmd_pipeline import MPMDPipelineError

    rt = rt_session
    cfg = _tiny_cfg(n_layers=2, dim=64, intermediate=128)
    B, T, m = 64, 32, 32
    pipe = _build_pipe(
        rt, cfg, 2, m, B // m, T,
        hop_timeout_s=30, step_timeout_s=45,
    )
    try:
        inp, tgt = _batch(cfg, B, T)
        pipe.step(inp, tgt)  # warm the programs
        result = {}

        def stepper():
            try:
                pipe.step(inp, tgt)
                result["ok"] = True
            except BaseException as e:  # noqa: BLE001 — recorded
                result["err"] = e

        thread = threading.Thread(target=stepper)
        thread.start()
        time.sleep(0.05)  # land the kill mid-step
        rt.kill(pipe.stages[1])
        thread.join(timeout=60)
        assert not thread.is_alive(), "step hung after stage death"
        assert isinstance(result.get("err"), MPMDPipelineError), (
            result
        )
        # The pipeline is marked broken — further steps refuse fast.
        with pytest.raises(MPMDPipelineError):
            pipe.step(inp, tgt)
    finally:
        pipe.shutdown()
