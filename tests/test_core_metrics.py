"""Core-metrics registry tests.

Reference test model: src/ray/stats metric_defs — a central table of
runtime gauges/counters; here validated end-to-end: daemon counters
bump, worker-node snapshots ride heartbeats, the head aggregates
across nodes, and the Prometheus endpoint exposes the series.
"""

import time

import pytest

from ray_tpu._private.metric_defs import CORE_METRICS


def test_registry_is_well_formed():
    assert len(CORE_METRICS) >= 35
    for name, (kind, unit, description) in CORE_METRICS.items():
        assert name.startswith("rt_")
        assert kind in ("gauge", "counter")
        assert description
        if kind == "counter":
            assert name.endswith("_total"), name


def test_core_metrics_after_tasks(rt_session):
    rt = rt_session
    from ray_tpu.util.metrics import metrics_summary

    @rt.remote
    def work(x):
        return x + 1

    assert rt.get([work.remote(i) for i in range(5)]) == list(
        range(1, 6)
    )

    @rt.remote
    class Probe:
        def ping(self):
            return 1

    actor = Probe.remote()
    assert rt.get(actor.ping.remote()) == 1

    summary = metrics_summary()
    core = {k: v for k, v in summary.items() if k.startswith("rt_")}
    assert core["rt_tasks_finished_total"]["total"] >= 5
    assert core["rt_actors_created_total"]["total"] >= 1
    assert core["rt_workers_alive"]["value"] >= 1
    assert core["rt_nodes_alive"]["value"] >= 1
    assert core["rt_rpc_requests_total"]["total"] > 0
    assert core["rt_object_store_bytes_capacity"]["value"] > 0
    assert core["rt_uptime_s"]["value"] > 0
    # Every gauge/counter in the registry that reports here is typed
    # correctly. rt_-prefixed MEMORY-LEDGER series (rt_job_*, the
    # transfer matrix) ride the same summary but are not core-registry
    # metrics — they are covered by the data-plane tests.
    for name, entry in core.items():
        if name not in CORE_METRICS:
            continue
        kind, _, _ = CORE_METRICS[name]
        assert entry["kind"] == kind
        assert ("total" if kind == "counter" else "value") in entry


@pytest.mark.timeout(180)
def test_worker_node_metrics_ride_heartbeats():
    """Two-daemon cluster: the head's summary includes the worker
    node's snapshot under by_node."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 1.0})
    try:
        cluster.add_node(num_cpus=2.0)
        cluster.wait_for_nodes(2, timeout=60)
        rt.init(address=cluster.address)
        try:

            @rt.remote(num_cpus=2)
            def on_worker_node():
                return "ok"

            assert rt.get(on_worker_node.remote(), timeout=60) == "ok"
            from ray_tpu.util.metrics import metrics_summary

            deadline = time.time() + 30
            by_node = {}
            while time.time() < deadline:
                summary = metrics_summary()
                by_node = summary.get("rt_workers_alive", {}).get(
                    "by_node", {}
                )
                if len(by_node) >= 2:
                    break
                time.sleep(0.5)
            assert len(by_node) >= 2, by_node
        finally:
            rt.shutdown()
    finally:
        cluster.shutdown()


def test_prometheus_endpoint_serves_core_series(rt_session):
    rt = rt_session
    import urllib.request

    from ray_tpu.dashboard import start_dashboard

    @rt.remote
    def touch():
        return 1

    rt.get(touch.remote())
    dashboard = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{dashboard.port}/metrics", timeout=30
        ) as resp:
            text = resp.read().decode()
    finally:
        dashboard.stop()
    assert "# TYPE rt_tasks_finished_total counter" in text
    assert "# HELP rt_tasks_finished_total" in text
    assert 'rt_workers_alive{node="' in text
