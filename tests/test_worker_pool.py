"""Worker pool lifecycle: prestart warms the first task, idle reaping
shrinks a burst-inflated pool back to the cap (reference:
worker_pool.cc PrestartWorkers / TryKillingIdleWorkers)."""

import time

import pytest

import ray_tpu as rt


def test_burst_pool_shrinks_to_idle_cap():
    rt.init(
        num_cpus=8,
        _system_config={
            "worker_pool_max_idle_workers": 2,
            "object_eviction_check_interval_s": 0.2,
        },
    )
    try:
        daemon = rt.api._session.daemon
        # Shorten the grace so the test doesn't idle for 5s.
        daemon._IDLE_WORKER_GRACE_S = 0.5

        @rt.remote
        def burst(i):
            time.sleep(0.2)
            return i

        # Saturate: forces ~8 concurrent workers.
        assert sorted(
            rt.get([burst.remote(i) for i in range(16)], timeout=60)
        ) == list(range(16))
        peak = len(daemon.workers)
        assert peak >= 4, f"burst should have inflated the pool ({peak})"

        deadline = time.time() + 15
        while time.time() < deadline:
            if len(daemon.workers) <= 2:
                break
            time.sleep(0.2)
        assert len(daemon.workers) <= 2, (
            f"idle pool must shrink to the cap, still {len(daemon.workers)}"
        )

        # The shrunken pool still serves work.
        assert rt.get(burst.remote(99), timeout=30) == 99
    finally:
        rt.shutdown()


def test_actor_pinned_workers_never_reaped():
    rt.init(
        num_cpus=4,
        _system_config={
            "worker_pool_max_idle_workers": 1,
            "object_eviction_check_interval_s": 0.2,
        },
    )
    try:
        daemon = rt.api._session.daemon
        daemon._IDLE_WORKER_GRACE_S = 0.3

        @rt.remote
        class Keeper:
            def ping(self):
                return "alive"

        keepers = [Keeper.remote() for _ in range(3)]
        assert rt.get(
            [k.ping.remote() for k in keepers], timeout=30
        ) == ["alive"] * 3
        time.sleep(2.0)  # several reap cycles
        assert rt.get(
            [k.ping.remote() for k in keepers], timeout=30
        ) == ["alive"] * 3
    finally:
        rt.shutdown()
