"""Worker pool lifecycle: prestart warms the first task, idle reaping
shrinks a burst-inflated pool back to the cap (reference:
worker_pool.cc PrestartWorkers / TryKillingIdleWorkers)."""

import time

import pytest

import ray_tpu as rt


def test_burst_pool_shrinks_to_idle_cap():
    rt.init(
        num_cpus=8,
        _system_config={
            "worker_pool_max_idle_workers": 2,
            "object_eviction_check_interval_s": 0.2,
        },
    )
    try:
        daemon = rt.api._session.daemon
        # Shorten the grace so the test doesn't idle for 5s.
        daemon._IDLE_WORKER_GRACE_S = 0.5

        @rt.remote
        def burst(i):
            time.sleep(0.2)
            return i

        # Saturate: forces ~8 concurrent workers.
        assert sorted(
            rt.get([burst.remote(i) for i in range(16)], timeout=60)
        ) == list(range(16))
        peak = len(daemon.workers)
        assert peak >= 4, f"burst should have inflated the pool ({peak})"

        deadline = time.time() + 15
        while time.time() < deadline:
            if len(daemon.workers) <= 2:
                break
            time.sleep(0.2)
        assert len(daemon.workers) <= 2, (
            f"idle pool must shrink to the cap, still {len(daemon.workers)}"
        )

        # The shrunken pool still serves work.
        assert rt.get(burst.remote(99), timeout=30) == 99
    finally:
        rt.shutdown()


def test_actor_pinned_workers_never_reaped():
    rt.init(
        num_cpus=4,
        _system_config={
            "worker_pool_max_idle_workers": 1,
            "object_eviction_check_interval_s": 0.2,
        },
    )
    try:
        daemon = rt.api._session.daemon
        daemon._IDLE_WORKER_GRACE_S = 0.3

        @rt.remote
        class Keeper:
            def ping(self):
                return "alive"

        keepers = [Keeper.remote() for _ in range(3)]
        assert rt.get(
            [k.ping.remote() for k in keepers], timeout=30
        ) == ["alive"] * 3
        time.sleep(2.0)  # several reap cycles
        assert rt.get(
            [k.ping.remote() for k in keepers], timeout=30
        ) == ["alive"] * 3
    finally:
        rt.shutdown()


def test_zero_cpu_actors_pack_past_worker_cap():
    """An EXPLICIT num_cpus=0 actor requests {} — any number of them
    pack onto a node, each on a DEDICATED worker past the task-pool
    cap (reference: ray_option_utils.py num_cpus=0 actors; worker_pool
    starts one process per actor, bounded only by startup
    concurrency). Regression: `resources or {"CPU": 1.0}` turned the
    empty request back into 1 CPU and the pool cap deadlocked the
    creations."""
    rt.init(num_cpus=1, _system_config={"max_workers_per_node": 2})
    try:
        @rt.remote(num_cpus=0)
        class Slot:
            def pid(self):
                import os

                return os.getpid()

        # 6 actors on a 1-CPU node with a 2-worker task cap: only
        # possible if creations bypass the cap with dedicated workers.
        actors = [Slot.remote() for _ in range(6)]
        pids = rt.get([a.pid.remote() for a in actors], timeout=90)
        assert len(set(pids)) == 6

        # Pinned actor workers must not count against the task-pool
        # cap: a plain task still gets a worker spawned for it.
        @rt.remote
        def plain():
            return 42

        assert rt.get(plain.remote(), timeout=60) == 42
    finally:
        rt.shutdown()


def test_forked_proc_detects_recycled_pid():
    """ForkedProc.poll() must not trust a bare signal-0 probe: the
    fork-server reaps children immediately, so an exited worker's pid
    can be recycled by an unrelated process. Liveness requires the
    /proc starttime captured at fork to still match; a mismatch (here
    simulated by tampering the captured value against a live pid)
    reads as dead, and terminate()/kill() then refuse to signal the
    innocent holder of the recycled pid."""
    import os

    from ray_tpu._private.worker_forkserver import (
        ForkedProc,
        _proc_starttime,
    )

    me = os.getpid()
    mine = _proc_starttime(me)
    assert mine is not None
    live = ForkedProc(me, mine)
    assert live.poll() is None  # genuinely alive, starttime matches

    recycled = ForkedProc(me, mine - 1)  # pretend an older child
    assert recycled.poll() == 0
    recycled.kill()  # must be a no-op, not SIGKILL to ourselves
    assert os.getpid() == me

    # Template's reaper won the race: starttime arrives as None and
    # the handle reads dead without trusting the pid at all.
    assert ForkedProc(me, None).poll() == 0

    gone = ForkedProc(2**22 - 17, 123)  # vanishingly unlikely to exist
    assert gone.poll() == 0


def test_default_actors_exceed_node_cpus():
    """Default actors need 1 CPU to *schedule* but hold 0 for their
    lifetime (reference: DEFAULT_ACTOR_CREATION_CPU_SIMPLE=0 — the
    1 CPU is placement-only and released after scheduling), so more
    default actors than node CPUs still all come up. Regression:
    holding the creation CPU for the lifetime queued the third actor
    forever on a 2-CPU node with no error."""
    rt.init(num_cpus=2)
    try:
        @rt.remote
        class A:
            def ping(self):
                return "up"

        actors = [A.remote() for _ in range(5)]
        assert rt.get(
            [a.ping.remote() for a in actors], timeout=90
        ) == ["up"] * 5

        # The released CPUs are genuinely back: plain 1-CPU tasks
        # still run while all five actors are alive.
        @rt.remote
        def f():
            return 7

        assert rt.get([f.remote() for _ in range(4)], timeout=60) == [7] * 4

        # EXPLICIT num_cpus keeps lifetime-hold semantics: a sixth
        # actor demanding 2 full CPUs schedules too (the default
        # actors freed theirs), and holds them.
        @rt.remote(num_cpus=2)
        class Holder:
            def ping(self):
                return "held"

        h = Holder.remote()
        assert rt.get(h.ping.remote(), timeout=60) == "held"
    finally:
        rt.shutdown()


def test_fork_server_spawns_workers():
    """Workers come from the warm fork-server template by default;
    they must execute tasks and report distinct pids (the template's
    children, not the daemon's)."""
    rt.init(num_cpus=4)
    try:
        daemon = rt.api._session.daemon
        assert daemon._fork_server is not None

        @rt.remote
        def whoami():
            import os

            return os.getpid(), os.getppid()

        pid, ppid = rt.get(whoami.remote(), timeout=60)
        assert pid != ppid
        # The worker's parent is the fork-server template, not the
        # daemon's own process.
        import os as _os

        assert ppid != _os.getpid()
    finally:
        rt.shutdown()


def test_spawn_watcher_judgment():
    """The spawn watcher must count a worker that dies before EVER
    registering as a startup crash, but must NOT count a fast
    register→work→exit lifecycle (short trial, idle reap) — judging by
    the live workers dict alone miscounted healthy short-lived workers
    whenever the watcher thread was starved past their whole lifetime
    (observed: TPE trials under heavy box load)."""
    rt.init(num_cpus=2)
    try:
        daemon = rt.api._session.daemon

        class FakeProc:
            def __init__(self, pid, rc):
                self.pid = pid
                self._rc = rc

            def poll(self):
                return self._rc

        base = daemon._spawn_crash_total

        # Registered-then-exited: pid is in the history set even
        # though it is long gone from daemon.workers.
        reg_pid = 2**22 - 101
        with daemon._lock:
            daemon._registered_pids_ever.add(reg_pid)
        daemon._watch_worker_start(FakeProc(reg_pid, 0))

        # Never-registered exit: a genuine startup crash.
        daemon._watch_worker_start(FakeProc(2**22 - 103, 1))

        deadline = time.time() + 15
        while time.time() < deadline:
            if daemon._spawn_crash_total > base:
                break
            time.sleep(0.1)
        assert daemon._spawn_crash_total == base + 1, (
            "exactly the unregistered exit must count as a crash"
        )
        # Counter hygiene for the session fixture's zero assertion.
        daemon._spawn_crash_total = base
        with daemon._lock:
            daemon._registered_pids_ever.discard(reg_pid)
    finally:
        rt.shutdown()
