"""Head (GCS) fault tolerance: control-plane state survives a head
crash via the session op log; worker nodes resync with the restarted
head and actors hosted on them stay callable.

Reference behavior matched: GCS persistence through a store client
(src/ray/gcs/store_client/redis_store_client.h) + raylet resync on
head restart (src/ray/raylet/node_manager.cc:1189
HandleNotifyGCSRestart)."""

import time

import pytest


@pytest.fixture(params=["unix", "tcp"])
def ft_cluster(request):
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        initialize_head=True,
        # Keep the head compute-free so all actors/tasks land on the
        # worker node (which must survive the head crash).
        head_resources={"CPU": 0.0},
        use_tcp=(request.param == "tcp"),
    )
    yield c
    c.shutdown()


def test_head_restart_recovers_state(ft_cluster):
    import ray_tpu as rt

    c = ft_cluster
    c.add_node(num_cpus=2)
    rt.init(address=c.address)
    try:

        @rt.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def add(self, x):
                self.n += x
                return self.n

        counter = Counter.options(name="survivor").remote()
        assert rt.get(counter.add.remote(5), timeout=60) == 5
    finally:
        rt.shutdown()

    # --- head crashes; the worker node (hosting the actor) survives.
    c.crash_head()
    time.sleep(0.3)
    c.restart_head()
    # Worker node's heartbeat loop re-registers + resyncs.
    c.wait_for_nodes(2, timeout=30)

    rt.init(address=c.address)
    try:
        # Named actor resolvable from the replayed control tables and
        # the node resync, with its in-memory state intact.
        survivor = rt.get_actor("survivor")
        assert rt.get(survivor.add.remote(1), timeout=60) == 6

        # KV (exported function defs) replayed: new tasks run too.
        @rt.remote
        def f(x):
            return x * 2

        assert rt.get(f.remote(21), timeout=60) == 42
    finally:
        rt.shutdown()


def test_oplog_replay_tables(tmp_path):
    """StateLog + ControlState restore round-trip, including a torn
    tail frame (crash mid-write)."""
    from ray_tpu._private.gcs import (
        ACTOR_ALIVE,
        ActorInfo,
        ControlState,
        JobInfo,
        StateLog,
    )
    from ray_tpu._private.ids import ActorID, JobID

    path = str(tmp_path / "oplog.bin")
    state = ControlState(log=StateLog(path))
    state.kv_put("ns", "k1", b"v1")
    state.kv_put("ns", "k2", b"v2")
    state.kv_del("ns", "k2")
    job_id = state.next_job_id()
    state.add_job(JobInfo(job_id=job_id, driver_pid=1, start_time=0.0))
    actor_id = ActorID(b"a" * ActorID.SIZE)
    state.register_actor(
        ActorInfo(
            actor_id=actor_id,
            name="named",
            namespace="default",
            state=ACTOR_ALIVE,
            class_name="C",
        )
    )
    state.log.close()

    # Torn tail: simulate a crash mid-append.
    with open(path, "ab") as f:
        f.write(b"\x00\x00\x01\x00garbage")

    restored = ControlState()
    restored.restore(StateLog.replay(path))
    assert restored.kv_get("ns", "k1") == b"v1"
    assert restored.kv_get("ns", "k2") is None
    assert job_id in restored.jobs
    info = restored.get_named_actor("default", "named")
    assert info is not None and info.actor_id == actor_id
    # Job counter resumes past replayed ids.
    assert restored.next_job_id().binary() != job_id.binary()
