"""Generator returns (num_returns="dynamic"/"streaming") and
concurrent actors (max_concurrency, async methods).

Reference behavior matched: python/ray/remote_function.py:385-391
(dynamic/streaming num_returns), python/ray/_raylet.pyx:269
(ObjectRefGenerator), src/ray/core_worker/transport/
concurrency_group_manager.h (threaded/async actors)."""

import time

import pytest


def test_dynamic_generator(rt_session):
    rt = rt_session

    @rt.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * 10

    ref = gen.remote(5)
    g = rt.get(ref, timeout=20)
    assert isinstance(g, rt.ObjectRefGenerator)
    assert [rt.get(r, timeout=10) for r in g] == [0, 10, 20, 30, 40]


def test_streaming_generator_incremental(rt_session):
    rt = rt_session

    @rt.remote
    def warm():
        return None

    rt.get(warm.remote(), timeout=30)  # pay worker spawn outside timing

    @rt.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            time.sleep(0.05)
            yield i

    t0 = time.monotonic()
    first_at = None
    got = []
    for r in gen.remote(4):
        got.append(rt.get(r, timeout=10))
        if first_at is None:
            first_at = time.monotonic() - t0
    assert got == [0, 1, 2, 3]
    # First item arrives while the task is still producing.
    assert first_at < 0.15, first_at


def test_streaming_generator_empty_and_error(rt_session):
    rt = rt_session

    @rt.remote(num_returns="streaming")
    def empty():
        return iter(())

    assert list(empty.remote()) == []

    @rt.remote(num_returns="streaming")
    def boom():
        yield 1
        raise ValueError("midstream")

    it = iter(boom.remote())
    assert rt.get(next(it), timeout=10) == 1
    with pytest.raises(ValueError, match="midstream"):
        for r in it:
            rt.get(r, timeout=10)


def test_streaming_non_generator_rejected(rt_session):
    rt = rt_session

    @rt.remote(num_returns="dynamic")
    def not_gen():
        return 42

    with pytest.raises(TypeError, match="generator"):
        rt.get(rt.get(not_gen.remote(), timeout=10))

    with pytest.raises(ValueError, match="num_returns"):

        @rt.remote(num_returns="bogus")  # rt: noqa[RT102] — deliberate bad literal under test
        def bad():
            yield 1

        bad.remote()  # rt: noqa[RT106] — submit raises; no ref exists


def test_actor_streaming_method(rt_session):
    rt = rt_session

    @rt.remote
    class Tok:
        def tokens(self, n):
            for i in range(n):
                yield f"tok{i}"

    a = Tok.remote()
    out = [
        rt.get(r, timeout=10)
        for r in a.tokens.options(num_returns="streaming").remote(3)
    ]
    assert out == ["tok0", "tok1", "tok2"]


def test_threaded_actor_concurrency(rt_session):
    rt = rt_session

    @rt.remote(max_concurrency=4)
    class Par:
        def work(self, t):
            time.sleep(t)
            return t

    a = Par.remote()
    rt.get(a.work.remote(0.01), timeout=30)  # warm
    t0 = time.monotonic()
    rt.get([a.work.remote(0.3) for _ in range(4)], timeout=30)
    assert time.monotonic() - t0 < 0.9  # concurrent, not 1.2s serial


def test_async_actor_methods(rt_session):
    rt = rt_session

    @rt.remote(max_concurrency=4)
    class Async:
        async def sleepy(self, t):
            import asyncio

            await asyncio.sleep(t)
            return t

        async def add(self, a, b):
            return a + b

    a = Async.remote()
    assert rt.get(a.add.remote(2, 3), timeout=30) == 5
    t0 = time.monotonic()
    out = rt.get([a.sleepy.remote(0.3) for _ in range(4)], timeout=30)
    assert out == [0.3] * 4
    assert time.monotonic() - t0 < 0.9


def test_serial_actor_stays_serial(rt_session):
    rt = rt_session

    @rt.remote
    class Serial:
        def __init__(self):
            self.active = 0
            self.max_active = 0

        def work(self):
            self.active += 1
            self.max_active = max(self.max_active, self.active)
            time.sleep(0.05)
            self.active -= 1
            return self.max_active

    a = Serial.remote()
    results = rt.get([a.work.remote() for _ in range(5)], timeout=30)
    assert max(results) == 1  # never interleaved
