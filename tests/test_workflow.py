"""Workflow tests (reference test model: python/ray/workflow/tests/ —
durable step results, failure + resume without re-executing finished
steps)."""

import os

import pytest


def test_workflow_runs_dag(rt_session, tmp_path):
    rt = rt_session
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), double.bind(inp))
    result = workflow.run(
        dag,
        workflow_id="wf1",
        input_value=5,
        storage=str(tmp_path),
    )
    assert result == 20
    assert workflow.get_status("wf1", storage=str(tmp_path)) == (
        workflow.STATUS_SUCCESSFUL
    )
    assert workflow.get_output("wf1", storage=str(tmp_path)) == 20
    assert [m["workflow_id"] for m in workflow.list_all(
        storage=str(tmp_path)
    )] == ["wf1"]


def test_workflow_failure_and_resume(rt_session, tmp_path):
    """Steps completed before a failure are NOT re-executed on resume
    (reference: workflow storage skip-if-done)."""
    rt = rt_session
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    marker = str(tmp_path / "executions")
    flag = str(tmp_path / "fail.flag")
    open(flag, "w").close()

    @rt.remote
    def counted(x):
        with open(marker, "a") as f:
            f.write("A")
        return x + 1

    @rt.remote
    def flaky(x):
        if os.path.exists(flag):
            raise RuntimeError("transient failure")
        return x * 100

    with InputNode() as inp:
        dag = flaky.bind(counted.bind(inp))

    with pytest.raises(Exception, match="transient"):
        workflow.run(
            dag,
            workflow_id="wf2",
            input_value=1,
            storage=str(tmp_path),
        )
    assert workflow.get_status("wf2", storage=str(tmp_path)) == (
        workflow.STATUS_FAILED
    )
    assert open(marker).read() == "A"  # first step ran once

    os.remove(flag)
    result = workflow.resume("wf2", storage=str(tmp_path))
    assert result == 200
    assert open(marker).read() == "A"  # still once: loaded from storage
    assert workflow.get_status("wf2", storage=str(tmp_path)) == (
        workflow.STATUS_SUCCESSFUL
    )
    # Resuming a finished workflow returns the stored output.
    assert workflow.resume("wf2", storage=str(tmp_path)) == 200


def test_workflow_with_input_projection(rt_session, tmp_path):
    """inp["key"] projections work in the workflow execution mode too
    (the third mode over the same DAG types)."""
    rt = rt_session
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    @rt.remote
    def double(x):
        return x * 2

    @rt.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(double.bind(inp["a"]), inp["b"])
    out = workflow.run(
        dag, input_value={"a": 4, "b": 1}, workflow_id="proj",
        storage=str(tmp_path),
    )
    assert out == 9
