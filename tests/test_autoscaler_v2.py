"""Autoscaler v2 tests.

Reference test model: autoscaler/v2 tests exercise the instance state
machine and Reconciler against fake providers and synthetic cluster
states (no cloud, no real nodes), plus one e2e pass against the
in-process fake multi-node cluster.
"""

import time

import pytest

from ray_tpu.autoscaler.autoscaler import NodeTypeConfig
from ray_tpu.autoscaler.v2.instance import (
    Instance,
    InstanceStatus as S,
    VALID_TRANSITIONS,
)
from ray_tpu.autoscaler.v2.instance_manager import (
    InstanceManager,
    InstanceUpdateEvent,
)
from ray_tpu.autoscaler.v2.reconciler import (
    CloudInstance,
    ProviderError,
    ReconcileConfig,
    Reconciler,
)


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------

def test_happy_path_transitions_recorded():
    inst = Instance(instance_type="cpu")
    for status in [
        S.REQUESTED,
        S.ALLOCATED,
        S.RAY_RUNNING,
        S.RAY_STOP_REQUESTED,
        S.RAY_STOPPING,
        S.RAY_STOPPED,
        S.TERMINATING,
        S.TERMINATED,
    ]:
        assert inst.transition(status), status
    assert [t.status for t in inst.history][0] == S.QUEUED
    assert inst.history[-1].status == S.TERMINATED
    assert len(inst.history) == 9


def test_invalid_transition_rejected_without_mutation():
    inst = Instance(instance_type="cpu")
    assert not inst.transition(S.RAY_RUNNING)  # QUEUED -/-> RUNNING
    assert inst.status == S.QUEUED
    assert len(inst.history) == 1
    # Terminal states go nowhere.
    inst.transition(S.REQUESTED)
    inst.transition(S.ALLOCATION_FAILED)
    for status in S:
        assert not inst.transition(status)


def test_transition_table_is_closed():
    """Every status appears as a key; every edge target is a status."""
    assert set(VALID_TRANSITIONS) == set(S)
    for targets in VALID_TRANSITIONS.values():
        assert targets <= set(S)


# ---------------------------------------------------------------------------
# instance manager (versioned updates)
# ---------------------------------------------------------------------------

def test_versioned_update_rejected_on_stale_version():
    im = InstanceManager()
    im.update(
        [InstanceUpdateEvent(instance_type="cpu", new_status=S.QUEUED)]
    )
    version, instances = im.get_state()
    (iid,) = instances
    # A write with the current version lands...
    assert im.update(
        [
            InstanceUpdateEvent(
                instance_id=iid, new_status=S.REQUESTED
            )
        ],
        expected_version=version,
    )
    # ...a second write computed against the same (now stale) version
    # is rejected wholesale.
    assert not im.update(
        [
            InstanceUpdateEvent(
                instance_id=iid, new_status=S.ALLOCATION_FAILED
            )
        ],
        expected_version=version,
    )
    assert im.instances()[0].status == S.REQUESTED


def test_subscriber_sees_each_applied_transition_once():
    im = InstanceManager()
    seen = []
    im.subscribe(lambda inst, ev: seen.append(ev.new_status))
    im.update(
        [InstanceUpdateEvent(instance_type="cpu", new_status=S.QUEUED)]
    )
    iid = im.instances()[0].instance_id
    im.update(
        [
            InstanceUpdateEvent(instance_id=iid, new_status=S.REQUESTED),
            # Invalid edge: dropped, not delivered.
            InstanceUpdateEvent(
                instance_id=iid, new_status=S.RAY_STOPPED
            ),
        ]
    )
    assert seen == [S.QUEUED, S.REQUESTED]


# ---------------------------------------------------------------------------
# reconciler against synthetic reality
# ---------------------------------------------------------------------------

TYPES = {
    "cpu": NodeTypeConfig(
        resources={"CPU": 2.0}, min_workers=0, max_workers=4
    ),
    "v5e-16": NodeTypeConfig(
        resources={"CPU": 1.0, "TPU": 4.0},
        min_workers=0,
        max_workers=2,
        slice_hosts=4,
    ),
}


def _empty_load(nodes=None, infeasible=None, pgs=None):
    return {
        "nodes": nodes or [],
        "infeasible": infeasible or [],
        "pending_placement_groups": pgs or [],
    }


def _reconcile(im, cloud=None, load=None, errors=None, cfg=None):
    return Reconciler.reconcile(
        im,
        node_types=TYPES,
        cloud_instances=cloud or {},
        load=load or _empty_load(),
        config=cfg or ReconcileConfig(idle_timeout_s=0.2),
        provider_errors=errors,
    )


def test_demand_queues_then_requests_instance():
    im = InstanceManager()
    _reconcile(im, load=_empty_load(infeasible=[{"CPU": 2.0}]))
    (inst,) = im.instances()
    assert inst.instance_type == "cpu"
    assert inst.status == S.QUEUED
    # Next pass hands it a launch slot.
    _reconcile(im, load=_empty_load(infeasible=[{"CPU": 2.0}]))
    assert im.instances()[0].status == S.REQUESTED
    # Demand already covered by the pending instance: no extras.
    assert len(im.instances()) == 1


def test_full_lifecycle_to_running_and_idle_scale_down():
    im = InstanceManager()

    # Stopper subscriber: acknowledge drain immediately (what
    # AutoscalerV2._on_update does for providers with no drain API).
    def stopper(inst, ev):
        if ev.new_status == S.RAY_STOP_REQUESTED:
            im.update(
                [
                    InstanceUpdateEvent(
                        instance_id=inst.instance_id,
                        new_status=S.RAY_STOPPING,
                        details="drain acknowledged",
                    )
                ]
            )

    im.subscribe(stopper)
    _reconcile(im, load=_empty_load(infeasible=[{"CPU": 2.0}]))
    _reconcile(im, load=_empty_load(infeasible=[{"CPU": 2.0}]))
    (inst,) = im.instances()

    # Cloud instance appears, tagged with our instance id.
    cloud = {
        "gce-1": CloudInstance("gce-1", "cpu", inst.instance_id)
    }
    _reconcile(im, cloud=cloud)
    assert inst.status == S.ALLOCATED
    assert inst.cloud_instance_id == "gce-1"

    # Daemon registers with the head -> RAY_RUNNING with node ids.
    node = {
        "node_id": "abc123",
        "labels": {"rt.io/provider-node": "gce-1"},
        "available": {"CPU": 2.0},
        "total": {"CPU": 2.0},
        "queued": 0,
    }
    _reconcile(im, cloud=cloud, load=_empty_load(nodes=[node]))
    assert inst.status == S.RAY_RUNNING
    assert inst.node_ids == ["abc123"]

    # Busy node never scales down...
    busy = dict(node, available={"CPU": 0.0})
    time.sleep(0.25)
    _reconcile(im, cloud=cloud, load=_empty_load(nodes=[busy]))
    assert inst.status == S.RAY_RUNNING
    # ...idle past the timeout drains then reclaims.
    time.sleep(0.25)
    _reconcile(im, cloud=cloud, load=_empty_load(nodes=[node]))
    assert inst.status == S.RAY_STOPPING  # stop ack'd by subscriber
    _reconcile(im, cloud=cloud, load=_empty_load(nodes=[node]))
    assert inst.status == S.TERMINATING
    # Provider drops it -> TERMINATED.
    _reconcile(im, cloud={}, load=_empty_load())
    assert inst.status == S.TERMINATED


def test_launch_timeout_retries_then_fails():
    im = InstanceManager()
    cfg = ReconcileConfig(
        request_timeout_s=0.0, max_launch_attempts=2
    )
    im.update(
        [InstanceUpdateEvent(instance_type="cpu", new_status=S.QUEUED)]
    )
    _reconcile(im, cfg=cfg)  # QUEUED -> REQUESTED
    (inst,) = im.instances()
    inst.launch_attempts = 1
    assert inst.status == S.REQUESTED
    _reconcile(im, cfg=cfg)  # timeout -> back to QUEUED
    assert inst.status == S.QUEUED
    _reconcile(im, cfg=cfg)  # retry -> REQUESTED
    inst.launch_attempts = 2
    assert inst.status == S.REQUESTED
    _reconcile(im, cfg=cfg)  # attempts exhausted
    assert inst.status == S.ALLOCATION_FAILED


def test_launch_error_surfaces_as_retry():
    im = InstanceManager()
    im.update(
        [InstanceUpdateEvent(instance_type="cpu", new_status=S.QUEUED)]
    )
    _reconcile(im)
    (inst,) = im.instances()
    inst.launch_attempts = 1
    _reconcile(
        im,
        errors=[
            ProviderError(
                kind="launch",
                instance_id=inst.instance_id,
                details="quota",
            )
        ],
    )
    assert inst.status == S.QUEUED
    assert "quota" in inst.history[-1].details


def test_vanished_cloud_instance_marks_terminated():
    im = InstanceManager()
    im.update(
        [InstanceUpdateEvent(instance_type="cpu", new_status=S.QUEUED)]
    )
    (inst,) = im.instances()
    inst.transition(S.REQUESTED)
    inst.transition(S.ALLOCATED)
    inst.cloud_instance_id = "gce-9"
    inst.transition(S.RAY_RUNNING)
    _reconcile(im, cloud={})  # preempted / crashed
    assert inst.status == S.TERMINATED


def test_leaked_cloud_instance_reported():
    im = InstanceManager()
    result = _reconcile(
        im, cloud={"mystery": CloudInstance("mystery", "cpu")}
    )
    assert result["leaked"] == ["mystery"]


def test_gang_demand_launches_one_slice_instance():
    """A 4-bundle STRICT_SPREAD TPU gang becomes ONE v5e-16 instance
    (slice-granular scale-up), not four."""
    im = InstanceManager()
    pg = {
        "strategy": "STRICT_SPREAD",
        "bundles": [{"TPU": 4.0}] * 4,
    }
    _reconcile(im, load=_empty_load(pgs=[pg]))
    insts = im.instances()
    assert len(insts) == 1
    assert insts[0].instance_type == "v5e-16"


def test_min_workers_floor_maintained():
    im = InstanceManager()
    types = {
        "cpu": NodeTypeConfig(
            resources={"CPU": 2.0}, min_workers=2, max_workers=4
        )
    }
    Reconciler.reconcile(
        im,
        node_types=types,
        cloud_instances={},
        load=_empty_load(),
        config=ReconcileConfig(),
    )
    assert len(im.instances()) == 2
    # Floor already satisfied by active instances: stable.
    Reconciler.reconcile(
        im,
        node_types=types,
        cloud_instances={},
        load=_empty_load(),
        config=ReconcileConfig(),
    )
    assert len(im.instances()) == 2


# ---------------------------------------------------------------------------
# e2e against the in-process fake cluster
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_v2_scales_up_and_down_e2e():
    import ray_tpu as rt
    from ray_tpu.autoscaler.v2 import AutoscalingClusterV2

    cluster = AutoscalingClusterV2(
        head_resources={"CPU": 1.0},
        worker_node_types={
            "cpu-worker": {
                "resources": {"CPU": 2.0, "memory": float(2**30)},
                "min_workers": 0,
                "max_workers": 2,
            },
        },
        idle_timeout_s=2.0,
    )
    cluster.start()
    try:
        rt.init(address=cluster.address)
        try:

            @rt.remote(num_cpus=2)
            def heavy():
                return "ran"

            assert rt.get(heavy.remote(), timeout=90) == "ran"
            assert cluster.num_workers() >= 1
            # RAY_RUNNING lands on the reconcile pass AFTER the
            # daemon registers; poll briefly.
            deadline = time.time() + 15
            statuses: set = set()
            while time.time() < deadline:
                statuses = {
                    s["status"]
                    for s in cluster.autoscaler.summary()
                }
                if "RAY_RUNNING" in statuses:
                    break
                time.sleep(0.2)
            assert "RAY_RUNNING" in statuses, statuses

            deadline = time.time() + 45
            while (
                time.time() < deadline
                and cluster.num_workers() > 0
            ):
                time.sleep(0.3)
            assert cluster.num_workers() == 0
            # The instance record survives with a full audit trail;
            # TERMINATED lands on the pass after the provider list
            # empties.
            deadline = time.time() + 15
            trail: list = []
            while time.time() < deadline:
                trail = cluster.autoscaler.summary()[0][
                    "transitions"
                ]
                if trail[-1]["status"] == "TERMINATED":
                    break
                time.sleep(0.2)
            assert [t["status"] for t in trail][-1] == "TERMINATED"
        finally:
            rt.shutdown()
    finally:
        cluster.shutdown()
