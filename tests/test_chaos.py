"""Fault-injection tests: the RT_testing_rpc_failure chaos hook
(reference: rpc_chaos.h + python/ray/tests/test_network_failure_e2e —
inject RPC drops on the object-transfer plane and assert the workload
still converges through the retry machinery).

The hook (_private/rpc.py configure_chaos) drops the first N calls of
a named RPC method at the client side. These tests aim it at the
pull/push object-transfer methods (`pull_object` chunk requests and
the `get_object_meta` lookups that precede them) while running a
task + put/get workload across a two-node cluster: every injected
drop must be absorbed by a retry, never surfacing to the user or
corrupting data.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def _shared_cluster():
    """One two-node cluster for the whole module: cluster boot is the
    dominant cost of these tests, and chaos state is reset around each
    test (see chaos_cluster) so sharing is safe."""
    from ray_tpu.cluster_utils import Cluster

    import ray_tpu as rt

    c = Cluster(
        initialize_head=True,
        head_resources={"CPU": 2.0},
        # Fast ledger ticks so the kill-pin-holder test observes
        # attribution drop within its patience.
        system_config={"memory_report_interval_s": 0.2},
    )
    c.add_node(num_cpus=2, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:
        yield rt, c
    finally:
        rt.shutdown()
        c.shutdown()


@pytest.fixture
def chaos_cluster(_shared_cluster):
    from ray_tpu._private.rpc import configure_chaos

    configure_chaos("")  # never inherit budgets from a prior test
    try:
        yield _shared_cluster
    finally:
        configure_chaos("")  # never leak budgets into other tests


def test_cross_node_get_converges_under_pull_chaos(chaos_cluster):
    """Driver-side get of a remotely produced object while the first
    pull_object chunk RPCs are chaos-dropped: the pull retry loop
    (daemon._pull_once x5 attempts) must converge to the right
    bytes."""
    from ray_tpu._private.rpc import configure_chaos

    rt, _ = chaos_cluster

    @rt.remote(resources={"remote_node": 1.0})
    def produce():
        return np.arange(1_000_000, dtype=np.int64)  # 8 MB: 2 chunks

    ref = produce.remote()
    # Arm chaos only once the task path has settled, so the drops hit
    # the object-transfer plane, not task submission.
    configure_chaos("pull_object=3")
    out = rt.get(ref, timeout=90)
    np.testing.assert_array_equal(out, np.arange(1_000_000, dtype=np.int64))


def test_task_workload_converges_under_pull_and_meta_chaos(chaos_cluster):
    """put/get + task round trip with chaos on BOTH transfer-plane
    methods: the remote task pulls the driver's put object (its
    get_object_meta and pull_object calls eat the injected failures),
    computes, and the driver pulls the result back."""
    from ray_tpu._private.rpc import configure_chaos

    rt, _ = chaos_cluster

    payload = np.ones(600_000, dtype=np.float64)  # ~4.8 MB, not inline

    @rt.remote(resources={"remote_node": 1.0})
    def consume(x):
        return float(x.sum())

    # Warm one round trip so worker spawn is out of the chaos window.
    assert rt.get(consume.remote(payload), timeout=90) == 600_000.0

    configure_chaos("pull_object=4,get_object_meta=2")
    refs = [rt.get(rt.put(payload), timeout=60) for _ in range(2)]
    for got in refs:
        assert got.shape == payload.shape
    total = rt.get(consume.remote(rt.put(2.0 * payload)), timeout=90)
    assert total == 2.0 * 600_000.0


def test_metrics_and_flight_recorder_survive_rpc_chaos(chaos_cluster):
    """Observability under faults (satellite): chaos-drop the first
    metrics_record flushes — the flusher must requeue the batch and
    deliver it on a later tick (never wedging, never dropping), and
    the flight recorder must keep recording throughout."""
    import time

    from ray_tpu._private.flight_recorder import recorder
    from ray_tpu._private.rpc import configure_chaos
    from ray_tpu.util.metrics import Counter, metrics_summary

    rt, _ = chaos_cluster
    counter = Counter("chaos_survivor")
    counter.inc(1.0)
    counter.inc(2.0)
    configure_chaos("metrics_record=2")
    # The background flusher eats the injected failures (requeue +
    # warn-once) and converges once the budget is spent.
    deadline = time.time() + 30
    total = None
    while time.time() < deadline:
        try:
            total = (
                metrics_summary()
                .get("chaos_survivor", {})
                .get("total")
            )
        except Exception:
            # metrics_summary force-flushes; while the chaos budget
            # lasts, the explicit flush path is allowed to raise.
            total = None
        if total == 3.0:
            break
        time.sleep(0.3)
    assert total == 3.0
    # The flusher thread survived the outage and keeps delivering.
    from ray_tpu.util.metrics import _Buffer

    assert _Buffer.get().thread.is_alive()
    counter.inc(4.0)
    deadline = time.time() + 15
    while time.time() < deadline:
        if (
            metrics_summary()["chaos_survivor"]["total"] == 7.0
        ):
            break
        time.sleep(0.3)
    assert metrics_summary()["chaos_survivor"]["total"] == 7.0
    # The driver's flight-recorder ring recorded client RPCs through
    # the whole episode (the successful retry among them).
    assert any(
        r["kind"] == "rpc.client" and r["name"] == "metrics_record"
        for r in recorder().snapshot()
    )


def test_batch_submit_exactly_once_under_chaos(chaos_cluster, tmp_path):
    """Batched task submission under injected frame drops: a dropped
    execute_tasks batch resends without re-executing (the drop fires
    before any bytes hit the wire), and every task's side effect lands
    exactly once with per-spec results intact."""
    import os

    from ray_tpu._private.rpc import configure_chaos

    rt, _ = chaos_cluster
    marker_dir = str(tmp_path)

    @rt.remote
    def touch(i):
        with open(os.path.join(marker_dir, f"{i}.txt"), "a") as f:
            f.write("x\n")
        return i

    assert rt.get(touch.remote(999), timeout=90) == 999
    configure_chaos("execute_tasks=2")
    refs = [touch.remote(i) for i in range(50)]
    assert rt.get(refs, timeout=120) == list(range(50))
    for i in range(50):
        with open(os.path.join(marker_dir, f"{i}.txt")) as f:
            assert len(f.readlines()) == 1, f"task {i} re-executed"


def test_kill_of_pin_holding_worker_frees_pins_and_attribution(
    chaos_cluster,
):
    """ISSUE 14 satellite: `rt.kill` of a worker holding zero-copy
    arena pins must not leak the slots — the daemon's dead-reader
    reap reclaims them, the object becomes deletable, and the memory
    ledger drops the dead owner's attribution once the bytes are
    gone."""
    import time

    rt, c = chaos_cluster
    remote_daemon = c.nodes[0]
    baseline_used = remote_daemon.store.size_info()["used"]

    @rt.remote(resources={"remote_node": 1.0})
    class PinHolder:
        def pin(self, data):
            # The resolved arg is a zero-copy view of the pulled
            # arena copy — holding it keeps an arena reader pin
            # alive in THIS worker process.
            self.view = data
            return int(data.nbytes)

    payload = np.ones(600_000, dtype=np.float64)  # 4.8 MB
    ref = rt.put(payload)
    holder = PinHolder.remote()
    assert rt.get(holder.pin.remote(ref), timeout=90) == payload.nbytes
    oid = ref.hex()
    from ray_tpu.util.state import list_objects

    assert any(r["object_id"] == oid for r in list_objects())
    rt.kill(holder, no_restart=True)
    # Drop the driver's ref: with the dead holder's pin reaped (the
    # daemon's dead-reader bookkeeping), the delete completes on
    # every node and the arena slots free; a leaked pin would defer
    # the remote deletion forever.
    del ref
    from ray_tpu._private.worker import global_worker

    global_worker().flush_pending_dels()
    deadline = time.time() + 45
    while time.time() < deadline:
        from ray_tpu._private.ids import ObjectID

        gone = ObjectID(bytes.fromhex(oid)) not in remote_daemon.objects
        used = remote_daemon.store.size_info()["used"]
        if gone and used <= baseline_used:
            break
        time.sleep(0.3)
    assert used <= baseline_used, (used, baseline_used)
    assert gone
    # The ledger's state view dropped the object with the bytes.
    assert not any(r["object_id"] == oid for r in list_objects())


def test_pulled_copy_attributed_on_consumer_node(chaos_cluster):
    """A secondary copy pulled to a consumer node fills THAT node's
    arena: the pull must carry the owner from the head's meta so the
    consumer node's memory report attributes the bytes too (without
    it, cross-node consumption tanks cluster attribution_fraction
    below the >=95% bar and the README runbook misdirects)."""
    import time

    rt, c = chaos_cluster
    remote_daemon = c.nodes[0]
    payload = np.ones(500_000, dtype=np.float64)  # 4 MB
    ref = rt.put(payload)  # primary lands on the head node

    @rt.remote(resources={"remote_node": 1.0})
    class Consumer:
        def consume(self, data):
            self.view = data  # hold: the pulled copy stays resident
            return int(data.nbytes)

    consumer = Consumer.remote()
    assert (
        rt.get(consumer.consume.remote(ref), timeout=90)
        == payload.nbytes
    )
    node_hex = remote_daemon.node_id.hex()
    from ray_tpu.util.state import memory_summary

    deadline = time.time() + 30
    report = None
    while time.time() < deadline:
        reports = {
            n["node"]: n for n in memory_summary()["nodes"]
        }
        report = reports.get(node_hex)
        if report and report["attributed_bytes"] >= payload.nbytes:
            break
        time.sleep(0.3)
    assert report is not None, "consumer node never reported"
    # The pulled copy is attributed to the driver's (job, owner) —
    # first writer wins, the consumer doesn't re-own it.
    owners = report["owners"]
    assert any(
        row["owner"] == "driver"
        and row["bytes"] >= payload.nbytes
        for row in owners.values()
    ), owners
    assert report["attribution_fraction"] >= 0.95, report
    del ref, consumer


def test_chaos_budget_is_finite_and_clears():
    """The spec drops exactly the first N calls: once the budget is
    consumed, the method flows normally again (budget bookkeeping in
    configure_chaos/_chaos_should_fail). Pure bookkeeping — no
    cluster needed."""
    from ray_tpu._private.rpc import _chaos_should_fail, configure_chaos

    try:
        configure_chaos("some_method=2")
        assert _chaos_should_fail("some_method")
        assert _chaos_should_fail("some_method")
        assert not _chaos_should_fail("some_method")
        assert not _chaos_should_fail("other_method")
    finally:
        configure_chaos("")
    assert not _chaos_should_fail("some_method")
