"""Core task/actor/object API tests (modeled on the reference's
python/ray/tests/test_basic*.py / test_actor*.py coverage)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import exceptions as exc


@pytest.fixture(autouse=True)
def _session():
    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield
    rt.shutdown()


def test_simple_task():
    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(1, 2)) == 3


def test_task_kwargs_and_closure():
    base = 100

    @rt.remote
    def f(a, b=10):
        return a + b + base

    assert rt.get(f.remote(1)) == 111
    assert rt.get(f.remote(1, b=20)) == 121


def test_many_tasks():
    @rt.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(50)]
    assert rt.get(refs) == [i * i for i in range(50)]


def test_put_get_roundtrip_small():
    ref = rt.put({"a": [1, 2, 3], "b": "hello"})
    assert rt.get(ref) == {"a": [1, 2, 3], "b": "hello"}


def test_put_get_large_numpy_zero_copy():
    arr = np.arange(500_000, dtype=np.float32).reshape(500, 1000)
    ref = rt.put(arr)
    out = rt.get(ref)
    np.testing.assert_array_equal(out, arr)
    # Large objects come back as views over shared memory (zero-copy).
    assert not out.flags.writeable


def test_object_ref_as_arg():
    @rt.remote
    def total(x):
        return float(x.sum())

    arr = np.ones(300_000, dtype=np.float64)
    ref = rt.put(arr)
    assert rt.get(total.remote(ref)) == 300_000.0


def test_chained_tasks():
    @rt.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(5):
        ref = inc.remote(ref)
    assert rt.get(ref) == 6


def test_num_returns():
    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates_type():
    @rt.remote
    def boom():
        raise ValueError("broken")

    with pytest.raises(ValueError, match="broken"):
        rt.get(boom.remote())


def test_error_propagates_through_dependency():
    @rt.remote
    def boom():
        raise KeyError("first")

    @rt.remote
    def use(x):
        return x

    with pytest.raises(KeyError):
        rt.get(use.remote(boom.remote()))


def test_get_timeout():
    @rt.remote
    def slow():
        import time

        time.sleep(30)

    with pytest.raises(exc.GetTimeoutError):
        rt.get(slow.remote(), timeout=0.2)


def test_wait():
    @rt.remote
    def fast(i):
        return i

    @rt.remote
    def slow():
        import time

        time.sleep(30)

    refs = [fast.remote(i) for i in range(3)] + [slow.remote()]
    ready, remaining = rt.wait(refs, num_returns=3, timeout=10)
    assert len(ready) == 3
    assert len(remaining) == 1


def test_nested_tasks():
    @rt.remote
    def inner(x):
        return x * 2

    @rt.remote
    def outer(x):
        return rt.get(inner.remote(x)) + 1

    assert rt.get(outer.remote(10)) == 21


def test_actor_basics():
    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.v = start

        def inc(self, by=1):
            self.v += by
            return self.v

        def value(self):
            return self.v

    c = Counter.remote(5)
    assert rt.get(c.inc.remote()) == 6
    assert rt.get(c.inc.remote(by=4)) == 10
    assert rt.get(c.value.remote()) == 10


def test_actor_ordering():
    @rt.remote
    class Appender:
        def __init__(self):
            self.items = []

        def append(self, x):
            self.items.append(x)

        def get(self):
            return self.items

    a = Appender.remote()
    refs = [a.append.remote(i) for i in range(20)]
    rt.get(refs)  # surface append errors instead of discarding refs
    assert rt.get(a.get.remote()) == list(range(20))


def test_named_actor():
    @rt.remote
    class Registry:
        def ping(self):
            return "pong"

    Registry.options(name="reg").remote()
    handle = rt.get_actor("reg")
    assert rt.get(handle.ping.remote()) == "pong"


def test_actor_handle_passing():
    @rt.remote
    class Store:
        def __init__(self):
            self.v = 0

        def set(self, v):
            self.v = v

        def get(self):
            return self.v

    @rt.remote
    def writer(store):
        rt.get(store.set.remote(42))
        return True

    s = Store.remote()
    rt.get(writer.remote(s))
    assert rt.get(s.get.remote()) == 42


def test_actor_error():
    @rt.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method failed")

    b = Bad.remote()
    with pytest.raises(RuntimeError, match="actor method failed"):
        rt.get(b.fail.remote())


def test_kill_actor():
    @rt.remote
    class Victim:
        def ping(self):
            return "alive"

    v = Victim.remote()
    assert rt.get(v.ping.remote()) == "alive"
    rt.kill(v)
    with pytest.raises(
        (exc.ActorDiedError, exc.ActorUnavailableError, exc.WorkerCrashedError)
    ):
        rt.get(v.ping.remote(), timeout=10)


def test_actor_restart_keeps_creation_args_pinned():
    """Creation args must survive the caller dropping its ObjectRef and
    the first creation completing: restarts re-run the creation task
    with the same args (reference: lineage pinning, reference_count.h)."""

    @rt.remote(max_restarts=1)
    class Holder:
        def __init__(self, payload):
            self.total = int(payload.sum())

        def value(self):
            return self.total

        def die(self):
            import os

            os._exit(1)

    arr = np.ones(300_000, dtype=np.float32)  # large → real shm object
    ref = rt.put(arr)
    h = Holder.remote(ref)
    assert rt.get(h.value.remote(), timeout=30) == 300_000
    del ref  # caller handle drop must not delete the pinned arg
    import gc

    gc.collect()
    with pytest.raises(
        (exc.ActorDiedError, exc.ActorUnavailableError, exc.WorkerCrashedError)
    ):
        rt.get(h.die.remote(), timeout=30)
    # After restart the creation arg was still available.
    assert rt.get(h.value.remote(), timeout=30) == 300_000


def test_kill_queued_actor_seals_creation_and_unpins():
    """kill() of an actor whose creation task is still queued must fail
    the creation returns and release pinned args (no object leak)."""
    import time

    @rt.remote
    def blocker():
        time.sleep(60)

    arr = np.ones(300_000, dtype=np.float32)
    ref = rt.put(arr)
    blockers = [blocker.remote() for _ in range(4)]  # saturate 4 CPUs
    time.sleep(0.3)

    @rt.remote(num_cpus=1)
    class Queued:
        def __init__(self, payload):
            self.payload = payload

        def ping(self):
            return 1

    q = Queued.remote(ref)
    time.sleep(0.3)
    rt.kill(q)
    with pytest.raises(
        (exc.ActorDiedError, exc.ActorUnavailableError, exc.WorkerCrashedError)
    ):
        rt.get(q.ping.remote(), timeout=10)
    # Dropping the caller's ref must now actually delete the object:
    # the daemon's pin was released by the kill.
    del ref
    import gc

    gc.collect()
    deadline = time.time() + 10
    while time.time() < deadline:
        used = rt.state_summary().get("used", 0)
        if used < arr.nbytes:
            break
        time.sleep(0.2)
    assert used < arr.nbytes, f"creation arg leaked ({used} bytes in use)"
    del blockers


def test_cancel_queued_task():
    @rt.remote
    def blocker():
        import time

        time.sleep(60)

    @rt.remote
    def victim():
        return 1

    # Saturate the 4 CPUs, then queue + cancel the victim.
    blockers = [blocker.remote() for _ in range(4)]
    ref = victim.remote()
    import time

    time.sleep(0.5)
    rt.cancel(ref)
    with pytest.raises((exc.TaskCancelledError, exc.RayTpuError)):
        rt.get(ref, timeout=5)
    del blockers


def test_cluster_resources():
    total = rt.cluster_resources()
    assert total["CPU"] == 4.0


def test_fractional_resources():
    @rt.remote(num_cpus=0.5)
    def half():
        return 1

    assert rt.get([half.remote() for _ in range(8)]) == [1] * 8


def test_task_events_recorded():
    @rt.remote
    def traced():
        return 1

    rt.get(traced.remote())
    # task_done (which records FINISHED) is a fire-and-forget
    # notification that can land just after get() returns.
    import time

    states = []
    for _ in range(50):
        events = rt.timeline()
        states = [e["state"] for e in events if e["name"] == "traced"]
        if "FINISHED" in states:
            break
        time.sleep(0.1)
    assert "RUNNING" in states
    assert "FINISHED" in states


def test_runtime_context():
    """get_runtime_context() exposes job/node/task/actor identity in
    every execution context (reference: runtime_context.py:30)."""
    ctx = rt.get_runtime_context()
    assert len(ctx.get_job_id()) > 0
    assert len(ctx.get_node_id()) == 32
    assert ctx.get_task_id() is None  # driver
    assert ctx.get_actor_id() is None
    assert "TPU" in ctx.get_accelerator_ids()

    @rt.remote
    def inside_task():
        c = rt.get_runtime_context()
        return (c.get_task_id(), c.get_actor_id(), c.get_job_id())

    task_id, actor_id, job_id = rt.get(inside_task.remote(), timeout=30)
    assert task_id is not None and actor_id is None
    assert job_id == ctx.get_job_id()

    @rt.remote
    class Inside:
        def who(self):
            c = rt.get_runtime_context()
            return (c.get_actor_id(), c.get_task_id())

    a = Inside.remote()
    actor_id, task_id = rt.get(a.who.remote(), timeout=30)
    assert actor_id is not None and task_id is not None


def test_runtime_context_async_actor():
    """Task identity inside ASYNC actor methods (coroutines run on the
    shared loop thread; identity rides an asyncio-task-local
    contextvar, so interleaved calls can't cross-contaminate)."""

    @rt.remote(max_concurrency=4)
    class AsyncIdent:
        async def who(self):
            import asyncio

            c = rt.get_runtime_context()
            first = c.get_task_id()
            await asyncio.sleep(0.05)  # force interleaving
            return (first, c.get_task_id())

    a = AsyncIdent.remote()
    pairs = rt.get([a.who.remote() for _ in range(4)], timeout=30)
    ids = set()
    for first, after_await in pairs:
        assert first is not None
        # Identity survives the await AND is unique per call.
        assert first == after_await
        ids.add(first)
    assert len(ids) == 4


def test_duplicate_actor_name_surfaces_error():
    """Creates are pipelined one-way notifies, so a name collision
    can't ride the create's RPC reply — it must still surface as a
    detectable failure on the duplicate handle's method calls
    (reference: ray raises on duplicate named actors; here the dead
    handle errors instead of hanging)."""

    @rt.remote
    class Named:
        def ping(self):
            return "first"

    first = Named.options(name="dup-name").remote()
    assert rt.get(first.ping.remote(), timeout=60) == "first"

    second = Named.options(name="dup-name").remote()
    with pytest.raises(Exception) as exc_info:
        rt.get(second.ping.remote(), timeout=30)
    assert "dead" in str(exc_info.value).lower() or "registration" in str(
        exc_info.value
    ).lower()

    # The original actor is untouched by the failed duplicate.
    assert rt.get(first.ping.remote(), timeout=60) == "first"
