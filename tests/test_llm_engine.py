"""Continuous-batching engine tests (ISSUE 10): scheduler invariants,
engine-vs-generate parity, cancellation, multiplex isolation, and
chaos — in-flight requests get errors, never hangs."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm.scheduler import (
    EngineOverloaded,
    SlotScheduler,
)


# ---------------------------------------------------------------------
# scheduler invariants (pure bookkeeping, no jax)
# ---------------------------------------------------------------------

def test_scheduler_fifo_admission_and_slot_reuse():
    sched = SlotScheduler(2, max_waiting=8)
    for name in ("a", "b", "c", "d"):
        sched.submit(name)
    first = sched.admit_next()
    second = sched.admit_next()
    assert (first[0], second[0]) == ("a", "b")  # FIFO
    assert sched.admit_next() is None  # no free slot
    freed = first[1]
    assert sched.release(freed) == "a"
    third = sched.admit_next()
    assert third[0] == "c"  # still FIFO
    assert third[1] == freed  # the evicted slot is reused
    assert sched.stats() == {
        "slots_total": 2, "slots_used": 2, "waiting": 1,
    }


def test_scheduler_overload_and_waiting_removal():
    sched = SlotScheduler(1, max_waiting=2)
    sched.submit("a")
    sched.submit("b")
    with pytest.raises(EngineOverloaded):
        sched.submit("c")
    assert sched.remove_waiting("b")
    assert not sched.remove_waiting("b")
    sched.submit("d")  # freed waiting capacity
    assert [r for r in sched.waiting] == ["a", "d"]


def test_scheduler_drain_returns_everything():
    sched = SlotScheduler(2, max_waiting=8)
    for name in ("a", "b", "c"):
        sched.submit(name)
    sched.admit_next()
    sched.admit_next()
    doomed = sched.drain()
    assert sorted(doomed) == ["a", "b", "c"]
    assert sched.stats()["slots_used"] == 0
    assert sched.admit_next() is None


# ---------------------------------------------------------------------
# engine (tiny model; ONE shape family so XLA compiles once per suite)
# ---------------------------------------------------------------------

ENGINE_KW = dict(slots=2, max_len=48, prefill_chunk=8)


@pytest.fixture(scope="module")
def tiny_model():
    from ray_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        intermediate=128, max_seq_len=128, dtype=jnp.float32,
        attention="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture
def engine(tiny_model):
    from ray_tpu.llm import EngineConfig, InferenceEngine

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg, EngineConfig(max_new_tokens=8, **ENGINE_KW),
        family="tiny",
    )
    yield eng
    eng.close()


def test_engine_matches_generate_greedy(tiny_model, engine):
    """Satellite 1 parity: tokens decoded through the shared slot
    cache (concurrent requests, per-row positions, chunked prefill)
    must equal `generate()`'s greedy output per prompt."""
    from ray_tpu.models.generate import generate

    cfg, params = tiny_model
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 128, size=n).tolist() for n in (5, 8, 11)]
    streams = [engine.submit(p, max_new_tokens=8) for p in prompts]
    outs = [list(s) for s in streams]
    assert [s.finish_reason for s in streams] == ["length"] * 3
    for prompt, out in zip(prompts, outs):
        ref, _ = generate(
            params,
            jnp.asarray([prompt], jnp.int32),
            jnp.asarray([len(prompt)], jnp.int32),
            cfg,
            max_new_tokens=8,
            temperature=0.0,
        )
        assert out == np.asarray(ref)[0].tolist()


def test_prefix_hit_parity_with_generate(tiny_model):
    """ISSUE 11 satellite: with the paged cache AND prefix caching ON,
    a request whose prompt prefix hits the pool must skip prefill for
    the shared blocks and STILL decode token-for-token what
    `generate()` produces — including a request that shares only the
    prefix, not the whole prompt."""
    from ray_tpu.llm import EngineConfig, InferenceEngine
    from ray_tpu.models.generate import generate

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_new_tokens=8, prefix_cache=True, **ENGINE_KW),
        family="tiny",
    )
    try:
        rng = np.random.default_rng(21)
        base = rng.integers(1, 128, size=20).tolist()
        prompts = [
            base,  # seeds the prefix cache (miss)
            list(base),  # identical prompt: full-prefix hit
            base[:16] + rng.integers(1, 128, size=5).tolist(),
            # ^ shares only the first two blocks (16 tokens)
        ]
        outs = []
        for prompt in prompts:
            stream = eng.submit(prompt, max_new_tokens=8)
            outs.append(list(stream))
            assert stream.finish_reason == "length"
        stats = eng.stats()
        # Prompt 1 missed; prompts 2 and 3 hit (block_len=8: two full
        # blocks of `base` are cached, and skip is chunk-aligned at
        # 16 tokens for both).
        assert stats["prefix_misses"] >= 1
        assert stats["prefix_hits"] == 2
        assert stats["prefix_tokens_saved"] == 32
        for prompt, out in zip(prompts, outs):
            ref, _ = generate(
                params,
                jnp.asarray([prompt], jnp.int32),
                jnp.asarray([len(prompt)], jnp.int32),
                cfg, max_new_tokens=8, temperature=0.0,
            )
            assert out == np.asarray(ref)[0].tolist()
    finally:
        eng.close()


def test_midprefill_row_not_corrupted_by_interleaved_decode(
    tiny_model,
):
    """Review-caught paged-cache corruption: while a request CHUNK-
    PREFILLS, its block table is already built but its row is not yet
    alive — the interleaved decode step over the full slot batch must
    NOT scatter its junk row (stale position, masked token) into the
    request's real pages. Pre-fix, a slot whose previous occupant
    finished at a low position wrote junk INSIDE the new prompt's
    already-prefilled region (position 0 here), and the output
    diverged from generate()."""
    from ray_tpu.llm import EngineConfig, InferenceEngine
    from ray_tpu.models.generate import generate

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_new_tokens=8, prefix_cache=False,
                     **ENGINE_KW),
        family="tiny",
    )
    try:
        # Keep the decode batch hot so every prefill chunk of the
        # long request interleaves with a decode step.
        busy = eng.submit([9, 9, 9, 9], max_new_tokens=30)
        assert isinstance(next(busy), int)
        rng = np.random.default_rng(5)
        prompt = rng.integers(1, 128, size=20).tolist()  # 3 chunks
        stream = eng.submit(prompt, max_new_tokens=8)
        out = list(stream)
        busy.cancel()
        list(busy)
        ref, _ = generate(
            params,
            jnp.asarray([prompt], jnp.int32),
            jnp.asarray([len(prompt)], jnp.int32),
            cfg, max_new_tokens=8, temperature=0.0,
        )
        assert out == np.asarray(ref)[0].tolist()
    finally:
        eng.close()


def test_engine_eos_stops_row(tiny_model, engine):
    from ray_tpu.models.generate import generate

    cfg, params = tiny_model
    prompt = [3, 14, 15, 9]
    ref, _ = generate(
        params,
        jnp.asarray([prompt], jnp.int32),
        jnp.asarray([len(prompt)], jnp.int32),
        cfg, max_new_tokens=8, temperature=0.0,
    )
    eos = int(np.asarray(ref)[0][2])  # declare the 3rd token EOS
    stream = engine.submit(prompt, max_new_tokens=8, eos_token=eos)
    out = list(stream)
    assert stream.finish_reason == "stop"
    assert out == np.asarray(ref)[0][:3].tolist()
    assert out[-1] == eos


def test_slot_reuse_after_eviction(engine):
    """3 requests through 2 slots: the third admits into a slot one
    of the first two vacated, and the waiting queue drains."""
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]]
    streams = [engine.submit(p, max_new_tokens=6) for p in prompts]
    # With 2 slots the third request must wait first.
    assert engine.stats()["waiting"] >= 1 or list(streams[2])
    outs = [list(s) for s in streams]
    assert all(len(o) == 6 for o in outs)
    slots = [s._req.slot for s in streams]
    assert slots[2] in (slots[0], slots[1])  # reused, not grown
    stats = engine.stats()
    assert stats["slots_used"] == 0
    assert stats["waiting"] == 0
    assert stats["requests_done"] >= 3


def test_admission_fifo_no_long_prompt_starvation(engine):
    """Both slots busy; a LONG-prompt request queued ahead of short
    ones is admitted first when a slot frees (FIFO — chunked prefill
    bounds its cost instead of its priority)."""
    busy = [
        engine.submit([1 + i, 2, 3, 4], max_new_tokens=24)
        for i in range(2)
    ]
    long_req = engine.submit(
        list(range(1, 21)), max_new_tokens=4
    )  # 20-token prompt => 3 prefill chunks
    shorts = [
        engine.submit([40 + i, 41, 42, 43], max_new_tokens=4)
        for i in range(2)
    ]

    first_token_at = {}

    def consume(tag, stream):
        for i, _tok in enumerate(stream):
            if i == 0:
                first_token_at[tag] = time.perf_counter()

    threads = [
        threading.Thread(target=consume, args=(tag, s), daemon=True)
        for tag, s in [
            ("b0", busy[0]), ("b1", busy[1]), ("long", long_req),
            ("s0", shorts[0]), ("s1", shorts[1]),
        ]
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert set(first_token_at) == {"b0", "b1", "long", "s0", "s1"}
    assert first_token_at["long"] < first_token_at["s0"]
    assert first_token_at["long"] < first_token_at["s1"]


def test_cancel_frees_slot_mid_decode(engine):
    stream = engine.submit([7, 7, 7, 7], max_new_tokens=32)
    first = next(stream)
    assert isinstance(first, int)
    stream.cancel()
    rest = list(stream)
    assert stream.finish_reason == "cancelled"
    assert 1 + len(rest) < 32  # budget NOT decoded to the end
    deadline = time.time() + 10
    while time.time() < deadline:
        if engine.stats()["slots_used"] == 0:
            break
        time.sleep(0.02)
    assert engine.stats()["slots_used"] == 0
    # The freed slot serves a new request normally.
    out = list(engine.submit([8, 8, 8, 8], max_new_tokens=4))
    assert len(out) == 4


def test_cancel_mid_prefill_does_not_kill_engine(engine):
    """Cancelling while the prompt is still CHUNK-PREFILLING must
    free the slot exactly once — the prefilling request is both the
    scheduler's slot holder and the engine's prefill cursor, and a
    double release used to kill the whole loop (every other request
    failed with EngineDead)."""
    # 20-token prompt = 3 chunks at prefill_chunk=8: cancel lands in
    # the prefill window with high probability; the invariant must
    # hold regardless of where it lands.
    for attempt in range(5):
        stream = engine.submit(
            list(range(1, 21)), max_new_tokens=4
        )
        time.sleep(0.002 * attempt)
        stream.cancel()
        list(stream)
        assert stream.finish_reason in ("cancelled", "length")
    # Engine survived every cancel point and still serves.
    out = list(engine.submit([2, 4, 6, 8], max_new_tokens=4))
    assert len(out) == 4
    assert engine.stats()["dead"] is False


def test_cancel_waiting_request_never_admitted(engine):
    busy = [
        engine.submit([1, 2, 3, 4], max_new_tokens=24)
        for _ in range(2)
    ]
    queued = engine.submit([9, 9, 9, 9], max_new_tokens=4)
    deadline = time.time() + 10
    while time.time() < deadline:
        if engine.stats()["slots_used"] == 2:  # busy pair admitted
            break
        time.sleep(0.01)
    assert engine.stats()["waiting"] == 1
    queued.cancel()
    assert list(queued) == []
    assert queued.finish_reason == "cancelled"
    assert engine.stats()["waiting"] == 0
    for stream in busy:
        stream.cancel()
        list(stream)


def test_engine_overload_rejects(tiny_model):
    from ray_tpu.llm import (
        EngineConfig, EngineOverloaded as Overloaded, InferenceEngine,
    )

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_new_tokens=8, max_waiting=1, **ENGINE_KW),
        family="tiny",
    )
    try:
        busy = []
        for n in range(2):
            busy.append(
                eng.submit([1 + n, 2, 3, 4], max_new_tokens=24)
            )
            deadline = time.time() + 10
            while time.time() < deadline:
                if eng.stats()["slots_used"] == n + 1:
                    break
                time.sleep(0.01)
        eng.submit([5, 5, 5, 5])  # fills the 1-deep waiting queue
        with pytest.raises(Overloaded):
            eng.submit([6, 6, 6, 6])
        for stream in busy:
            stream.cancel()
    finally:
        eng.close()


def test_engine_death_fails_inflight_not_hangs(tiny_model):
    """Chaos: the step loop dying mid-decode must surface as an error
    on every in-flight stream (and on later submits), never a hang."""
    from ray_tpu.llm import EngineConfig, EngineDead, InferenceEngine

    cfg, params = tiny_model
    eng = InferenceEngine(
        params, cfg, EngineConfig(max_new_tokens=8, **ENGINE_KW),
        family="tiny",
    )
    live = eng.submit([1, 2, 3, 4])
    assert len(list(live)) == 8  # engine is healthy
    eng._kv.pool = None  # chaos: corrupt the loop's device state
    doomed = eng.submit([5, 6, 7, 8])
    with pytest.raises(EngineDead):
        list(doomed)  # the step loop died on this request
    deadline = time.time() + 10
    while True:  # once dead, submit must reject — never queue/hang
        try:
            eng.submit([1, 2, 3])
        except EngineDead:
            break
        assert time.time() < deadline, "engine death not latched"
        time.sleep(0.02)
    eng.close()


def test_fallback_padding_is_exact(tiny_model):
    """Kill-switch fallback (per-request generate_stream over a
    BUCKET-padded prompt) must emit the same greedy tokens as
    generate() on the unpadded prompt: generate_stream decodes from
    each row's TRUE length, so padding never enters attention."""
    from ray_tpu.llm.serving import LLMServer
    from ray_tpu.models.generate import generate

    cfg, params = tiny_model
    server = LLMServer(
        {
            "tiny": {
                "kind": "init", "seed": 0,
                "config": {
                    "vocab_size": 128, "dim": 64, "n_layers": 2,
                    "n_heads": 4, "n_kv_heads": 2,
                    "intermediate": 128, "max_seq_len": 128,
                    "dtype": "float32",
                },
            }
        },
        engine=dict(max_new_tokens=8, **ENGINE_KW),
        engine_enabled=False,
    )
    prompt = [3, 99, 41, 7, 58]  # 5 tokens: NOT a bucket multiple
    out = [
        int(chunk)
        for chunk in b"".join(
            server({"prompt": prompt, "max_new_tokens": 8})
        ).split()
    ]
    ref, _ = generate(
        params,
        jnp.asarray([prompt], jnp.int32),
        jnp.asarray([len(prompt)], jnp.int32),
        cfg, max_new_tokens=8, temperature=0.0,
    )
    assert out == np.asarray(ref)[0].tolist()


def test_multiplex_swap_blocks_only_affected_family(
    tiny_model, monkeypatch
):
    """Loading family B (slow) must not stall family A's decode loop:
    A's tokens keep arriving DURING B's load window."""
    import ray_tpu.llm.serving as serving
    from ray_tpu.llm.serving import LLMServer

    cfg, params = tiny_model
    spec_a = {"kind": "init", "seed": 0, "config": None}
    spec_b = {"kind": "init", "seed": 1, "config": None}

    load_window = {}

    def build_model(spec):
        if spec is spec_b:
            load_window["start"] = time.perf_counter()
            time.sleep(1.0)  # a slow swap (HF checkpoint load)
            load_window["end"] = time.perf_counter()
        return params, cfg

    monkeypatch.setattr(serving, "build_model", build_model)
    server = LLMServer(
        {"a": spec_a, "b": spec_b},
        engine=dict(max_new_tokens=40, **ENGINE_KW),
    )
    a_times = []
    b_done = threading.Event()

    def consume_a():
        for _chunk in server({"prompt": [1, 2, 3], "model": "a",
                              "max_new_tokens": 40}):
            a_times.append(time.perf_counter())

    def consume_b():
        list(server({"prompt": [4, 5, 6], "model": "b",
                     "max_new_tokens": 4}))
        b_done.set()

    ta = threading.Thread(target=consume_a, daemon=True)
    ta.start()
    while not a_times:  # family A is decoding
        time.sleep(0.005)
    tb = threading.Thread(target=consume_b, daemon=True)
    tb.start()
    ta.join(timeout=60)
    assert b_done.wait(timeout=60)
    during_load = [
        t for t in a_times
        if load_window["start"] <= t <= load_window["end"]
    ]
    assert during_load, (
        "family A produced no tokens while family B loaded — the "
        "swap blocked the wrong family"
    )


# ---------------------------------------------------------------------
# serve-level chaos: replica death mid-stream errors, doesn't hang
# ---------------------------------------------------------------------

@pytest.mark.timeout(240)
def test_replica_death_fails_inflight_stream(rt_session):
    rt = rt_session
    import ray_tpu.serve as serve
    from ray_tpu.llm import build_llm_app

    tiny = {
        "kind": "init", "seed": 0,
        "config": {
            "vocab_size": 128, "dim": 64, "n_layers": 2,
            "n_heads": 4, "n_kv_heads": 2, "intermediate": 128,
            "max_seq_len": 128, "dtype": "float32",
        },
    }
    try:
        handle = serve.run(
            build_llm_app(
                {"tiny": tiny},
                # Big per-slot capacity: the in-flight stream must
                # still be decoding (900-token budget, seconds of
                # work) when the replica dies.
                engine={
                    "slots": 2, "max_len": 1024,
                    "prefill_chunk": 8, "max_new_tokens": 900,
                },
                max_ongoing_requests=8,
            ),
            name="llm-chaos",
            route_prefix=None,
        )
        warm = handle.options(stream=True).remote(
            {"prompt": [1, 2, 3], "max_new_tokens": 2}
        )
        assert len(list(warm)) == 2
        stream = handle.options(stream=True).remote(
            {"prompt": [5, 6, 7], "max_new_tokens": 900}
        )
        first = next(stream)
        assert first  # stream is live
        controller = rt.get_actor(
            "SERVE_CONTROLLER", namespace="serve"
        )
        replicas = rt.get(
            controller.get_replicas.remote("llm-chaos", "llm"),
            timeout=30,
        )
        assert replicas
        rt.kill(replicas[0]["actor"])
        outcome = None
        deadline = time.time() + 120
        try:
            while time.time() < deadline:
                next(stream)
        except StopIteration:
            outcome = "clean_stop"
        except BaseException as e:  # noqa: BLE001 — the assertion
            outcome = repr(e)
        # The dead replica must surface as an ERROR within the
        # deadline — not a hang, and not a well-formed early stop
        # that hides the truncation.
        assert outcome not in (None, "clean_stop"), outcome
    finally:
        serve.shutdown()
