"""util API tests: collective groups, ActorPool, Queue (reference
test models: util/collective tests, test_actor_pool.py,
test_queue.py)."""

import numpy as np
import pytest


def test_collective_group_allreduce_across_tasks(rt_session):
    rt = rt_session

    @rt.remote
    def member(rank, world):
        import numpy as np

        from ray_tpu.util.collective import init_collective_group

        group = init_collective_group(world, rank, "g1")
        reduced = group.allreduce(np.full(4, rank + 1.0))
        gathered = group.allgather(np.array([rank]))
        got = group.broadcast(
            np.array([42.0]) if rank == 0 else None, src_rank=0
        )
        shard = group.reducescatter(np.arange(4, dtype=np.float64))
        group.barrier()
        return (
            reduced.tolist(),
            [int(g[0]) for g in gathered],
            float(got[0]),
            shard.tolist(),
        )

    world = 3
    results = rt.get(
        [member.remote(rank, world) for rank in range(world)],
        timeout=120,
    )
    from ray_tpu.util.collective import destroy_collective_group

    destroy_collective_group("g1")
    for rank, (reduced, gathered, got, shard) in enumerate(results):
        assert reduced == [6.0] * 4  # 1+2+3
        assert gathered == [0, 1, 2]
        assert got == 42.0
    # reducescatter shards the reduced tensor across ranks.
    all_shards = [r[3] for r in results]
    flat = [v for shard in all_shards for v in shard]
    assert flat == [0.0, 3.0, 6.0, 9.0]


def test_collective_p2p(rt_session):
    rt = rt_session

    @rt.remote
    def member(rank):
        import numpy as np

        from ray_tpu.util.collective import init_collective_group

        group = init_collective_group(2, rank, "p2p")
        if rank == 0:
            group.send(np.array([7.0, 8.0]), dst_rank=1)
            return None
        return group.recv(src_rank=0).tolist()

    results = rt.get(
        [member.remote(0), member.remote(1)], timeout=120
    )
    from ray_tpu.util.collective import destroy_collective_group

    destroy_collective_group("p2p")
    assert results[1] == [7.0, 8.0]


def test_actor_pool_ordered_and_unordered(rt_session):
    rt = rt_session
    from ray_tpu.util.actor_pool import ActorPool

    @rt.remote
    class Worker:
        def double(self, x):
            return 2 * x

    pool = ActorPool([Worker.remote() for _ in range(2)])
    results = list(
        pool.map(lambda a, v: a.double.remote(v), range(6))
    )
    assert results == [0, 2, 4, 6, 8, 10]

    unordered = sorted(
        pool.map_unordered(lambda a, v: a.double.remote(v), range(6))
    )
    assert unordered == [0, 2, 4, 6, 8, 10]


def test_queue_cross_task(rt_session):
    rt = rt_session
    from ray_tpu.util.queue import Queue

    queue = Queue(maxsize=10)

    @rt.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return "done"

    @rt.remote
    def consumer(q, n):
        return [q.get(timeout=30) for _ in range(n)]

    p = producer.remote(queue, 5)
    c = consumer.remote(queue, 5)
    assert rt.get(p, timeout=60) == "done"
    assert rt.get(c, timeout=60) == [0, 1, 2, 3, 4]
    assert queue.empty()
    queue.shutdown()


def test_queue_full_and_empty(rt_session):
    from ray_tpu.util.queue import Empty, Full, Queue

    queue = Queue(maxsize=1)
    queue.put("x")
    with pytest.raises(Full):
        queue.put("y", block=False)
    assert queue.get() == "x"
    with pytest.raises(Empty):
        queue.get(block=False)
    queue.shutdown()


def test_list_named_actors(rt_session):
    """reference: ray.util.list_named_actors — live named actors,
    optionally across namespaces."""
    rt = rt_session
    from ray_tpu.util import list_named_actors

    @rt.remote
    class N:
        def ping(self):
            return 1

    a = N.options(name="walter").remote()
    b = N.options(name="jesse", namespace="abq").remote()
    rt.get([a.ping.remote(), b.ping.remote()], timeout=30)

    names = list_named_actors()
    assert "walter" in names and "jesse" not in names
    rows = list_named_actors(all_namespaces=True)
    assert {"name": "jesse", "namespace": "abq"} in rows
    assert any(r["name"] == "walter" for r in rows)

    rt.kill(a)
    import time as _t

    deadline = _t.time() + 15
    while _t.time() < deadline and "walter" in list_named_actors():
        _t.sleep(0.2)
    assert "walter" not in list_named_actors()


def test_session_namespace_scopes_named_actors():
    """rt.init(namespace=...) scopes named-actor creation, get_actor,
    and list_named_actors (reference: ray.init(namespace))."""
    import ray_tpu as rt
    from ray_tpu.util import list_named_actors

    rt.init(num_cpus=2, namespace="abq")
    try:

        @rt.remote
        class N:
            def ping(self):
                return 1

        a = N.options(name="gus").remote()
        rt.get(a.ping.remote(), timeout=30)
        # Scoped listing sees it; explicit default-namespace miss.
        assert "gus" in list_named_actors()
        h = rt.get_actor("gus")  # session namespace is the default
        assert rt.get(h.ping.remote(), timeout=20) == 1
        import pytest as _pytest

        with _pytest.raises(ValueError):
            rt.get_actor("gus", namespace="default")
    finally:
        rt.shutdown()
