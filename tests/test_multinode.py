"""Multi-node cluster tests (reference test model: ray_start_cluster
fixture + python/ray/tests/test_multi_node*.py — scheduling spillback,
cross-node objects, node failure handling)."""

import os
import time

import numpy as np
import pytest


@pytest.fixture(params=["unix", "tcp"])
def cluster(request):
    """Every multinode scenario runs twice: once over Unix sockets
    (single-host fast path) and once with all daemons forced onto TCP
    loopback — the cross-host DCN transport (VERDICT round-1 item 1)."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        initialize_head=True,
        head_resources={"CPU": 2.0},
        use_tcp=(request.param == "tcp"),
    )
    yield c
    c.shutdown()


@pytest.fixture
def rt_cluster(cluster):
    import ray_tpu as rt

    rt.init(address=cluster.address)
    yield rt, cluster
    rt.shutdown()


def test_spillback_to_fitting_node(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=2, resources={"special": 2.0})
    cluster.wait_for_nodes(2)

    @rt.remote(resources={"special": 1.0})
    def where():
        import os as _os
        return _os.environ.get("RT_SOCKET", "")

    socket = rt.get(where.remote(), timeout=30)
    assert "node-1" in socket


def test_broadcast_to_many_nodes(rt_cluster):
    """One producer, consumers on several nodes: every node pulls the
    full object correctly (pipelined chunk window + randomized source
    selection — PushManager-style broadcast spread)."""
    rt, cluster = rt_cluster
    for i in range(3):
        cluster.add_node(num_cpus=1, resources={f"n{i}": 1.0})
    cluster.wait_for_nodes(4)

    @rt.remote(resources={"n0": 0.5})
    def produce():
        return np.arange(2_000_000, dtype=np.float64)  # ~16 MB

    ref = produce.remote()

    @rt.remote
    def check(x):
        return float(x[1_234_567]) == 1_234_567.0 and x.nbytes

    checks = [
        check.options(resources={f"n{i}": 1.0}).remote(ref)
        for i in range(3)
    ]
    results = rt.get(checks, timeout=120)
    assert all(r == 16_000_000 for r in results), results


def test_cross_node_large_object_transfer(rt_cluster):
    rt, cluster = rt_cluster
    node = cluster.add_node(num_cpus=2, resources={"special": 2.0})
    cluster.wait_for_nodes(2)

    @rt.remote(resources={"special": 1.0})
    def produce():
        return np.arange(300_000, dtype=np.float64)  # ~2.4 MB

    ref = produce.remote()
    arr = rt.get(ref, timeout=30)
    assert arr.shape == (300_000,)
    assert float(arr[12345]) == 12345.0

    # Large driver-side arg consumed on the remote node.
    big = np.ones(250_000, dtype=np.float64)
    big_ref = rt.put(big)

    @rt.remote(resources={"special": 1.0})
    def total(x):
        return float(x.sum())

    assert rt.get(total.remote(big_ref), timeout=30) == 250_000.0


def test_node_affinity_strategy(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(2)
    target = next(
        n for n in rt.nodes() if not n["is_head"] and n["alive"]
    )

    from ray_tpu.util import NodeAffinitySchedulingStrategy

    @rt.remote
    def where():
        import os as _os
        return _os.environ.get("RT_SOCKET", "")

    strategy = NodeAffinitySchedulingStrategy(node_id=target["node_id"])
    socket = rt.get(
        where.options(scheduling_strategy=strategy).remote(), timeout=30
    )
    # Workers always ride their node's session Unix socket even when
    # the node advertises TCP; identify the node by session dir.
    target_node = next(
        n for n in cluster.nodes
        if n.node_id.hex() == target["node_id"]
    )
    assert socket == target_node.socket_path


def test_node_label_strategy(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=2, labels={"zone": "us-a"})
    cluster.add_node(num_cpus=2, labels={"zone": "us-b"})
    cluster.wait_for_nodes(3)

    from ray_tpu.util import NodeLabelSchedulingStrategy

    @rt.remote
    def where():
        import os as _os
        return _os.environ.get("RT_SOCKET", "")

    strategy = NodeLabelSchedulingStrategy(hard={"zone": ["us-b"]})
    socket = rt.get(
        where.options(scheduling_strategy=strategy).remote(), timeout=30
    )
    expected_id = next(
        n["node_id"] for n in rt.nodes()
        if n["labels"].get("zone") == "us-b"
    )
    expected = next(
        n.socket_path for n in cluster.nodes
        if n.node_id.hex() == expected_id
    )
    assert socket == expected


def test_spread_strategy_uses_multiple_nodes(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(3)

    @rt.remote
    def where():
        time.sleep(0.05)
        import os as _os
        return _os.environ.get("RT_SOCKET", "")

    refs = [
        where.options(scheduling_strategy="SPREAD").remote()
        for _ in range(12)
    ]
    sockets = set(rt.get(refs, timeout=60))
    assert len(sockets) >= 2


def test_infeasible_task_waits_for_node(rt_cluster):
    rt, cluster = rt_cluster

    @rt.remote(resources={"accel": 1.0})
    def need_accel():
        return "ran"

    ref = need_accel.remote()
    ready, _ = rt.wait([ref], timeout=0.5)
    assert not ready  # infeasible: no node has `accel`
    cluster.add_node(num_cpus=1, resources={"accel": 1.0})
    assert rt.get(ref, timeout=30) == "ran"


def test_remote_actor_and_named_lookup(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=2, resources={"special": 1.0})
    cluster.wait_for_nodes(2)

    @rt.remote(resources={"special": 1.0}, name="counter")
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

        def node(self):
            import os as _os
            return _os.environ.get("RT_SOCKET", "")

    counter = Counter.remote()
    assert rt.get(counter.incr.remote(), timeout=30) == 1
    assert rt.get(counter.incr.remote(5), timeout=30) == 6
    assert "node-1" in rt.get(counter.node.remote(), timeout=30)

    fetched = rt.get_actor("counter")
    assert rt.get(fetched.incr.remote(), timeout=30) == 7


def test_task_retry_on_node_death(rt_cluster):
    rt, cluster = rt_cluster
    node = cluster.add_node(num_cpus=2, resources={"special": 1.0})
    cluster.wait_for_nodes(2)

    from ray_tpu.util import NodeAffinitySchedulingStrategy

    @rt.remote(max_retries=2)
    def slow_value():
        time.sleep(1.5)
        return "done"

    target = next(n for n in rt.nodes() if not n["is_head"])
    ref = slow_value.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=target["node_id"], soft=True
        )
    ).remote()
    time.sleep(0.6)  # let it start on the doomed node
    cluster.remove_node(node)
    # Retried on a surviving node (head) and completes.
    assert rt.get(ref, timeout=60) == "done"


def test_actor_restart_on_node_death(rt_cluster):
    rt, cluster = rt_cluster
    node = cluster.add_node(num_cpus=2, resources={"special": 1.0})
    cluster.wait_for_nodes(2)

    @rt.remote(resources={"CPU": 1.0}, max_restarts=1)
    class Stateful:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def node(self):
            import os as _os
            return _os.environ.get("RT_SOCKET", "")

    from ray_tpu.util import NodeAffinitySchedulingStrategy

    target = next(n for n in rt.nodes() if not n["is_head"])
    actor = Stateful.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=target["node_id"], soft=True
        )
    ).remote()
    assert rt.get(actor.incr.remote(), timeout=30) == 1
    assert "node-1" in rt.get(actor.node.remote(), timeout=30)

    cluster.remove_node(node)
    # Restarted (state reset) on a surviving node.
    deadline = time.time() + 60
    value = None
    while time.time() < deadline:
        try:
            value = rt.get(actor.incr.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.3)
    assert value == 1
    assert "head" in rt.get(actor.node.remote(), timeout=30)


def test_cluster_resources_aggregate(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=3, resources={"special": 5.0})
    cluster.wait_for_nodes(2)
    total = rt.cluster_resources()
    assert total["CPU"] == 5.0  # 2 head + 3 node
    assert total["special"] == 5.0


def test_nested_task_submission_from_remote_node(rt_cluster):
    rt, cluster = rt_cluster
    cluster.add_node(num_cpus=2, resources={"special": 2.0})
    cluster.wait_for_nodes(2)

    @rt.remote
    def inner(x):
        return x * 2

    @rt.remote(resources={"special": 1.0})
    def outer():
        import ray_tpu as rt2

        refs = [inner.remote(i) for i in range(4)]
        return sum(rt2.get(refs, timeout=30))

    assert rt.get(outer.remote(), timeout=60) == 12


def test_versioned_heartbeats_elide_unchanged_load(rt_cluster):
    """Resource snapshots ride heartbeats only when they CHANGED since
    the head's last ack (reference: ray_syncer versioned resource
    messages) — idle nodes beat liveness-only."""
    rt, cluster = rt_cluster
    node = cluster.add_node(num_cpus=1, resources={"special": 1.0})
    cluster.wait_for_nodes(2)

    head = cluster.head
    seen = []
    orig = head._h_node_heartbeat

    def spy(conn, msg):
        if msg.get("node_id") == node.node_id.binary():
            seen.append("available" in msg)
        return orig(conn, msg)

    head.server._handlers["node_heartbeat"] = spy
    try:
        time.sleep(1.5)  # ~6 idle beats
        idle = list(seen)
        assert len(idle) >= 3
        # After the initial (changed) beat, payloads stop.
        assert not any(idle[1:]), idle

        seen.clear()

        @rt.remote(resources={"special": 1.0})
        def touch():
            time.sleep(0.8)  # hold the resource across several beats
            return 1

        assert rt.get(touch.remote(), timeout=30) == 1
        # Running a task changed availability -> payload reappears.
        deadline = time.time() + 10
        while time.time() < deadline and not any(seen):
            time.sleep(0.1)
        assert any(seen), seen
        # Head's view converges back to fully available once the
        # lease returns (idle lease timeout ~1s).
        deadline = time.time() + 10
        info = head.control.nodes[node.node_id]
        while time.time() < deadline:
            if info.available.get("special") == 1.0:
                break
            time.sleep(0.1)
        assert info.available.get("special") == 1.0
    finally:
        head.server._handlers["node_heartbeat"] = orig
