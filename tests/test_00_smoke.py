"""Liveness smoke test — MUST stay first in collection order.

VERDICT r2 item 1: round 2 snapshotted a repo whose ``rt.init()`` never
completed (half-landed RPC nonce handshake), wedging the whole suite
and the bench. This file is the guardrail: it collects first
(``test_00_``), has a tight hard timeout, and fails fast if the
control plane cannot complete a full init → task → get → shutdown
cycle. Reference analog: the first thing ray's CI runs is
``test_basic.py::test_simple_task`` class smoke coverage.
"""

import time

import pytest


@pytest.mark.timeout(15)
def test_init_roundtrip_is_fast():
    import ray_tpu as rt

    t0 = time.monotonic()
    rt.init(num_cpus=2)
    try:

        @rt.remote
        def f(x):
            return x + 1

        assert rt.get(f.remote(41)) == 42
        ref = rt.put({"k": [1, 2, 3]})
        assert rt.get(ref) == {"k": [1, 2, 3]}
        # The timeout marker is the liveness gate: a wedged handshake
        # (which hangs forever) fails here in 15s instead of stalling
        # the suite. No wall-clock assert — cold caches on a loaded CI
        # box can make a healthy init slow without anything being
        # wedged.
        print(f"init+roundtrip in {time.monotonic() - t0:.2f}s")
    finally:
        rt.shutdown()
