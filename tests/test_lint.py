"""Distributed-correctness linter tests (`ray_tpu lint`,
devtools/lint.py + rules.py) and regression tests for the four bug
classes that motivated it (ADVICE round 5: tcp_channel payload-dedup,
autoscaler request packing, worker namespace pinning, sdk num_cpus
truncation).

Every rule RT001-RT010 has a positive fixture (must fire) and a
negative fixture (must stay quiet); the repo lints itself clean — so
a new framework idiom either passes the rules or carries an explicit
`# rt: noqa[RTxxx]` reviewed in the diff.
"""

import io
import json
import os
import struct
import textwrap
import threading

import pytest

from ray_tpu.devtools.lint import lint_paths, lint_source, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fired(source: str, path: str):
    return {f.rule for f in lint_source(textwrap.dedent(source), path)}


# ---------------------------------------------------------------------------
# one positive + one negative fixture per rule
# ---------------------------------------------------------------------------

CASES = [
    # (rule, path, source, expect_fire)
    (
        "RT001",
        "serve/actor_mod.py",
        """
        import ray_tpu as rt

        @rt.remote
        class Pool:
            def gather(self, ref):
                return rt.get(ref)
        """,
        True,
    ),
    (
        "RT001",
        "serve/async_mod.py",
        """
        import ray_tpu as rt

        async def gather(ref):
            return rt.get(ref)
        """,
        True,
    ),
    (
        "RT001",
        "serve/driver_mod.py",
        """
        import ray_tpu as rt

        def gather(ref):  # plain driver-side helper: fine
            return rt.get(ref)
        """,
        False,
    ),
    (
        "RT002",
        "dag/some_channel.py",
        """
        class Chan:
            def put(self, payload):
                retry = payload == self._tx_payload  # the old bug
                return retry
        """,
        True,
    ),
    (
        "RT002",
        "dag/some_channel.py",
        """
        class Chan:
            def put(self, payload, seq):
                retry = seq == self._tx_seq  # identity, not content
                return retry
        """,
        False,
    ),
    (
        "RT003",
        "dag/proto.py",
        """
        import time

        def frame_record(data):
            return (time.time(), data)
        """,
        True,
    ),
    (
        "RT003",
        "dag/proto.py",
        """
        import time

        def frame_record(data, seq):
            deadline = time.monotonic() + 5  # local timing: fine
            return (seq, data, deadline)
        """,
        False,
    ),
    (
        "RT004",
        "_private/fork_loaded.py",
        """
        import threading

        _lock = threading.Lock()
        """,
        True,
    ),
    (
        "RT004",
        "_private/fork_loaded.py",
        """
        import threading

        def start():
            return threading.Thread(target=print)  # lazy: post-fork
        """,
        False,
    ),
    (
        "RT005",
        "autoscaler/mysdk.py",
        """
        def request_capacity(num_cpus: float = 0):
            return int(num_cpus)
        """,
        True,
    ),
    (
        "RT005",
        "autoscaler/mysdk.py",
        """
        def request_capacity(num_cpus: float = 0):
            if isinstance(num_cpus, float) and not num_cpus.is_integer():
                raise ValueError("fractional num_cpus")
            return int(num_cpus)
        """,
        False,
    ),
    (
        "RT006",
        "serve/lookup.py",
        """
        def controller(get_actor):
            return get_actor("controller", namespace="default")
        """,
        True,
    ),
    (
        # the session-context module itself may name the default
        "RT006",
        "x/ray_tpu/api.py",
        """
        def controller(get_actor):
            return get_actor("controller", namespace="default")
        """,
        False,
    ),
    (
        "RT007",
        "_private/daemon_like.py",
        """
        def _h_submit(conn, msg):
            try:
                dispatch(msg)
            except Exception:
                pass
        """,
        True,
    ),
    (
        "RT007",
        "_private/daemon_like.py",
        """
        def _h_submit(conn, msg):
            try:
                dispatch(msg)
            except Exception as e:
                conn.reply(msg["_mid"], {"_error": repr(e)})
        """,
        False,
    ),
    (
        "RT008",
        "util/sync.py",
        """
        def drain(evt):
            evt.wait()
        """,
        True,
    ),
    (
        "RT008",
        "util/sync.py",
        """
        def drain(evt):
            evt.wait(5.0)
        """,
        False,
    ),
    (
        "RT009",
        "serve/metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter, Histogram

        requests = Counter("serve.requests", tag_keys=("app",))
        latency = Histogram(
            "serve_latency_ms", tag_keys=("Deployment-Name",)
        )
        """,
        True,
    ),
    (
        "RT009",
        "serve/metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter, Histogram

        requests = Counter(
            "serve_requests_total", tag_keys=("app", "deployment")
        )
        latency = Histogram(
            "serve_latency_ms", tag_keys=("app", "deployment")
        )
        """,
        False,
    ),
    (
        "RT010",
        "serve/metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter

        requests = Counter(
            "serve_requests_total", tag_keys=("app", "request_id")
        )
        """,
        True,
    ),
    (
        "RT010",
        "llm/engine_mod.py",
        """
        from ray_tpu.util.metrics import Gauge

        def record(gauge, oid, nbytes):
            gauge.set(nbytes, tags={"object_id": oid})
        """,
        True,
    ),
    (
        "RT010",
        "serve/metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter, Histogram

        requests = Counter(
            "serve_requests_total", tag_keys=("app", "deployment")
        )

        def record(hist, job, ms):
            # job labels are bounded by design (goodput/ledger key
            # on them); ids are what RT010 rejects.
            hist.observe(ms, tags={"job": job})
        """,
        False,
    ),
    (
        # The XLA compile-series cardinality contract (ISSUE 15): a
        # per-shape-digest label mints one series per arg-shape set —
        # unbounded under exactly the recompile storm the series
        # exists to catch.
        "RT010",
        "user/compile_metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter

        compiles = Counter(
            "my_compiles_total", tag_keys=("program", "digest")
        )

        def record(hist, shape_digest, ms):
            hist.observe(ms, tags={"shape_digest": shape_digest})
        """,
        True,
    ),
    (
        # ...while the program NAME alone (a bounded registered
        # family) is the sanctioned label — the shape of
        # rt_jax_compiles_total / rt_jax_compile_ms.
        "RT010",
        "user/compile_metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter, Histogram

        compiles = Counter(
            "my_compiles_total", tag_keys=("program",)
        )

        def record(hist, program, ms):
            hist.observe(ms, tags={"program": program})
        """,
        False,
    ),
    (
        # The transfer-matrix cardinality contract (ISSUE 20): a
        # fused src-dst pair label is N^2 series no PromQL
        # aggregation can decompose; so is a per-pull flow id.
        "RT010",
        "user/transfer_metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter

        transfers = Counter(
            "my_transfer_bytes_total", tag_keys=("job", "flow")
        )

        def record(hist, src, dst, ms):
            hist.observe(ms, tags={"src_dst": src + ":" + dst})
        """,
        True,
    ),
    (
        "RT010",
        "user/transfer_metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter

        def record(counter, fid, nbytes):
            counter.inc(nbytes, tags={"flow_id": fid})
        """,
        True,
    ),
    (
        # ...while src_node / dst_node as SEPARATE labels are the
        # sanctioned shape (node granularity is bounded; either side
        # aggregates) — the shape of rt_object_transfer_bytes_total.
        "RT010",
        "user/transfer_metrics_mod.py",
        """
        from ray_tpu.util.metrics import Counter

        transfers = Counter(
            "my_transfer_bytes_total",
            tag_keys=("job", "src_node", "dst_node"),
        )

        def record(counter, job, src, dst, nbytes):
            counter.inc(
                nbytes,
                tags={"job": job, "src_node": src, "dst_node": dst},
            )
        """,
        False,
    ),
]


@pytest.mark.parametrize(
    "rule,path,source,expect",
    CASES,
    ids=[f"{c[0]}-{'fires' if c[3] else 'quiet'}-{i}" for i, c in enumerate(CASES)],
)
def test_rule_fixtures(rule, path, source, expect):
    rules = fired(source, path)
    if expect:
        assert rule in rules, f"{rule} did not fire on its fixture"
    else:
        assert rule not in rules, f"{rule} false-positived"


def test_rt002_would_have_caught_the_shipped_bug():
    """The exact dedup line tcp_channel.py shipped (pre-fix) trips
    RT002 under the real file path."""
    old_code = """
    class TcpChannel:
        def put_bytes(self, payload, timeout=None):
            if self._tx:
                retry = payload == self._tx_payload
                self._flush(sock)
                if retry:
                    self._tx_payload = None
                    return
    """
    rules = fired(old_code, "ray_tpu/dag/tcp_channel.py")
    assert "RT002" in rules


def test_rule_scoping_is_path_based():
    # Same nondeterminism source outside the replayable scope: quiet.
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert "RT003" in {f.rule for f in lint_source(src, "dag/x.py")}
    assert "RT003" not in {f.rule for f in lint_source(src, "serve/x.py")}


# ---------------------------------------------------------------------------
# suppressions / output modes / self-check
# ---------------------------------------------------------------------------


def test_noqa_suppressions():
    bad = "import threading\n_lock = threading.Lock()"
    path = "_private/m.py"
    assert {f.rule for f in lint_source(bad, path)} == {"RT004"}
    # targeted suppression
    ok = bad + "  # rt: noqa[RT004]"
    assert lint_source(ok, path) == []
    # suppression for a DIFFERENT rule does not apply — and the
    # useless suppression is itself reported (noqa hygiene, RT090).
    wrong = bad + "  # rt: noqa[RT001]"
    assert {f.rule for f in lint_source(wrong, path)} == {
        "RT004",
        "RT090",
    }
    # blanket suppression
    blanket = bad + "  # rt: noqa"
    assert lint_source(blanket, path) == []
    # multi-rule form: RT004 is suppressed, but naming RT001 — which
    # never fires on that line — is a stale suppression.
    multi = bad + "  # rt: noqa[RT001,RT004]"
    assert {f.rule for f in lint_source(multi, path)} == {"RT090"}


def test_json_output_mode(tmp_path):
    target = tmp_path / "dag" / "badchan.py"
    target.parent.mkdir()
    target.write_text(
        "def dedup(payload, prev):\n    return payload == prev\n"
    )
    out = io.StringIO()
    code = main(["--json", str(target)], out=out)
    assert code == 1
    findings = json.loads(out.getvalue())
    assert len(findings) == 1
    f = findings[0]
    assert f["rule"] == "RT002"
    assert f["path"] == str(target)
    assert f["line"] == 2
    assert "sequence number" in f["message"]


def test_rules_filter_and_errors(tmp_path):
    target = tmp_path / "dag" / "multi.py"
    target.parent.mkdir()
    target.write_text(
        "import time\n"
        "def f(payload, prev):\n"
        "    t = time.time()\n"
        "    return payload == prev, t\n"
    )
    # both rules fire unfiltered; --rules restricts to one
    unfiltered = io.StringIO()
    assert main([str(target)], out=unfiltered) == 1
    assert "RT002" in unfiltered.getvalue()
    assert "RT003" in unfiltered.getvalue()
    out = io.StringIO()
    assert main(["--rules", "RT003", str(target)], out=out) == 1
    assert "RT002" not in out.getvalue()
    assert "RT003" in out.getvalue()
    # unknown rule id and missing path are usage errors
    assert main(["--rules", "RT999", str(target)], out=io.StringIO()) == 2
    assert main([str(tmp_path / "nope.py")], out=io.StringIO()) == 2


def test_repo_lints_clean():
    """`ray_tpu lint ray_tpu/` exits 0: every intentional pattern in
    the tree carries an explicit `# rt: noqa[RTxxx]`."""
    out = io.StringIO()
    code = main([os.path.join(REPO, "ray_tpu")], out=out)
    assert code == 0, f"repo lint not clean:\n{out.getvalue()}"


def test_every_rule_has_id_title_and_doc():
    from ray_tpu.devtools.rules import ALL_RULES

    ids = [r.id for r in ALL_RULES]
    assert ids == [f"RT{i:03d}" for i in range(1, 11)]
    for rule in ALL_RULES:
        assert rule.title
        assert rule.__doc__


# ---------------------------------------------------------------------------
# regression: tcp_channel sequence-number framing (ADVICE #1)
# ---------------------------------------------------------------------------


@pytest.fixture
def tcp_pair(monkeypatch):
    """Reader/writer TcpChannel endpoints rendezvousing through an
    in-process fake KV (no cluster needed)."""
    import ray_tpu.dag.tcp_channel as tc

    kv = {}

    def fake_kv(method, **kw):
        key = (kw.get("ns"), kw["key"])
        if method == "kv_put":
            kv[key] = kw["value"]
            return {}
        if method == "kv_get":
            return {"value": kv.get(key)}
        if method == "kv_del":
            kv.pop(key, None)
            return {}
        raise AssertionError(method)

    monkeypatch.setattr(tc, "_kv_call", fake_kv)
    reader = tc.TcpChannel(1 << 16, chan_id="lint-regress")
    writer = tc.TcpChannel(1 << 16, chan_id="lint-regress")
    reader.bind_reader()
    yield reader, writer
    reader.close()
    writer.close()


def test_tcp_equal_payloads_are_distinct_records(tcp_pair):
    """The shipped bug: a put whose bytes equal the pending record was
    swallowed as a 'retry'. Equal payloads must all be delivered."""
    reader, writer = tcp_pair
    got = []

    def drain():
        for _ in range(3):
            got.append(reader.get_bytes(timeout=10))

    t = threading.Thread(target=drain)
    t.start()
    assert writer.put_bytes(b"same", timeout=5) == 0
    assert writer.put_bytes(b"same", timeout=5) == 1  # NOT deduped
    assert writer.put_bytes(b"same", timeout=5) == 2
    t.join(10)
    assert got == [b"same", b"same", b"same"]


def test_tcp_retry_token_dedups_exactly_once(tcp_pair):
    """A retry carrying the timed-out record's seq finishes delivering
    THAT record; it never queues a duplicate. (White-box: stage the
    'timed out before any byte was sent' writer state directly.)"""
    reader, writer = tcp_pair
    writer._ensure("writer", 5)
    payload = b"retry-me"
    # Stage a pending record exactly as a timed-out put leaves it.
    seq = writer._next_tx_seq
    writer._next_tx_seq += 1
    writer._tx = memoryview(
        struct.pack("<QQ", len(payload), seq) + payload
    )
    writer._tx_seq = seq
    # The retry (same payload + token) flushes the pending record once.
    assert writer.put_bytes(payload, timeout=5, seq=seq) == seq
    # A later token-less put of EQUAL bytes is a brand-new record.
    assert writer.put_bytes(payload, timeout=5) == seq + 1
    got = [reader.get_bytes(timeout=10) for _ in range(2)]
    assert got == [payload, payload]
    # Re-retrying an already-delivered token is a no-op...
    assert writer.put_bytes(payload, timeout=5, seq=seq) == seq
    # ...and an unknown (future) token is rejected loudly.
    with pytest.raises(ValueError):
        writer.put_bytes(payload, seq=writer._next_tx_seq + 7)
    # The stream stayed in sync: a fresh record still round-trips.
    writer.put(("v", 42), timeout=5)
    assert reader.get(timeout=10) == ("v", 42)


def test_execute_retry_resumes_torn_fanout():
    """A timed-out execute() leaves some input channels without its
    record; the NEXT execute() must finish that fanout exactly once
    per channel (using the transport's retry token where one was
    issued) before submitting the new record — so per-channel streams
    stay aligned with the DAG's seq accounting and nothing double-
    delivers."""
    from ray_tpu.dag.channels import ChannelTimeoutError
    from ray_tpu.dag.compiled import _WHOLE, CompiledDAG

    class FakeChan:
        def __init__(self, fail_first=False, token=None):
            self.records = []
            self.fail_first = fail_first
            self.token = token
            self.seq_retries = []

        def put(self, record, timeout=None, **kw):
            if "seq" in kw and kw["seq"] is not None:
                # retry token: the pending record completes, once.
                self.seq_retries.append(kw["seq"])
                self.records.append(record)
                return
            if self.fail_first:
                self.fail_first = False
                err = ChannelTimeoutError("put")
                err.seq = self.token
                raise err
            self.records.append(record)

    good = FakeChan()
    slow = FakeChan(fail_first=True, token=7)
    untried = FakeChan()

    class FakeOut:
        def __init__(self, records):
            self.records = list(records)

        def get(self, timeout=None):
            return self.records.pop(0)

    dag = CompiledDAG.__new__(CompiledDAG)
    dag._lock = threading.Lock()
    dag._read_mutex = threading.Lock()
    dag._submit_mutex = threading.Lock()
    dag._torn_down = False
    dag._next_seq = 0
    dag._next_read_seq = 0
    dag._results = {}
    dag._orphan_seqs = set()
    dag._pending_inputs = []
    dag._root = None  # not a MultiOutputNode: single output value
    dag._input_channels = [
        (good, _WHOLE), (slow, _WHOLE), (untried, _WHOLE)
    ]

    with pytest.raises(ChannelTimeoutError):
        dag.execute("v1", timeout=0.1)
    # good got the record; slow + untried are parked with v1's tail.
    assert [r for _, r, _ in dag._pending_inputs] == [
        ("v", "v1"), ("v", "v1")
    ]
    assert dag._pending_inputs[0][2] == 7  # slow's retry token
    assert dag._orphan_seqs == {0}  # seq 0 raised: nobody holds a ref

    ref = dag.execute("v2", timeout=5)
    assert dag._pending_inputs == []
    # Every channel saw v1 exactly once, then v2 exactly once.
    for chan in (good, slow, untried):
        assert chan.records == [("v", "v1"), ("v", "v2")], chan.records
    # slow's v1 landed via its retry token, not a duplicate record.
    assert slow.seq_retries == [7]
    # The torn execute still consumed DAG seq 0; the retry got seq 1.
    assert ref._seq == 1

    # The orphaned seq-0 output is read-and-discarded (never cached):
    # ref(1).get() skips past it and nothing leaks in _results.
    dag._output_channels = [FakeOut([("v", "r0"), ("v", "r1")])]
    assert ref.get(timeout=5) == "r1"
    assert dag._results == {}
    assert dag._orphan_seqs == set()


# ---------------------------------------------------------------------------
# regression: request_resources packs against node TOTALS (ADVICE #2)
# ---------------------------------------------------------------------------


class _FakeProvider:
    head_address = "unused"

    def __init__(self):
        self.nodes = ["n0"]
        self.created = []

    def non_terminated_nodes(self):
        return list(self.nodes)

    def node_type(self, p):
        return "cpu"

    def cluster_node_id(self, p):
        return "daemon-0"

    def create_node(self, node_type, resources, labels):
        name = f"new-{len(self.created)}"
        self.created.append(name)
        self.nodes.append(name)
        return name

    def terminate_node(self, p):
        self.nodes.remove(p)


def _autoscaler_with_busy_node():
    from ray_tpu.autoscaler.autoscaler import (
        NodeTypeConfig,
        StandardAutoscaler,
    )

    provider = _FakeProvider()
    autoscaler = StandardAutoscaler(
        provider,
        {"cpu": NodeTypeConfig(resources={"CPU": 4.0}, max_workers=5)},
        idle_timeout_s=999.0,
    )
    load = {
        "infeasible": [],
        "pending_placement_groups": [],
        # ONE live node, busy: 0.5 of its 4 CPUs available.
        "nodes": [
            {
                "node_id": "daemon-0",
                "available": {"CPU": 0.5},
                "total": {"CPU": 4.0},
                "queued": 0,
                "labels": {},
            }
        ],
        "resource_requests": [],
    }
    autoscaler._load = lambda: load
    return autoscaler, provider, load


def test_request_resources_pack_against_totals_not_available():
    """A standing {CPU:2} target on a busy 4-CPU node must NOT launch
    a new node (HandleRequestClusterResourceConstraint packs against
    totals) — and the satisfying node is held against scale-down."""
    autoscaler, provider, load = _autoscaler_with_busy_node()
    load["resource_requests"] = [{"CPU": 2.0}]
    result = autoscaler.update()
    assert result["launched"] == []
    assert result["unsatisfied_requests"] == 0
    assert provider.created == []
    assert "n0" in autoscaler._last_busy  # held (busy-marked), no flap


def test_request_resources_still_launches_when_totals_exhausted():
    autoscaler, provider, load = _autoscaler_with_busy_node()
    # 2 bundles: the first consumes half the node's TOTAL, the second
    # (4 CPUs) no longer fits any total -> exactly one launch.
    load["resource_requests"] = [{"CPU": 2.0}, {"CPU": 4.0}]
    result = autoscaler.update()
    assert len(result["launched"]) == 1
    assert result["unsatisfied_requests"] == 0


def test_task_demand_still_packs_against_available():
    """Pending TASK demand genuinely consumes capacity, so it must
    keep packing against availability: a 2-CPU infeasible task on the
    busy (0.5 CPU free) node launches a worker."""
    autoscaler, provider, load = _autoscaler_with_busy_node()
    load["infeasible"] = [{"CPU": 2.0}]
    result = autoscaler.update()
    assert len(result["launched"]) == 1


# ---------------------------------------------------------------------------
# regression: session namespace reaches workers (ADVICE #3)
# ---------------------------------------------------------------------------


def test_namespace_propagates_into_tasks_and_nested_actors():
    import ray_tpu as rt

    rt.init(num_cpus=2, namespace="apps")
    try:

        @rt.remote
        class Registry:
            def ping(self):
                return "ok"

        registry = Registry.options(name="registry").remote()
        assert rt.get(registry.ping.remote(), timeout=60) == "ok"

        @rt.remote
        def lookup():
            # No explicit namespace: must resolve in the SESSION
            # namespace, not a hardcoded "default".
            return rt.get_actor("registry").actor_id.hex()

        assert (
            rt.get(lookup.remote(), timeout=60)
            == registry.actor_id.hex()
        )

        @rt.remote
        def make_named():
            @rt.remote
            class Inner:
                def ping(self):
                    return "pong"

            handle = Inner.options(name="inner").remote()
            rt.get(handle.ping.remote(), timeout=60)
            return handle.actor_id.hex()

        inner_id = rt.get(make_named.remote(), timeout=90)
        # Registered in the session namespace...
        assert (
            rt.get_actor("inner", namespace="apps").actor_id.hex()
            == inner_id
        )
        # ...and NOT leaked into "default".
        with pytest.raises(ValueError):
            rt.get_actor("inner", namespace="default")
    finally:
        rt.shutdown()


# ---------------------------------------------------------------------------
# regression: request_resources(num_cpus=...) validation (ADVICE #4)
# ---------------------------------------------------------------------------


def test_request_resources_rejects_bad_num_cpus_up_front():
    """Validation precedes any cluster traffic (no init() needed):
    fractional and negative targets raise instead of truncating."""
    from ray_tpu.autoscaler.sdk import request_resources

    with pytest.raises(ValueError, match="whole number"):
        request_resources(num_cpus=2.5)
    with pytest.raises(ValueError, match=">= 0"):
        request_resources(num_cpus=-1)
    with pytest.raises(TypeError):
        request_resources(num_cpus="4")
    with pytest.raises(TypeError):
        request_resources(num_cpus=True)
    # Valid shapes pass validation and reach the session gate.
    for num_cpus in (None, 0, 4, 4.0):
        with pytest.raises(RuntimeError, match="init"):
            request_resources(num_cpus=num_cpus)
