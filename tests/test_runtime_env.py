"""Runtime-env tests (reference test model:
python/ray/tests/test_runtime_env*.py — env var injection + isolation,
working_dir packaging across nodes, py_modules imports, unsupported
installer fields)."""

import os

import pytest


def test_env_vars_applied_and_restored(rt_session):
    rt = rt_session

    @rt.remote(runtime_env={"env_vars": {"RT_TEST_FLAG": "on"}})
    def with_env():
        return os.environ.get("RT_TEST_FLAG")

    @rt.remote
    def without_env():
        return os.environ.get("RT_TEST_FLAG")

    assert rt.get(with_env.remote(), timeout=30) == "on"
    # Shared workers must not leak the env var into later tasks.
    assert rt.get(without_env.remote(), timeout=30) is None


def test_working_dir_ships_files(rt_session, tmp_path):
    rt = rt_session
    project = tmp_path / "proj"
    project.mkdir()
    (project / "data.txt").write_text("shipped-content")
    (project / "helper.py").write_text("VALUE = 123\n")

    @rt.remote(runtime_env={"working_dir": str(project)})
    def read_relative():
        import helper  # importable: working_dir joins sys.path

        with open("data.txt") as f:
            return f.read(), helper.VALUE

    content, value = rt.get(read_relative.remote(), timeout=30)
    assert content == "shipped-content"
    assert value == 123


def test_working_dir_cross_node(tmp_path):
    """The package travels via the cluster KV store, not a shared
    filesystem path (reference: GCS package distribution)."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_resources={"CPU": 1.0})
    try:
        cluster.add_node(num_cpus=2, resources={"special": 1.0})
        rt.init(address=cluster.address)
        project = tmp_path / "proj"
        project.mkdir()
        (project / "payload.txt").write_text("over-the-wire")

        @rt.remote(
            resources={"special": 1.0},
            runtime_env={"working_dir": str(project)},
        )
        def remote_read():
            with open("payload.txt") as f:
                return f.read()

        assert rt.get(remote_read.remote(), timeout=60) == "over-the-wire"
    finally:
        rt.shutdown()
        cluster.shutdown()


def test_py_modules(rt_session, tmp_path):
    rt = rt_session
    module_dir = tmp_path / "mylib"
    module_dir.mkdir()
    (module_dir / "__init__.py").write_text("def f():\n    return 'lib'\n")

    @rt.remote(runtime_env={"py_modules": [str(module_dir)]})
    def use_module():
        import mylib

        return mylib.f()

    assert rt.get(use_module.remote(), timeout=30) == "lib"


def test_actor_keeps_runtime_env(rt_session):
    rt = rt_session

    @rt.remote(runtime_env={"env_vars": {"ACTOR_ENV": "sticky"}})
    class Holder:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    holder = Holder.remote()
    assert rt.get(holder.read.remote(), timeout=30) == "sticky"
    assert rt.get(holder.read.remote(), timeout=30) == "sticky"


def test_conda_rejected(rt_session):
    """pip is now supported (tests/test_runtime_env_pip.py); conda/uv
    stay rejected — not installed in the image."""
    rt = rt_session
    import ray_tpu.exceptions as exc

    @rt.remote(runtime_env={"conda": ["something"]})
    def nope():
        return 1

    with pytest.raises(exc.RuntimeEnvSetupError):
        nope.remote()  # rt: noqa[RT106] — submit raises; no ref exists


def test_unknown_field_rejected(rt_session):
    rt = rt_session

    @rt.remote(runtime_env={"bogus_field": 1})
    def nope():
        return 1

    with pytest.raises(ValueError, match="bogus_field"):
        nope.remote()  # rt: noqa[RT106] — submit raises; no ref exists
