"""Decode-path tests: KV-cache forward equals the full forward, greedy
decode is self-consistent, EOS accounting works."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import LlamaConfig, forward, init_params


@pytest.fixture(scope="module")
def small_model():
    cfg = LlamaConfig(
        vocab_size=128,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,  # exercises GQA repeat
        intermediate=128,
        max_seq_len=64,
        dtype=jnp.float32,
        attention="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_cached_forward_matches_full_forward(small_model):
    from ray_tpu.models.generate import (
        _forward_with_cache,
        init_kv_cache,
    )

    cfg, params = small_model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 128)
    full_logits = forward(params, tokens, cfg)

    cache = init_kv_cache(cfg, 2, 16)
    cached_logits, cache = _forward_with_cache(
        params, cfg, tokens, cache, jnp.int32(0), jnp.int32(10)
    )
    np.testing.assert_allclose(
        np.asarray(cached_logits),
        np.asarray(full_logits),
        rtol=2e-4,
        atol=2e-4,
    )

    # Incremental: feed one more token; must equal full forward over 11.
    extra = jax.random.randint(jax.random.PRNGKey(2), (2, 1), 0, 128)
    inc_logits, _ = _forward_with_cache(
        params, cfg, extra, cache, jnp.int32(10), jnp.int32(11)
    )
    full11 = forward(
        params, jnp.concatenate([tokens, extra], axis=1), cfg
    )
    np.testing.assert_allclose(
        np.asarray(inc_logits[:, 0]),
        np.asarray(full11[:, -1]),
        rtol=2e-4,
        atol=2e-4,
    )


def test_greedy_generate_matches_stepwise_argmax(small_model):
    from ray_tpu.models.generate import generate

    cfg, params = small_model
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0, 128)
    out, lengths = generate(
        params,
        prompt,
        jnp.array([6], jnp.int32),
        cfg,
        max_new_tokens=5,
        temperature=0.0,
    )
    # Reference: grow the sequence with full forwards + argmax.
    seq = prompt
    expected = []
    for _ in range(5):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert out[0].tolist() == expected
    assert int(lengths[0]) == 5


def test_eos_stops_counting(small_model):
    from ray_tpu.models.generate import generate

    cfg, params = small_model
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0, 128)
    # Find what greedy emits first, then declare it the EOS token.
    out, _ = generate(
        params,
        prompt,
        jnp.array([4], jnp.int32),
        cfg,
        max_new_tokens=4,
        temperature=0.0,
    )
    eos = int(out[0, 0])
    out2, lengths = generate(
        params,
        prompt,
        jnp.array([4], jnp.int32),
        cfg,
        max_new_tokens=4,
        temperature=0.0,
        eos_token=eos,
    )
    assert int(lengths[0]) == 1  # EOS itself counts, then stop


def test_sampled_generate_in_vocab(small_model):
    from ray_tpu.models.generate import generate

    cfg, params = small_model
    prompt = jax.random.randint(jax.random.PRNGKey(5), (3, 5), 0, 128)
    out, lengths = generate(
        params,
        prompt,
        jnp.array([5, 5, 5], jnp.int32),
        cfg,
        max_new_tokens=8,
        temperature=0.8,
        top_k=20,
        rng=jax.random.PRNGKey(9),
    )
    assert out.shape == (3, 8)
    assert ((out >= 0) & (out < 128)).all()
    assert (lengths == 8).all()
