"""TPU accelerator-manager tests (reference test model:
python/ray/tests/accelerators/test_tpu.py — detection overrides, pod
metadata resources, slice gang reservation)."""

import pytest


def test_pod_type_parsing():
    from ray_tpu._private.accelerators.tpu import (
        TPUAcceleratorManager,
        chips_per_host,
        pod_type_num_chips,
        pod_worker_count,
    )

    assert pod_type_num_chips("v5e-16") == 16
    assert pod_type_num_chips("v4-8") == 8
    assert pod_type_num_chips("v3-32") == 16  # v2/v3 count cores
    assert chips_per_host("v5e-16") == 4
    assert chips_per_host("v5e-1") == 1
    assert pod_worker_count("v5e-16") == 4
    assert pod_worker_count("v5e-4") == 1
    assert TPUAcceleratorManager.is_valid_tpu_accelerator_type("v5e-16")
    assert not TPUAcceleratorManager.is_valid_tpu_accelerator_type("tpu-16")
    with pytest.raises(ValueError):
        pod_type_num_chips("nope")


def test_detection_env_override(monkeypatch):
    from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

    monkeypatch.setenv("RT_TPU_CHIPS", "4")
    TPUAcceleratorManager.get_current_node_num_accelerators.cache_clear()
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 4
    TPUAcceleratorManager.get_current_node_num_accelerators.cache_clear()


def test_pod_resources_and_labels(monkeypatch):
    from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

    monkeypatch.setenv("RT_TPU_POD_TYPE", "v5e-16")
    monkeypatch.setenv("RT_TPU_NAME", "my-slice")
    monkeypatch.setenv("RT_TPU_WORKER_ID", "0")
    resources, labels = (
        TPUAcceleratorManager.get_extra_resources_and_labels(4)
    )
    assert resources["TPU-v5e-16-head"] == 1.0
    assert resources["my-slice"] == 1.0
    assert labels["rt.io/tpu-pod-type"] == "v5e-16"
    assert labels["rt.io/tpu-worker-id"] == "0"

    # Non-zero workers don't claim the head marker.
    monkeypatch.setenv("RT_TPU_WORKER_ID", "2")
    resources, _ = TPUAcceleratorManager.get_extra_resources_and_labels(4)
    assert "TPU-v5e-16-head" not in resources
    assert resources["my-slice"] == 1.0


def test_slice_gang_reservation():
    """A fake 4-host v5e-16 slice is gang-reserved by a STRICT_SPREAD
    placement group over its per-host pod-name resources."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import PlacementGroupSchedulingStrategy
    from ray_tpu.util.accelerators.tpu import slice_placement_group

    cluster = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    try:
        for _ in range(4):
            cluster.add_node(
                num_cpus=1,
                resources={"TPU": 4.0, "my-slice": 1.0},
                labels={"rt.io/tpu-pod-name": "my-slice"},
            )
        rt.init(address=cluster.address)
        pg = slice_placement_group("v5e-16", pod_name="my-slice")
        assert pg.bundle_count == 4
        assert pg.wait(15)

        @rt.remote(num_cpus=0)
        def host_id():
            import os

            return os.environ.get("RT_SOCKET", "")

        sockets = rt.get(
            [
                host_id.options(
                    resources={"TPU": 1.0},
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=i,
                    ),
                ).remote()
                for i in range(4)
            ],
            timeout=60,
        )
        assert len(set(sockets)) == 4
    finally:
        rt.shutdown()
        cluster.shutdown()
