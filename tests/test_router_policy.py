"""Router policy + load-accounting tests (ISSUE 11): least-
outstanding-tokens beats round-robin under a skewed mix, the
outstanding-token estimate is released on every stream exit path
(the phantom-load regression: abandon/cancel and engine/replica
death must not leave ghost load pinned on a replica), and SLO
admission sheds when every candidate is over threshold.

These drive the DeploymentHandle's accounting surface directly — no
cluster — so the invariants run in milliseconds."""

import pytest

import ray_tpu.serve.router as router
from ray_tpu.serve.router import (
    DEFAULT_TOKEN_ESTIMATE,
    DeploymentHandle,
    DeploymentOverloaded,
    DeploymentResponseGenerator,
    estimate_request_tokens,
    pick_least_outstanding,
)


@pytest.fixture(autouse=True)
def _fresh_config_cache():
    """The router caches Config.from_env() process-wide (hot path);
    tests that monkeypatch RT_serve_* need a fresh read, and must not
    leak their config into later tests in the same process."""
    router._reset_config_cache()
    yield
    router._reset_config_cache()


# ---------------------------------------------------------------------
# token estimation
# ---------------------------------------------------------------------

def test_estimate_from_llm_payload():
    payload = {"prompt": list(range(40)), "max_new_tokens": 16}
    assert estimate_request_tokens((payload,), {}) == 56


def test_estimate_from_request_body():
    class FakeRequest:
        def json(self):
            return {"prompt": [1, 2, 3], "max_new_tokens": 7}

    assert estimate_request_tokens((FakeRequest(),), {}) == 10


def test_estimate_prompt_without_budget_adds_default():
    payload = {"prompt": [1, 2, 3]}
    assert (
        estimate_request_tokens((payload,), {})
        == 3 + DEFAULT_TOKEN_ESTIMATE
    )


def test_estimate_falls_back_for_opaque_payloads():
    assert estimate_request_tokens((), {}) == DEFAULT_TOKEN_ESTIMATE
    assert (
        estimate_request_tokens(("not a dict",), {})
        == DEFAULT_TOKEN_ESTIMATE
    )
    assert (
        estimate_request_tokens(({"x": 1},), {})
        == DEFAULT_TOKEN_ESTIMATE
    )


# ---------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------

def test_pick_least_outstanding_prefers_min_load():
    replicas = [{"id": "a"}, {"id": "b"}, {"id": "c"}]
    outstanding = {"a": 500, "b": 20, "c": 100}
    assert pick_least_outstanding(replicas, outstanding)["id"] == "b"
    # Missing entries count as zero load.
    outstanding = {"a": 1, "c": 1}
    assert pick_least_outstanding(replicas, outstanding)["id"] == "b"


def test_pick_least_outstanding_breaks_ties_across_replicas():
    replicas = [{"id": "a"}, {"id": "b"}]
    seen = {
        pick_least_outstanding(replicas, {})["id"] for _ in range(200)
    }
    assert seen == {"a", "b"}  # idle replicas share cold traffic


def test_least_tokens_beats_round_robin_under_skewed_mix():
    """ISSUE 11 satellite: a skewed mix (long completions interleaved
    with short chats) round-robined across 2 replicas piles every
    long request onto one of them; least-outstanding-tokens balances
    assigned WORK, so the busiest replica ends up with far less of
    it (lower makespan = lower queueing delay at equal throughput)."""
    heavy, light = 200, 10
    costs = [heavy, light] * 20

    round_robin = [0, 0]
    for i, cost in enumerate(costs):
        round_robin[i % 2] += cost

    replicas = [{"id": "r0"}, {"id": "r1"}]
    least = {"r0": 0, "r1": 0}
    for cost in costs:
        pick = pick_least_outstanding(replicas, least)
        least[pick["id"]] += cost

    assert max(round_robin) == 20 * heavy  # all longs on one replica
    assert max(least.values()) < 0.6 * max(round_robin)


# ---------------------------------------------------------------------
# phantom-load regression: every exit path releases the estimate
# ---------------------------------------------------------------------

def _handle():
    return DeploymentHandle("app", "dep")


def test_stream_chunks_decay_outstanding_tokens():
    handle = _handle()
    handle._ongoing_sent("r1", 10)
    gen = DeploymentResponseGenerator(
        iter(()), handle, "r1", tokens=10
    )
    # Simulate 4 delivered chunks' worth of decay.
    for _ in range(4):
        gen._tokens_left -= 1
        handle._tokens_done("r1", 1)
    assert handle._outstanding_tokens["r1"] == 6
    gen.close()  # releases the remainder exactly once
    assert handle._outstanding_tokens.get("r1", 0) == 0
    gen.close()  # idempotent
    assert handle._outstanding_tokens.get("r1", 0) == 0


def test_abandoned_stream_releases_full_estimate():
    """The PR 10 cancel path frees the engine's KV slot mid-decode;
    the router-side outstanding-token estimate must follow (ISSUE 11
    phantom-load fix), or the replica looks loaded forever."""
    handle = _handle()
    handle._ongoing_sent("r1", 464)
    gen = DeploymentResponseGenerator(
        iter(()), handle, "r1", tokens=464
    )
    gen.close()  # client disconnected before any chunk
    assert handle._outstanding_tokens.get("r1", 0) == 0
    assert handle._ongoing.get("r1") == 0


def test_membership_prune_clears_dead_replica_load():
    """Engine/replica death: the controller pushes a membership
    without the dead id; its accounting entries must vanish so the
    replacement replica doesn't inherit phantom load."""
    handle = _handle()
    handle._ongoing_sent("dead", 500)
    handle._ongoing_sent("live", 30)
    handle._state["replicas"] = [{"id": "live"}]
    with handle._lock:
        handle._prune_gone_locked()
    assert "dead" not in handle._outstanding_tokens
    assert "dead" not in handle._ongoing
    assert handle._outstanding_tokens["live"] == 30


def test_response_result_releases_tokens_once():
    handle = _handle()
    handle._ongoing_sent("r1", 64)
    from ray_tpu.serve.router import DeploymentResponse

    response = DeploymentResponse(lambda timeout: "ok", handle)
    response._replica_id = "r1"
    response._tokens = 64
    assert response.result() == "ok"
    assert handle._outstanding_tokens.get("r1", 0) == 0
    assert response.result() == "ok"  # second resolve: no double free
    assert handle._outstanding_tokens.get("r1", 0) == 0


def test_dropped_response_releases_estimate_on_gc():
    """Review-caught leak: a non-streaming response fired and DROPPED
    (never .result()-ed) must not pin its token estimate on the
    replica forever — a handful of dropped requests would otherwise
    push the least-loaded replica over the SLO threshold and 503
    everything after."""
    from ray_tpu.serve.router import DeploymentResponse

    handle = _handle()
    handle._ongoing_sent("r1", 500)
    response = DeploymentResponse(lambda timeout: "ok", handle)
    response._replica_id = "r1"
    response._tokens = 500
    del response  # GC without result()
    assert handle._outstanding_tokens.get("r1", 0) == 0
    assert handle._ongoing.get("r1") == 0


# ---------------------------------------------------------------------
# SLO admission
# ---------------------------------------------------------------------

def test_slo_admission_sheds_over_threshold(monkeypatch):
    monkeypatch.setenv("RT_serve_slo_queue_threshold_tokens", "100")
    handle = _handle()
    handle._ongoing_sent("r1", 150)
    with pytest.raises(DeploymentOverloaded):
        handle._slo_admit({"id": "r1"}, 10)


def test_slo_admission_admits_under_threshold(monkeypatch):
    monkeypatch.setenv("RT_serve_slo_queue_threshold_tokens", "100")
    handle = _handle()
    handle._ongoing_sent("r1", 99)
    handle._slo_admit({"id": "r1"}, 10)  # no raise


def test_slo_admission_kill_switch(monkeypatch):
    monkeypatch.setenv("RT_serve_slo_queue_threshold_tokens", "100")
    monkeypatch.setenv("RT_serve_slo_admission_enabled", "0")
    handle = _handle()
    handle._ongoing_sent("r1", 10_000)
    handle._slo_admit({"id": "r1"}, 10)  # disabled: no raise
