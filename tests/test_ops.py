"""Numerical tests for the compute ops. The Pallas kernels run in
interpreter mode on CPU (tiling/precision semantics preserved), so
these validate the same code path that runs on TPU."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ray_tpu.ops import (
    apply_rotary,
    flash_attention,
    mha_reference,
    ring_attention,
    rms_norm,
    rotary_embedding,
    swiglu,
    ulysses_attention,
)
from ray_tpu.parallel import MeshSpec


def _qkv(key, b=1, h=2, t=256, d=128, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, d), dtype)
    k = jax.random.normal(kk, (b, h, t, d), dtype)
    v = jax.random.normal(kv, (b, h, t, d), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128,
            force_pallas=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_multiple_kv_blocks(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), t=512)
        ref = mha_reference(q, k, v, causal=True)
        out = flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128,
            force_pallas=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(2), h=1, t=256)

        def loss_flash(q, k, v):
            out = flash_attention(
                q, k, v, causal=causal, block_q=128, block_k=128,
                force_pallas=True,
            )
            return jnp.sum(out * out)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
                err_msg=f"d{name} mismatch",
            )

    @pytest.mark.parametrize("blocks", [(128, 256), (256, 128)])
    def test_gradients_unequal_blocks(self, blocks):
        """Non-square tiles take the slow masking path and have no
        exact-diagonal structure — the regime where any square-block
        assumption in the fused backward (per-tile scale placement,
        bias fast path gating) breaks (review r5 finding)."""
        bq, bk = blocks
        q, k, v = _qkv(jax.random.PRNGKey(7), h=1, t=256)

        def loss_flash(q, k, v):
            out = flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk,
                force_pallas=True,
            )
            return jnp.sum(out * out)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
                err_msg=f"d{name} mismatch (bq={bq}, bk={bk})",
            )

    def test_bf16_inputs(self):
        q, k, v = _qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
        ref = mha_reference(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
        )
        out = flash_attention(
            q, k, v, block_q=128, block_k=128, force_pallas=True
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), np.asarray(ref),
            atol=2e-2, rtol=2e-2,
        )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = MeshSpec(sp=8).build()
        b, h, t, d = 1, 2, 128, 32
        q, k, v = _qkv(jax.random.PRNGKey(4), b=b, h=h, t=t, d=d)
        ref = mha_reference(q, k, v, causal=causal)
        out = shard_map(
            partial(ring_attention, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_grad_flows(self):
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = MeshSpec(sp=8).build()
        q, k, v = _qkv(jax.random.PRNGKey(5), t=64, d=16)

        @jax.jit
        def loss(q, k, v):
            out = shard_map(
                partial(ring_attention, axis_name="sp", causal=True),
                mesh=mesh,
                in_specs=P(None, None, "sp", None),
                out_specs=P(None, None, "sp", None),
                check_vma=False,
            )(q, k, v)
            return jnp.sum(out**2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def ref_loss(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(grads, ref_grads):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), atol=1e-4, rtol=1e-4
            )


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = MeshSpec(sp=8).build()
        b, h, t, d = 1, 8, 128, 32  # heads divisible by sp
        q, k, v = _qkv(jax.random.PRNGKey(6), b=b, h=h, t=t, d=d)
        ref = mha_reference(q, k, v, causal=causal)
        out = shard_map(
            partial(ulysses_attention, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )


class TestNorms:
    def test_rms_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
        w = jnp.ones(64) * 2.0
        out = rms_norm(x, w)
        expected = (
            x / np.sqrt(np.mean(np.asarray(x) ** 2, -1, keepdims=True) + 1e-6)
        ) * 2.0
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    def test_rope_rotation_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 16, 64))
        pos = jnp.arange(16)[None, :]
        cos, sin = rotary_embedding(pos, 64)
        out = apply_rotary(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1),
            atol=1e-4,
        )

    def test_rope_position_zero_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, 32))
        cos, sin = rotary_embedding(jnp.zeros((1, 1)), 32)
        out = apply_rotary(x, cos, sin)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)

    def test_swiglu(self):
        x = jnp.array([1.0, 2.0])
        g = jnp.array([0.0, 10.0])
        out = swiglu(x, g)
        np.testing.assert_allclose(
            np.asarray(out), [0.0, 2.0 * 10.0 / (1 + np.exp(-10.0))],
            rtol=1e-5,
        )


class TestFlashAttentionPadding:
    """Sequence lengths not divisible by block sizes must be exact
    (kernels mask padded KV columns and padded q rows)."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("t", [100, 300])
    def test_ragged_lengths_forward_and_grad(self, causal, t):
        q, k, v = _qkv(jax.random.PRNGKey(7), h=1, t=t, d=128)

        def loss_flash(q, k, v):
            out = flash_attention(
                q, k, v, causal=causal, block_q=128, block_k=128,
                force_pallas=True,
            )
            return jnp.sum(out * out)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        np.testing.assert_allclose(
            float(loss_flash(q, k, v)), float(loss_ref(q, k, v)),
            rtol=1e-4,
        )
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-3, rtol=1e-3,
                err_msg=f"d{name}",
            )
