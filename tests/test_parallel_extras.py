"""Pipeline-parallel + MoE/expert-parallel tests on the virtual
8-device CPU mesh (test model per SURVEY.md §4: hermetic sharding
coverage without TPU hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def _mesh(axes):
    devices = np.array(jax.devices()[: np.prod(list(axes.values()))])
    return Mesh(devices.reshape(tuple(axes.values())), tuple(axes))


def test_spmd_pipeline_matches_sequential():
    from ray_tpu.parallel.pipeline import (
        broadcast_from_last_stage,
        spmd_pipeline,
        stack_stage_params,
    )

    n_stages, num_mb, mb, d = 4, 8, 2, 16
    mesh = _mesh({"pp": n_stages})
    key = jax.random.PRNGKey(0)
    stages = []
    for i in range(n_stages):
        k1, k2, key = jax.random.split(key, 3)
        stages.append(
            {
                "w": jax.random.normal(k1, (d, d)) * 0.3,
                "b": jax.random.normal(k2, (d,)) * 0.1,
            }
        )
    stacked = stack_stage_params(stages)
    x = jax.random.normal(key, (num_mb, mb, d))

    def stage_fn(params, h):
        return jnp.tanh(h @ params["w"] + params["b"])

    def pipelined(params, microbatches):
        out = spmd_pipeline(stage_fn, params, microbatches)
        return broadcast_from_last_stage(out)

    run = jax.jit(
        shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P("pp"), P()),
            out_specs=P(),
        )
    )
    got = run(stacked, x)

    expected = x
    for params in stages:
        expected = jnp.tanh(expected @ params["w"] + params["b"])
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_differentiable():
    from ray_tpu.parallel.pipeline import (
        broadcast_from_last_stage,
        spmd_pipeline,
        stack_stage_params,
    )

    n_stages, num_mb, mb, d = 2, 4, 2, 8
    mesh = _mesh({"pp": n_stages})
    key = jax.random.PRNGKey(1)
    stages = [
        {"w": jax.random.normal(jax.random.fold_in(key, i), (d, d)) * 0.3}
        for i in range(n_stages)
    ]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(key, (num_mb, mb, d))

    def stage_fn(params, h):
        return jnp.tanh(h @ params["w"])

    def loss_fn(params, microbatches):
        out = spmd_pipeline(stage_fn, params, microbatches)
        out = broadcast_from_last_stage(out)
        return jnp.mean(out**2)

    def sequential_loss(params_list, microbatches):
        h = microbatches
        for p in params_list:
            h = jnp.tanh(h @ p["w"])
        return jnp.mean(h**2)

    # checked_shard_map: jax 0.4's replication checker rejects the
    # (correct) ppermute-transpose grad program; the helper disables
    # the check only there.
    from ray_tpu.parallel.sharding import checked_shard_map

    sharded_loss = jax.jit(
        checked_shard_map(loss_fn, mesh, (P("pp"), P()), P())
    )
    grads = jax.grad(lambda p: sharded_loss(p, x))(stacked)
    ref_grads = jax.grad(lambda ps: sequential_loss(ps, x))(stages)
    for i in range(n_stages):
        np.testing.assert_allclose(
            np.asarray(grads["w"][i]),
            np.asarray(ref_grads[i]["w"]),
            rtol=2e-4,
            atol=2e-5,
        )


def test_moe_dense_routes_topk():
    from ray_tpu.ops.moe import init_moe_params, moe_ffn_dense

    params = init_moe_params(jax.random.PRNGKey(0), 4, 16, 32)
    x = jax.random.normal(jax.random.PRNGKey(1), (10, 16))
    out, aux = moe_ffn_dense(params, x, k=2)
    assert out.shape == (10, 16)
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0


def test_moe_expert_parallel_matches_dense():
    """EP sharded MoE == dense MoE when capacity never overflows."""
    from ray_tpu.ops.moe import (
        init_moe_params,
        moe_ffn_dense,
        moe_ffn_ep,
    )

    ep, e_local, d, ff = 4, 2, 16, 32
    num_experts = ep * e_local
    t_local = 8
    mesh = _mesh({"ep": ep})
    params = init_moe_params(
        jax.random.PRNGKey(0), num_experts, d, ff
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (ep * t_local, d))

    def ep_fn(router, w_in, w_out, tokens):
        out, aux = moe_ffn_ep(
            {"router": router, "w_in": w_in, "w_out": w_out},
            tokens,
            k=2,
            capacity_factor=float(num_experts),  # no drops
        )
        return out

    run = jax.jit(
        shard_map(
            ep_fn,
            mesh=mesh,
            in_specs=(P(), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep"),
        )
    )
    got = run(params["router"], params["w_in"], params["w_out"], x)

    # Dense reference per token shard (routing is per-token, so the
    # shard split doesn't change assignments).
    want, _ = moe_ffn_dense(params, x, k=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_moe_ep_sharded_gradients_finite():
    from ray_tpu.ops.moe import init_moe_params, moe_ffn_ep

    ep, d, ff = 4, 8, 16
    mesh = _mesh({"ep": ep})
    params = init_moe_params(jax.random.PRNGKey(0), 8, d, ff)
    x = jax.random.normal(jax.random.PRNGKey(1), (32, d))

    def loss(router, w_in, w_out, tokens):
        out, aux = moe_ffn_ep(
            {"router": router, "w_in": w_in, "w_out": w_out},
            tokens,
            k=2,
        )
        from jax import lax

        return lax.pmean(jnp.mean(out**2) + 0.01 * aux, "ep")

    run = shard_map(
        loss,
        mesh=mesh,
        in_specs=(P(), P("ep"), P("ep"), P("ep")),
        out_specs=P(),
    )
    grads = jax.jit(
        jax.grad(
            lambda r, wi, wo: run(r, wi, wo, x), argnums=(0, 1, 2)
        )
    )(params["router"], params["w_in"], params["w_out"])
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0