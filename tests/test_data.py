"""Data tests (reference test model: python/ray/data/tests/ — lazy
transforms, shuffles, file IO round-trips, streaming split)."""

import numpy as np
import pytest


def test_range_map_filter_count(rt_session):
    from ray_tpu import data

    ds = (
        data.range(1000, parallelism=8)
        .map(lambda row: {"id": row["id"], "double": row["id"] * 2})
        .filter(lambda row: row["id"] % 10 == 0)
    )
    assert ds.count() == 100
    rows = ds.take(3)
    assert rows[0] == {"id": 0, "double": 0}


def test_map_batches_numpy(rt_session):
    from ray_tpu import data

    ds = data.range(256, parallelism=4).map_batches(
        lambda batch: {"sq": batch["id"] ** 2},
        batch_size=64,
        batch_format="numpy",
    )
    out = ds.to_numpy()
    np.testing.assert_array_equal(
        out["sq"], np.arange(256) ** 2
    )


def test_flat_map_and_limit(rt_session):
    from ray_tpu import data

    ds = data.from_items([1, 2, 3]).flat_map(
        lambda row: [
            {"v": row["item"]},
            {"v": row["item"] * 10},
        ]
    )
    assert [r["v"] for r in ds.take_all()] == [1, 10, 2, 20, 3, 30]
    assert data.range(100).limit(7).count() == 7


def test_repartition_and_shuffle(rt_session):
    from ray_tpu import data

    ds = data.range(100, parallelism=2).repartition(5).materialize()
    assert ds.num_blocks() == 5
    assert ds.count() == 100

    shuffled = data.range(50, parallelism=4).random_shuffle(seed=7)
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))


def test_sort(rt_session):
    from ray_tpu import data

    rng = np.random.default_rng(0)
    values = rng.permutation(200).tolist()
    ds = data.from_items(
        [{"v": v} for v in values], parallelism=4
    ).sort("v")
    out = [r["v"] for r in ds.take_all()]
    assert out == sorted(values)
    desc = (
        data.from_items([{"v": v} for v in values], parallelism=4)
        .sort("v", descending=True)
        .take_all()
    )
    assert [r["v"] for r in desc] == sorted(values, reverse=True)


def test_groupby_aggregations(rt_session):
    from ray_tpu import data

    ds = data.range(100, parallelism=4).map(
        lambda row: {"key": row["id"] % 3, "value": row["id"]}
    )
    counts = {
        r["key"]: r["count"]
        for r in ds.groupby("key").count().take_all()
    }
    assert counts == {0: 34, 1: 33, 2: 33}
    means = {
        r["key"]: r["mean(value)"]
        for r in ds.groupby("key").mean("value").take_all()
    }
    assert means[0] == pytest.approx(49.5)


def test_file_round_trips(rt_session, tmp_path):
    from ray_tpu import data

    ds = data.range(64, parallelism=2).map(
        lambda row: {"id": row["id"], "name": f"row{row['id']}"}
    )
    for fmt, reader in [
        ("csv", data.read_csv),
        ("json", data.read_json),
        ("parquet", data.read_parquet),
    ]:
        out_dir = str(tmp_path / fmt)
        getattr(ds, f"write_{fmt}")(out_dir)
        back = reader(out_dir)
        rows = sorted(back.take_all(), key=lambda r: r["id"])
        assert len(rows) == 64
        assert rows[5]["name"] == "row5"


def test_streaming_split_disjoint_and_complete(rt_session):
    from ray_tpu import data

    ds = data.range(300, parallelism=6)
    its = ds.streaming_split(3, equal=True)
    seen = [
        {row["id"] for row in it.iter_rows()} for it in its
    ]
    assert set().union(*seen) == set(range(300))
    assert sum(len(s) for s in seen) == 300  # disjoint


def test_train_dataset_integration_local(rt_session):
    """datasets= flows into the trainer and surfaces as a per-rank
    streaming shard (reference: DataConfig streaming split into
    train.get_dataset_shard)."""
    from ray_tpu import data, train

    ds = data.range(128, parallelism=4)

    def loop(config):
        shard = train.get_dataset_shard("train")
        total = 0
        count = 0
        for batch in shard.iter_batches(batch_size=32):
            total += int(batch["id"].sum())
            count += len(batch["id"])
        train.report({"total": total, "count": count})

    result = train.JaxTrainer(
        loop, train_loop_config={}, datasets={"train": ds}
    ).fit()
    assert result.error is None
    assert result.metrics["count"] == 128
    assert result.metrics["total"] == sum(range(128))


def test_train_dataset_integration_gang(rt_session):
    from ray_tpu import data, train

    ds = data.range(120, parallelism=6)

    def loop(config):
        shard = train.get_dataset_shard("train")
        ids = [row["id"] for row in shard.iter_rows()]
        train.report({"n": len(ids), "sum": sum(ids)})

    result = train.JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=train.ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}
        ),
        backend=train.CpuTestBackend(),
        datasets={"train": ds},
    ).fit()
    # Trainer wires shards to every rank; the gang result carries
    # rank 0's metrics only, but both shards together cover the data.
    assert result.error is None
    assert 0 < result.metrics["n"] < 120


def test_iter_batches_sizes(rt_session):
    from ray_tpu import data

    batches = list(
        data.range(100, parallelism=3).iter_batches(
            batch_size=32, batch_format="numpy"
        )
    )
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    all_ids = np.concatenate([b["id"] for b in batches])
    np.testing.assert_array_equal(np.sort(all_ids), np.arange(100))
