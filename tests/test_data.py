"""Data tests (reference test model: python/ray/data/tests/ — lazy
transforms, shuffles, file IO round-trips, streaming split)."""

import numpy as np
import pytest


def test_range_map_filter_count(rt_session):
    from ray_tpu import data

    ds = (
        data.range(1000, parallelism=8)
        .map(lambda row: {"id": row["id"], "double": row["id"] * 2})
        .filter(lambda row: row["id"] % 10 == 0)
    )
    assert ds.count() == 100
    rows = ds.take(3)
    assert rows[0] == {"id": 0, "double": 0}


def test_map_batches_numpy(rt_session):
    from ray_tpu import data

    ds = data.range(256, parallelism=4).map_batches(
        lambda batch: {"sq": batch["id"] ** 2},
        batch_size=64,
        batch_format="numpy",
    )
    out = ds.to_numpy()
    np.testing.assert_array_equal(
        out["sq"], np.arange(256) ** 2
    )


def test_flat_map_and_limit(rt_session):
    from ray_tpu import data

    ds = data.from_items([1, 2, 3]).flat_map(
        lambda row: [
            {"v": row["item"]},
            {"v": row["item"] * 10},
        ]
    )
    assert [r["v"] for r in ds.take_all()] == [1, 10, 2, 20, 3, 30]
    assert data.range(100).limit(7).count() == 7


def test_repartition_and_shuffle(rt_session):
    from ray_tpu import data

    ds = data.range(100, parallelism=2).repartition(5).materialize()
    assert ds.num_blocks() == 5
    assert ds.count() == 100

    shuffled = data.range(50, parallelism=4).random_shuffle(seed=7)
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))


def test_sort(rt_session):
    from ray_tpu import data

    rng = np.random.default_rng(0)
    values = rng.permutation(200).tolist()
    ds = data.from_items(
        [{"v": v} for v in values], parallelism=4
    ).sort("v")
    out = [r["v"] for r in ds.take_all()]
    assert out == sorted(values)
    desc = (
        data.from_items([{"v": v} for v in values], parallelism=4)
        .sort("v", descending=True)
        .take_all()
    )
    assert [r["v"] for r in desc] == sorted(values, reverse=True)


def test_groupby_aggregations(rt_session):
    from ray_tpu import data

    ds = data.range(100, parallelism=4).map(
        lambda row: {"key": row["id"] % 3, "value": row["id"]}
    )
    counts = {
        r["key"]: r["count"]
        for r in ds.groupby("key").count().take_all()
    }
    assert counts == {0: 34, 1: 33, 2: 33}
    means = {
        r["key"]: r["mean(value)"]
        for r in ds.groupby("key").mean("value").take_all()
    }
    assert means[0] == pytest.approx(49.5)


def test_file_round_trips(rt_session, tmp_path):
    from ray_tpu import data

    ds = data.range(64, parallelism=2).map(
        lambda row: {"id": row["id"], "name": f"row{row['id']}"}
    )
    for fmt, reader in [
        ("csv", data.read_csv),
        ("json", data.read_json),
        ("parquet", data.read_parquet),
    ]:
        out_dir = str(tmp_path / fmt)
        getattr(ds, f"write_{fmt}")(out_dir)
        back = reader(out_dir)
        rows = sorted(back.take_all(), key=lambda r: r["id"])
        assert len(rows) == 64
        assert rows[5]["name"] == "row5"


def test_streaming_split_disjoint_and_complete(rt_session):
    from ray_tpu import data

    ds = data.range(300, parallelism=6)
    its = ds.streaming_split(3, equal=True)
    seen = [
        {row["id"] for row in it.iter_rows()} for it in its
    ]
    assert set().union(*seen) == set(range(300))
    assert sum(len(s) for s in seen) == 300  # disjoint


def test_train_dataset_integration_local(rt_session):
    """datasets= flows into the trainer and surfaces as a per-rank
    streaming shard (reference: DataConfig streaming split into
    train.get_dataset_shard)."""
    from ray_tpu import data, train

    ds = data.range(128, parallelism=4)

    def loop(config):
        shard = train.get_dataset_shard("train")
        total = 0
        count = 0
        for batch in shard.iter_batches(batch_size=32):
            total += int(batch["id"].sum())
            count += len(batch["id"])
        train.report({"total": total, "count": count})

    result = train.JaxTrainer(
        loop, train_loop_config={}, datasets={"train": ds}
    ).fit()
    assert result.error is None
    assert result.metrics["count"] == 128
    assert result.metrics["total"] == sum(range(128))


def test_train_dataset_integration_gang(rt_session):
    from ray_tpu import data, train

    ds = data.range(120, parallelism=6)

    def loop(config):
        shard = train.get_dataset_shard("train")
        ids = [row["id"] for row in shard.iter_rows()]
        train.report({"n": len(ids), "sum": sum(ids)})

    result = train.JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=train.ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}
        ),
        backend=train.CpuTestBackend(),
        datasets={"train": ds},
    ).fit()
    # Trainer wires shards to every rank; the gang result carries
    # rank 0's metrics only, but both shards together cover the data.
    assert result.error is None
    assert 0 < result.metrics["n"] < 120


def test_iter_batches_sizes(rt_session):
    from ray_tpu import data

    batches = list(
        data.range(100, parallelism=3).iter_batches(
            batch_size=32, batch_format="numpy"
        )
    )
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    all_ids = np.concatenate([b["id"] for b in batches])
    np.testing.assert_array_equal(np.sort(all_ids), np.arange(100))


def _prefetch_threads():
    import threading

    return [
        t
        for t in threading.enumerate()
        if t.name.startswith("rt-data-prefetch") and t.is_alive()
    ]


def test_iter_batches_prefetch_matches_serial(rt_session):
    """prefetch_batches=k must be invisible in the output: identical
    batch boundaries, identical values, identical order vs the serial
    iterator — for full batches and the drop_last tail alike."""
    from ray_tpu import data

    def build():
        return data.range(100, parallelism=3)

    for drop_last in (False, True):
        serial = list(
            build().iter_batches(batch_size=32, drop_last=drop_last)
        )
        prefetched = list(
            build().iter_batches(
                batch_size=32, drop_last=drop_last, prefetch_batches=3
            )
        )
        assert len(serial) == len(prefetched)
        for s, p in zip(serial, prefetched):
            np.testing.assert_array_equal(s["id"], p["id"])
    assert not _prefetch_threads(), "prefetch thread outlived iteration"


def test_iter_batches_prefetch_zero_is_serial_path(rt_session):
    """prefetch_batches=0 must behave exactly like today's iterator:
    same sizes, same values, and no background thread at all."""
    from ray_tpu import data

    batches = []
    for batch in data.range(100, parallelism=3).iter_batches(
        batch_size=32, prefetch_batches=0
    ):
        batches.append(batch)
        # The serial path never starts a producer thread, even while
        # the stream is being consumed.
        assert not _prefetch_threads()
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]


def test_iter_batches_prefetch_early_break_no_leaks(rt_session):
    """Breaking out of a prefetching iterator mid-stream must cancel
    the producer: no leaked rt-data-prefetch threads, and the block
    get in flight completes instead of dangling."""
    import time

    from ray_tpu import data

    ds = data.range(400, parallelism=8)
    seen = []
    for batch in ds.iter_batches(batch_size=16, prefetch_batches=4):
        seen.append(batch["id"][0])
        if len(seen) >= 2:
            break  # generator close -> producer cancel
    assert len(seen) == 2
    deadline = time.time() + 5.0
    while _prefetch_threads() and time.time() < deadline:
        time.sleep(0.05)
    assert not _prefetch_threads(), (
        f"leaked prefetch threads: {_prefetch_threads()}"
    )
    # The session still works after the cancelled stream (no dangling
    # gets poisoning the runtime).
    import ray_tpu as rt

    assert rt.get(rt.put(41), timeout=30) == 41


def test_iter_batches_prefetch_propagates_udf_error(rt_session):
    """An exception raised by upstream block tasks must re-raise at
    the consumer's next(), not vanish into the producer thread."""
    import pytest as _pytest

    from ray_tpu import data

    def explode(row):
        if row["id"] == 37:
            raise ValueError("bad row 37")
        return row

    ds = data.range(64, parallelism=4).map(explode)
    with _pytest.raises(Exception, match="bad row 37"):
        for _ in ds.iter_batches(batch_size=8, prefetch_batches=2):
            pass
    assert not _prefetch_threads()


def test_streaming_split_iterator_prefetch(rt_session):
    """DataIterator.iter_batches honours the same prefetch contract
    (this is the object train workers consume via
    get_dataset_shard)."""
    from ray_tpu import data

    ds = data.range(120, parallelism=6)
    (it,) = ds.streaming_split(1)
    serial_ids = np.sort(
        np.concatenate(
            [
                b["id"]
                for b in data.range(120, parallelism=6).iter_batches(
                    batch_size=25
                )
            ]
        )
    )
    pre = list(it.iter_batches(batch_size=25, prefetch_batches=2))
    got = np.sort(np.concatenate([b["id"] for b in pre]))
    np.testing.assert_array_equal(got, serial_ids)
    assert [len(b["id"]) for b in pre] == [25, 25, 25, 25, 20]
    assert not _prefetch_threads()


def test_iter_block_refs_pull_ahead(rt_session):
    """iter_block_refs(prefetch=n) yields the same refs in the same
    order as the serial ref stream."""
    from ray_tpu import data

    import ray_tpu as rt

    ds = data.range(60, parallelism=6).materialize()
    serial = [rt.get(r) for r in ds.iter_block_refs()]
    ahead = [rt.get(r) for r in ds.iter_block_refs(prefetch=3)]
    assert serial == ahead
    assert not _prefetch_threads()


def test_byte_budget_backpressure_skewed_flat_map():
    """Bytes-budget backpressure (reference: _internal/execution/
    backpressure_policy/ resource-based policy): a skewed flat_map
    whose outputs balloon to ~4 MB/block must keep its in-flight
    sealed bytes under the configured budget — submission throttles on
    observed block sizes instead of flooding the store. The uncapped
    run (same plan, no byte budget) demonstrates the test's power:
    it holds a whole window of blocks (~3x the capped peak)."""
    import threading
    import time

    import ray_tpu as rt

    MB = 1024 * 1024

    def run(cap):
        rt.init(
            num_cpus=8,
            _system_config={
                "object_store_memory": 48 * MB,
                "object_eviction_check_interval_s": 0.05,
            },
        )
        try:
            from ray_tpu import data

            daemon = rt.api._session.daemon
            peak = [0]
            stop = [False]

            def watch():
                while not stop[0]:
                    used = sum(
                        entry.size or 0
                        for entry in list(daemon.objects.values())
                        if getattr(entry, "in_shm", False)
                    )
                    peak[0] = max(peak[0], used)
                    time.sleep(0.01)

            watcher = threading.Thread(target=watch, daemon=True)
            watcher.start()

            def explode(row):
                # One input row -> ~4MB of output (the skew).
                return [
                    {"payload": np.zeros(MB, dtype=np.uint8)}
                    for _ in range(4)
                ]

            ds = (
                data.range(12, parallelism=12)
                .flat_map(explode)
                .options(window=8, inflight_bytes=cap)
            )
            rows = 0
            for block_ref in ds.iter_block_refs():
                block = rt.get(block_ref)
                rows += len(block)
                for row in block:
                    assert row["payload"].nbytes == MB
                del block, block_ref
                time.sleep(0.4)  # slow consumer: producers outpace it
            stop[0] = True
            watcher.join(timeout=5)
            return rows, peak[0]
        finally:
            rt.shutdown()

    rows, uncapped_peak = run(None)  # default budget (256MB) >> data
    assert rows == 48
    rows, capped_peak = run(8 * MB)
    assert rows == 48
    # Budget 8MB + at most one in-flight block (4MB) + slack.
    assert capped_peak <= 16 * MB, (
        f"byte budget did not bound in-flight bytes: "
        f"{capped_peak / MB:.1f} MB sealed at peak"
    )
    assert uncapped_peak >= 20 * MB, (
        "test lost its power: the uncapped run no longer builds up "
        f"a window of blocks (peak {uncapped_peak / MB:.1f} MB)"
    )


def _make_warm_udf():
    """Expensive-setup UDF, built inside the test so cloudpickle
    serializes it BY VALUE (workers can't import tests/)."""

    class WarmUdf:
        SETUP_S = 0.4

        def __init__(self):
            import time as _t

            _t.sleep(self.SETUP_S)

        def __call__(self, batch):
            return {
                "v": batch["id"] * 2,
                "who": np.full(len(batch["id"]), id(self) % 2**31),
            }

    return WarmUdf


def test_actor_pool_map_beats_tasks_on_warm_udf(rt_session):
    """compute=ActorPoolStrategy (reference: actor_pool_map_operator
    .py): each pool actor builds the UDF ONCE and reuses it per block,
    so expensive-setup UDFs beat task-per-block (which re-does setup
    every task). Also checks pool bounds: distinct instances <=
    max_size, and > 1 shows autoscaling engaged under backlog."""
    import time

    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    WarmUdf = _make_warm_udf()
    n_blocks = 10

    def run_actor_pool():
        t0 = time.perf_counter()
        out = (
            data.range(n_blocks * 10, parallelism=n_blocks)
            .map_batches(
                WarmUdf,
                compute=ActorPoolStrategy(
                    min_size=2, max_size=3, max_tasks_per_actor=2
                ),
            )
            .to_numpy()
        )
        return time.perf_counter() - t0, out

    def task_setup_each(batch):
        time.sleep(WarmUdf.SETUP_S)  # cold setup paid per task
        return {
            "v": batch["id"] * 2,
            "who": np.zeros(len(batch["id"])),
        }

    def run_tasks():
        t0 = time.perf_counter()
        out = (
            data.range(n_blocks * 10, parallelism=n_blocks)
            .map_batches(task_setup_each)
            .to_numpy()
        )
        return time.perf_counter() - t0, out

    pool_s, pool_out = run_actor_pool()
    task_s, task_out = run_tasks()

    np.testing.assert_array_equal(
        np.sort(pool_out["v"]), np.sort(task_out["v"])
    )
    instances = set(pool_out["who"].tolist())
    assert 1 <= len(instances) <= 3, instances
    # 10 blocks x 0.4s setup split over 4 CPUs ~= 1.0s+ for tasks;
    # the pool pays <= 3 setups total. Margin kept loose for CI noise.
    assert pool_s < task_s, (
        f"warm actor pool ({pool_s:.2f}s) should beat per-task setup "
        f"({task_s:.2f}s)"
    )


def test_streaming_split_through_actor_pool(rt_session):
    """streaming_split consumes a plan containing an ActorPoolStage:
    the split coordinator drives the pool and both consumers see
    disjoint, complete output (VERDICT r4 task 2: route
    streaming_split through actor-pool compute)."""
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    ds = data.range(80, parallelism=8).map_batches(
        _make_warm_udf(),
        compute=ActorPoolStrategy(min_size=1, max_size=2),
    )
    left, right = ds.streaming_split(2)
    seen = []
    for it in (left, right):
        for row in it.iter_rows():
            seen.append(int(row["v"]))
    assert sorted(seen) == [2 * i for i in range(80)]


def test_pyarrow_batch_format_round_trip(rt_session):
    """batch_format="pyarrow" hands the UDF an Arrow Table (the
    reference's canonical block format) and converts the returned
    Table back into rows."""
    pa = pytest.importorskip("pyarrow")
    import ray_tpu.data as data

    ds = data.from_items([{"x": i} for i in range(8)])

    def double(table):
        assert isinstance(table, pa.Table)
        return table.set_column(
            0, "x", pa.array([v * 2 for v in table["x"].to_pylist()])
        )

    out = ds.map_batches(
        double, batch_format="pyarrow", batch_size=4
    ).take_all()
    assert sorted(r["x"] for r in out) == [i * 2 for i in range(8)]
