"""pip runtime environments: per-requirements-hash venv creation,
offline local-wheel installs, and no pollution of the shared session
env (reference behavior: python/ray/_private/runtime_env/pip.py —
virtualenv per spec hash, cached)."""

import os
import zipfile

import pytest

import ray_tpu as rt

WHEEL_NAME = "testpkg_rt-0.1-py3-none-any.whl"


def _forge_wheel(tmp_path, value=42):
    """Hand-build a tiny pure-python wheel (a wheel is just a zip with
    dist-info) so the test installs fully offline."""
    dist = "testpkg_rt-0.1.dist-info"
    path = tmp_path / WHEEL_NAME
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("testpkg_rt.py", f"VALUE = {value}\n")
        zf.writestr(
            f"{dist}/METADATA",
            "Metadata-Version: 2.1\nName: testpkg-rt\nVersion: 0.1\n",
        )
        zf.writestr(
            f"{dist}/WHEEL",
            "Wheel-Version: 1.0\nGenerator: forge\nRoot-Is-Purelib: "
            "true\nTag: py3-none-any\n",
        )
        zf.writestr(
            f"{dist}/RECORD",
            f"testpkg_rt.py,,\n{dist}/METADATA,,\n{dist}/WHEEL,,\n"
            f"{dist}/RECORD,,\n",
        )
    return str(path)


@pytest.fixture
def single_worker():
    # One CPU => one shared worker: the no-env task below provably runs
    # on the SAME process the pip task used.
    rt.init(num_cpus=1)
    yield
    rt.shutdown()


def test_wheel_installs_and_does_not_pollute(single_worker, tmp_path):
    wheel = _forge_wheel(tmp_path)

    @rt.remote(runtime_env={"pip": [wheel]})
    def use():
        import testpkg_rt

        return testpkg_rt.VALUE, testpkg_rt.__file__

    @rt.remote
    def probe():
        try:
            import testpkg_rt  # noqa: F401

            return "leaked"
        except ImportError:
            return "clean"

    value, file = rt.get(use.remote(), timeout=180)
    assert value == 42
    assert "pip-" in file, f"must import from the venv, got {file}"
    # Same worker, no runtime env: the module must NOT be reachable —
    # neither via sys.path nor via a stale sys.modules entry.
    assert rt.get(probe.remote(), timeout=60) == "clean"
    # And the session interpreter (driver) is untouched.
    with pytest.raises(ImportError):
        import testpkg_rt  # noqa: F401


def test_venv_cached_by_requirements_hash(single_worker, tmp_path):
    wheel = _forge_wheel(tmp_path)

    @rt.remote(runtime_env={"pip": [wheel]})
    def use():
        import testpkg_rt

        return os.path.dirname(os.path.dirname(testpkg_rt.__file__))

    site1 = rt.get(use.remote(), timeout=180)
    marker = os.path.join(site1, "cache-marker")
    open(marker, "w").close()
    # Second task, same requirements: reuses the cached venv (marker
    # survives => no rebuild).
    site2 = rt.get(use.remote(), timeout=60)
    assert site2 == site1
    assert os.path.exists(marker)


def test_wheel_installs_on_remote_node(tmp_path):
    """Local wheel requirements ship through the cluster KV — workers
    on OTHER nodes (no shared filesystem with the driver) install from
    the fetched content, like working_dir does."""
    from ray_tpu.cluster_utils import Cluster

    wheel = _forge_wheel(tmp_path)
    c = Cluster(initialize_head=True, head_resources={"CPU": 1.0})
    rt.init(address=c.address)
    try:
        c.add_node(num_cpus=1, resources={"special": 1.0})
        c.wait_for_nodes(2)

        @rt.remote(
            resources={"special": 1.0}, runtime_env={"pip": [wheel]}
        )
        def use():
            import testpkg_rt

            return testpkg_rt.VALUE

        assert rt.get(use.remote(), timeout=180) == 42
    finally:
        rt.shutdown()
        c.shutdown()


def test_conda_uv_still_rejected(single_worker):
    @rt.remote(runtime_env={"conda": {"deps": ["x"]}})
    def f():
        return 1

    with pytest.raises(Exception, match="conda"):
        rt.get(f.remote(), timeout=30)


def test_bad_requirement_surfaces_setup_error(single_worker, tmp_path):
    # A corrupt local wheel fails pip fast and fully offline (a
    # nonexistent requirement name would stall in index retries in
    # this zero-egress environment).
    bad = tmp_path / "broken_pkg-0.1-py3-none-any.whl"
    bad.write_bytes(b"this is not a zip archive")

    @rt.remote(runtime_env={"pip": [str(bad)]})
    def f():
        return 1

    with pytest.raises(Exception, match="pip install failed"):
        rt.get(f.remote(), timeout=120)
