"""Tune tests (reference test model: python/ray/tune/tests/ — variant
generation, trial execution, ASHA early stopping, PBT exploit/explore,
experiment resume)."""

import pytest


def test_variant_generation_grid_and_samples():
    from ray_tpu.tune import BasicVariantGenerator, grid_search, uniform

    gen = BasicVariantGenerator(seed=0)
    configs = gen.generate(
        {
            "lr": uniform(0.0, 1.0),
            "layers": grid_search([1, 2, 3]),
            "fixed": "x",
        },
        num_samples=2,
    )
    assert len(configs) == 6
    assert {c["layers"] for c in configs} == {1, 2, 3}
    assert all(0.0 <= c["lr"] <= 1.0 for c in configs)
    assert all(c["fixed"] == "x" for c in configs)


def test_tuner_runs_trials_and_picks_best(rt_session):
    from ray_tpu import tune

    def trainable(config):
        score = -((config["x"] - 3.0) ** 2)
        tune.report({"score": score, "x": config["x"]})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=1
        ),
    )
    results = tuner.fit()
    assert len(results) == 4
    assert not results.errors
    best = results.get_best_result("score", "max")
    assert best.config["x"] == 3.0


def test_trial_error_is_captured(rt_session):
    from ray_tpu import tune

    def trainable(config):
        if config["x"] == 1:
            raise RuntimeError("boom")
        tune.report({"score": config["x"]})

    results = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1])},
    ).fit()
    assert len(results.errors) == 1
    assert "boom" in results.errors[0].error


def test_asha_stops_bad_trials(rt_session):
    from ray_tpu import tune

    def trainable(config):
        for step in range(20):
            tune.report({"score": config["slope"] * (step + 1)})

    scheduler = tune.AsyncHyperBandScheduler(
        metric="score",
        mode="max",
        grace_period=2,
        reduction_factor=2,
        max_t=20,
    )
    # Strong trials run first (max_concurrent=2) and populate the
    # rungs; the weak stragglers then fall below the rung cutoffs —
    # ASHA's asynchronous-arrival behavior.
    results = tune.Tuner(
        trainable,
        param_space={"slope": tune.grid_search([2.0, 1.0, 0.2, 0.1])},
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            scheduler=scheduler,
            max_concurrent_trials=2,
        ),
    ).fit()
    iters = {
        r.config["slope"]: r.metrics.get("training_iteration", 0)
        for r in results
    }
    # The best slope survives to max_t; the weak ones stop early.
    assert iters[2.0] == 20
    assert iters[0.1] < 20
    assert iters[0.2] < 20


def test_pbt_exploits_and_mutates(rt_session):
    from ray_tpu import tune

    def trainable(config):
        ckpt = tune.get_checkpoint()
        value = ckpt["value"] if ckpt else 0.0
        for _ in range(50):
            value += config["rate"]
            tune.report(
                {"score": value}, checkpoint={"value": value}
            )

    scheduler = tune.PopulationBasedTraining(
        metric="score",
        mode="max",
        perturbation_interval=5,
        hyperparam_mutations={"rate": [0.5, 1.0, 2.0]},
        quantile_fraction=0.5,
        seed=0,
    )
    results = tune.Tuner(
        trainable,
        param_space={"rate": tune.grid_search([0.01, 2.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=scheduler
        ),
    ).fit()
    assert not results.errors
    # The weak trial was cloned from the strong one: its final score
    # reflects the donor's accumulated value, far above what rate=0.01
    # alone could reach (50 * 0.01 = 0.5).
    scores = sorted(r.metrics["score"] for r in results)
    assert scores[0] > 5.0


def test_experiment_resume(rt_session, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    storage = str(tmp_path / "exp")

    def trainable(config):
        tune.report({"score": config["x"] * 2})

    results = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(storage_path=storage),
    ).fit()
    assert len(results) == 2

    restored = tune.Tuner.restore(storage, trainable)
    results2 = restored.fit()
    assert len(results2) == 2
    assert {r.metrics["score"] for r in results2} == {2, 4}


def test_tuner_wraps_jax_trainer(rt_session):
    """Trainer-as-trainable (reference: BaseTrainer.fit wraps the
    trainer in a one-trial Tuner, base_trainer.py:608)."""
    from ray_tpu import tune
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.session import report as train_report

    def train_loop(config):
        train_report({"loss": 10.0 / config["lr_scale"]})

    trainer = JaxTrainer(train_loop, train_loop_config={"lr_scale": 1.0})
    results = tune.Tuner(
        trainer,
        param_space={"lr_scale": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    ).fit()
    assert not results.errors
    best = results.get_best_result("loss", "min")
    assert best.config["lr_scale"] == 2.0


# ---------------------------------------------------------------------
# Adaptive search (TPE) — reference slot: tune/search/optuna, hyperopt
# ---------------------------------------------------------------------


def test_tpe_converges_on_quadratic():
    """TPE's suggestions must concentrate near the optimum and beat
    pure random search on the same budget + seed."""
    import random as pyrandom

    from ray_tpu.tune.search import TPESearcher, uniform

    space = {"x": uniform(0.0, 1.0), "y": uniform(0.0, 1.0)}

    def score(cfg):
        return -((cfg["x"] - 0.7) ** 2 + (cfg["y"] - 0.2) ** 2)

    def run(adaptive, seed):
        if not adaptive:
            rng = pyrandom.Random(seed)
            return max(
                score({"x": rng.uniform(0, 1), "y": rng.uniform(0, 1)})
                for _ in range(40)
            ), []
        s = TPESearcher()
        s.setup(space, metric="score", mode="max", seed=seed)
        best, xs = -1e9, []
        for _ in range(40):
            cfg = s.suggest()
            xs.append(cfg["x"])
            val = score(cfg)
            best = max(best, val)
            s.record(cfg, {"score": val})
        return best, xs

    seeds = range(5)
    tpe_runs = [run(True, s) for s in seeds]
    rand_runs = [run(False, s) for s in seeds]
    # On average over seeds TPE beats random on the same budget (any
    # single seed can get lucky either way; 2-D is where model-based
    # search separates from best-of-N sampling).
    tpe_mean = sum(b for b, _ in tpe_runs) / len(seeds)
    rand_mean = sum(b for b, _ in rand_runs) / len(seeds)
    assert tpe_mean >= rand_mean, (tpe_mean, rand_mean)
    assert all(b > -0.02 for b, _ in tpe_runs), tpe_runs
    # Later suggestions concentrate near the optimum vs the startup
    # phase.
    for _, xs in tpe_runs:
        early = sum(abs(x - 0.7) for x in xs[:10]) / 10
        late = sum(abs(x - 0.7) for x in xs[-10:]) / 10
        assert late < early, (early, late)


def test_tpe_handles_choice_and_loguniform():
    from ray_tpu.tune.search import TPESearcher, choice, loguniform

    space = {"lr": loguniform(1e-5, 1e-1), "act": choice(["a", "b", "c"])}
    s = TPESearcher(n_startup=8)
    s.setup(space, metric="loss", mode="min", seed=1)
    for _ in range(30):
        cfg = s.suggest()
        assert 1e-5 <= cfg["lr"] <= 1e-1
        assert cfg["act"] in ("a", "b", "c")
        # Optimum: lr near 1e-3, act == "b".
        import math as m

        loss = (m.log10(cfg["lr"]) + 3) ** 2 + (0.0 if cfg["act"] == "b" else 1.0)
        s.record(cfg, {"loss": loss})
    # The model should now strongly prefer act="b".
    prefs = [s.suggest()["act"] for _ in range(20)]
    assert prefs.count("b") >= 10, prefs


def test_tpe_rejects_grid_axes():
    import pytest as _pytest

    from ray_tpu.tune.search import TPESearcher, grid_search

    s = TPESearcher()
    with _pytest.raises(ValueError, match="grid_search"):
        s.setup({"x": grid_search([1, 2])}, "score", "max")


def test_tuner_with_tpe_search_alg(rt_session):
    """End-to-end: Tuner drives TPE suggestions adaptively and finds a
    good config (BOHB-style composition: searcher + ASHA scheduler)."""
    rt = rt_session
    from ray_tpu import tune
    from ray_tpu.tune.search import TPESearcher, uniform

    def trainable(config):
        tune.report({"score": -((config["x"] - 0.3) ** 2)})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=20,
            max_concurrent_trials=2,
            search_alg=TPESearcher(n_startup=6),
            seed=3,
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 20
    best = grid.get_best_result(metric="score", mode="max")
    assert abs(best.config["x"] - 0.3) < 0.15, best.config
