"""Whole-program contract checker tests (`ray_tpu check`,
devtools/check.py + contracts.py) and the runtime half of RT102
(RemoteFunction/ActorClass/@rt.remote option-key validation).

Every rule RT101-RT106 has at least one fixture tree that triggers it
and one that stays quiet; the repo checks itself clean (package AND
tests) — so signature/wire drift either gets fixed or carries an
explicit reviewed `# rt: noqa[RTxxx]`, mirroring tests/test_lint.py.
"""

import io
import json
import os
import textwrap

import pytest

from ray_tpu.devtools.check import check_paths, check_sources, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fired(files):
    """files: {relpath: source}. Returns {rule ids} over the tree
    checked as one program."""
    sources = [
        (path, textwrap.dedent(source)) for path, source in files.items()
    ]
    return {f.rule for f in check_sources(sources)}


#: A minimal server+schema backdrop for the RPC rules: one registered,
#: schema'd, called method so RT103/RT104 global passes are armed.
SERVER = """
class Daemon:
    def __init__(self, server):
        for name in ["kv_put", "kv_get"]:
            server.register(name, getattr(self, "_h_" + name))

    def _h_kv_put(self, conn, msg): ...
    def _h_kv_get(self, conn, msg): ...

SCHEMAS = {
    "kv_put": {"key": (str, bytes), "value": bytes, "?ns": str},
    "kv_get": {"key": (str, bytes), "?ns": str},
}
"""


# ---------------------------------------------------------------------------
# per-rule fixtures
# ---------------------------------------------------------------------------

CASES = [
    # --- RT101: .remote() arity vs decorated signature ----------------
    (
        "RT101",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def add(a, b):
                return a + b

            def driver():
                return add.remote(1, 2, 3)
            """
        },
        True,
    ),
    (
        "RT101",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def add(a, b=0):
                return a + b

            def driver():
                return add.remote(1)
            """
        },
        False,
    ),
    (
        # actor-method call through a typed handle
        "RT101",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            class Counter:
                def __init__(self, start):
                    self.v = start

                def incr(self, by=1):
                    self.v += by

            def driver():
                h = Counter.remote(0)
                return h.incr.remote(1, 2)
            """
        },
        True,
    ),
    (
        "RT101",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            class Counter:
                def __init__(self, start):
                    self.v = start

                def incr(self, by=1):
                    self.v += by

            def driver():
                h = Counter.remote(0)
                return h.incr.remote(by=2)
            """
        },
        False,
    ),
    (
        # unknown method on a typed handle
        "RT101",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            class Counter:
                def incr(self):
                    pass

            def driver():
                h = Counter.remote()
                return h.nope.remote()
            """
        },
        True,
    ),
    # --- RT102: option keys -------------------------------------------
    (
        "RT102",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def f():
                return 1

            def driver():
                return f.options(num_cpu=1).remote()
            """
        },
        True,
    ),
    (
        "RT102",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def f():
                return 1

            def driver():
                return f.options(num_cpus=1, max_retries=2).remote()
            """
        },
        False,
    ),
    (
        # invalid-typed literal
        "RT102",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def f():
                return 1

            def driver():
                return f.options(num_cpus="two").remote()
            """
        },
        True,
    ),
    (
        # decorator-site unknown key on an actor
        "RT102",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote(max_restart=1)
            class A:
                def m(self):
                    pass
            """
        },
        True,
    ),
    # --- RT103: handler registry --------------------------------------
    (
        "RT103",
        {
            "server.py": SERVER,
            "app.py": """
            def driver(client):
                return client.call("frobnicate")
            """,
        },
        True,
    ),
    (
        "RT103",
        {
            "server.py": SERVER,
            "app.py": """
            def driver(client):
                client.call("kv_put", key="k", value=b"v")
                return client.call("kv_get", key="k")
            """,
        },
        False,
    ),
    (
        # dead handler: registered, schema'd, never named anywhere
        "RT103",
        {
            "server.py": SERVER
            + """
class Extra:
    def __init__(self, server):
        server.register("dead_verb", self._h_dead_verb)

    def _h_dead_verb(self, conn, msg): ...

SCHEMAS["dead_verb"] = {}
""",
            "app.py": """
            def driver(client):
                return client.call("kv_get", key="k")
            """,
        },
        True,
    ),
    (
        # a dynamic-dispatch string witness keeps a handler alive
        "RT103",
        {
            "server.py": SERVER,
            "app.py": """
            def driver(client, bundle_call):
                bundle_call(b"node", "kv_put", key="k", value=b"v")
                return client.call("kv_get", key="k")
            """,
        },
        False,
    ),
    # --- RT104: wire-schema drift -------------------------------------
    (
        "RT104",
        {
            "server.py": SERVER,
            "app.py": """
            def driver(client):
                return client.call("kv_put", key="k", value=b"v", wrong=1)
            """,
        },
        True,
    ),
    (
        # missing required field
        "RT104",
        {
            "server.py": SERVER,
            "app.py": """
            def driver(client):
                return client.call("kv_put", key="k")
            """,
        },
        True,
    ),
    (
        # **kwargs expansion: explicit keys checked, required relaxed
        "RT104",
        {
            "server.py": SERVER,
            "app.py": """
            def driver(client, kw):
                client.call("kv_put", **kw)
                return client.call("kv_put", key="k", value=b"v", ns="n",
                                   timeout=5, retries=2)
            """,
        },
        False,
    ),
    (
        # handler served without any schema entry
        "RT104",
        {
            "server.py": SERVER
            + """
class Extra:
    def __init__(self, server):
        server.register("no_schema", self._h_no_schema)

    def _h_no_schema(self, conn, msg): ...
""",
            "app.py": """
            def driver(client):
                client.notify("no_schema")
                return client.call("kv_get", key="k")
            """,
        },
        True,
    ),
    # --- RT105: unserializable .remote() args -------------------------
    (
        "RT105",
        {
            "app.py": """
            import threading
            import ray_tpu as rt

            @rt.remote
            def work(sync):
                return sync

            def driver():
                lock = threading.Lock()
                return work.remote(lock)
            """
        },
        True,
    ),
    (
        "RT105",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def work(payload):
                return payload

            def driver():
                data = open("f").read()  # the VALUE crosses, not the file
                return work.remote(data)
            """
        },
        False,
    ),
    (
        # direct constructor in the call, keyword position
        "RT105",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def work(out=None):
                return out

            def driver():
                return work.remote(out=open("log.txt", "w"))
            """
        },
        True,
    ),
    # --- RT106: discarded task refs -----------------------------------
    (
        "RT106",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def fire():
                return 1

            def driver():
                fire.remote()
            """
        },
        True,
    ),
    (
        "RT106",
        {
            "app.py": """
            import ray_tpu as rt

            @rt.remote
            def fire():
                return 1

            def driver():
                ref = fire.remote()
                return rt.get(ref)
            """
        },
        False,
    ),
]


@pytest.mark.parametrize(
    "rule,files,expect",
    CASES,
    ids=[
        f"{c[0]}-{'fires' if c[2] else 'quiet'}-{i}"
        for i, c in enumerate(CASES)
    ],
)
def test_rule_fixtures(rule, files, expect):
    rules = fired(files)
    if expect:
        assert rule in rules, f"{rule} did not fire on its fixture"
    else:
        assert rule not in rules, f"{rule} false-positived: {rules}"


# ---------------------------------------------------------------------------
# resolution precision
# ---------------------------------------------------------------------------


def test_same_name_symbols_resolve_per_scope():
    """Two test-style functions each defining `@rt.remote class A`
    must each resolve THEIR A (the lexical-shadowing bug class)."""
    rules_and_findings = check_sources(
        [
            (
                "app.py",
                textwrap.dedent(
                    """
                    import ray_tpu as rt

                    def test_one():
                        @rt.remote
                        class A:
                            def ping(self):
                                return 1

                        h = A.remote()
                        return h.ping.remote()

                    def test_two():
                        @rt.remote
                        class A:
                            def __init__(self, x):
                                self.x = x

                            def pong(self):
                                return 2

                        h = A.remote(5)
                        return h.pong.remote()
                    """
                ),
            )
        ]
    )
    assert rules_and_findings == [], [
        f.render() for f in rules_and_findings
    ]


def test_cross_file_import_resolution():
    """A .remote() call in one file is checked against the decorated
    signature defined in ANOTHER file (the whole-program property)."""
    rules = fired(
        {
            "lib/tasks.py": """
            import ray_tpu as rt

            @rt.remote
            def transform(block, fn):
                return fn(block)
            """,
            "driver.py": """
            from lib.tasks import transform

            def run():
                return transform.remote(1, 2, 3)
            """,
        }
    )
    assert "RT101" in rules


def test_inherited_actor_methods_not_flagged():
    """Methods from a base class are invisible to the class-body scan;
    unknown-method judgments must stay silent for derived actors."""
    findings = check_sources(
        [
            (
                "app.py",
                textwrap.dedent(
                    """
                    import ray_tpu as rt

                    class Base:
                        def ping(self):
                            return 1

                    @rt.remote
                    class Child(Base):
                        def own(self):
                            return 2

                    def driver():
                        h = Child.remote()
                        h.own.remote()  # rt: noqa[RT106]
                        return h.ping.remote()  # inherited: no finding
                    """
                ),
            )
        ]
    )
    assert findings == [], [f.render() for f in findings]


def test_unresolvable_receivers_stay_silent():
    """serve-style handles and unknown receivers are never judged."""
    findings = check_sources(
        [
            (
                "app.py",
                textwrap.dedent(
                    """
                    def route(handle, replica):
                        handle.options(stream=True).remote(None)
                        replica["actor"].m.remote(1, 2, 3, 4, 5)
                    """
                ),
            )
        ]
    )
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# suppressions / output modes / CLI
# ---------------------------------------------------------------------------


def test_noqa_suppressions():
    bad = (
        "import ray_tpu as rt\n"
        "\n"
        "@rt.remote\n"
        "def f(a):\n"
        "    return a\n"
        "\n"
        "def driver():\n"
        "    f.remote()\n"
    )
    assert {f.rule for f in check_sources([("m.py", bad)])} == {
        "RT101",
        "RT106",
    }
    one = bad.replace("f.remote()", "f.remote()  # rt: noqa[RT106]")
    assert {f.rule for f in check_sources([("m.py", one)])} == {"RT101"}
    both = bad.replace(
        "f.remote()", "f.remote()  # rt: noqa[RT101,RT106]"
    )
    assert check_sources([("m.py", both)]) == []
    blanket = bad.replace("f.remote()", "f.remote()  # rt: noqa")
    assert check_sources([("m.py", blanket)]) == []


def test_json_output_roundtrip(tmp_path):
    target = tmp_path / "app.py"
    target.write_text(
        "import ray_tpu as rt\n"
        "\n"
        "@rt.remote\n"
        "def f():\n"
        "    return 1\n"
        "\n"
        "def driver():\n"
        "    return f.options(num_cpu=1).remote()\n"
    )
    out = io.StringIO()
    code = main(["--json", str(tmp_path)], out=out)
    assert code == 1
    findings = json.loads(out.getvalue())
    assert len(findings) == 1
    finding = findings[0]
    assert finding["rule"] == "RT102"
    assert finding["path"] == str(target)
    assert finding["line"] == 8
    assert "num_cpu" in finding["message"]
    assert "num_cpus" in finding["message"]  # names the valid set


def test_rules_filter_and_errors(tmp_path):
    target = tmp_path / "app.py"
    target.write_text(
        "import ray_tpu as rt\n"
        "\n"
        "@rt.remote\n"
        "def f(a):\n"
        "    return a\n"
        "\n"
        "def driver():\n"
        "    f.remote()\n"
    )
    unfiltered = io.StringIO()
    assert main([str(tmp_path)], out=unfiltered) == 1
    assert "RT101" in unfiltered.getvalue()
    assert "RT106" in unfiltered.getvalue()
    out = io.StringIO()
    assert main(["--rules", "RT106", str(tmp_path)], out=out) == 1
    assert "RT101" not in out.getvalue()
    assert "RT106" in out.getvalue()
    assert main(["--rules", "RT999", str(tmp_path)], out=io.StringIO()) == 2
    assert main([str(tmp_path / "nope.py")], out=io.StringIO()) == 2
    assert main(["--list-rules"], out=io.StringIO()) == 0


def test_repo_checks_clean():
    """`ray_tpu check ray_tpu/ tests/` exits 0: every cross-program
    contract in the tree holds, or carries a reviewed noqa."""
    out = io.StringIO()
    code = main(
        [os.path.join(REPO, "ray_tpu"), os.path.join(REPO, "tests")],
        out=out,
    )
    assert code == 0, f"repo check not clean:\n{out.getvalue()}"


def test_devtools_all_merged_gate(tmp_path, capsys):
    """`ray_tpu devtools all` runs lint + check and merges findings
    into one JSON list (the single CI gate)."""
    from ray_tpu.scripts.cli import main as cli_main

    target = tmp_path / "dag" / "app.py"
    target.parent.mkdir()
    # One lint finding (RT002 payload dedup) + one check finding
    # (RT101 arity) in the same tree.
    target.write_text(
        "import ray_tpu as rt\n"
        "\n"
        "@rt.remote\n"
        "def f():\n"
        "    return 1\n"
        "\n"
        "def dedup(payload, prev):\n"
        "    return payload == prev\n"
        "\n"
        "def driver():\n"
        "    return f.remote(1)\n"
    )
    with pytest.raises(SystemExit) as excinfo:
        cli_main(["devtools", "all", str(tmp_path), "--json"])
    assert excinfo.value.code == 1
    findings = json.loads(capsys.readouterr().out)
    rules = {f["rule"] for f in findings}
    assert "RT002" in rules and "RT101" in rules
    # Clean tree exits 0 with an empty list.
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    with pytest.raises(SystemExit) as excinfo:
        cli_main(["devtools", "all", str(clean), "--json"])
    assert excinfo.value.code == 0
    assert json.loads(capsys.readouterr().out) == []


# ---------------------------------------------------------------------------
# runtime counterpart of RT102: unknown option keys raise
# ---------------------------------------------------------------------------


def test_options_rejects_unknown_task_keys():
    import ray_tpu as rt

    @rt.remote
    def f():
        return 1

    with pytest.raises(ValueError) as excinfo:
        f.options(num_cpu=1)  # rt: noqa[RT102] — the raise IS the test
    msg = str(excinfo.value)
    assert "num_cpu" in msg  # names the bad key
    assert "num_cpus" in msg and "max_retries" in msg  # valid key set

    # valid keys still merge fine
    assert f.options(num_cpus=2).task_options["num_cpus"] == 2


def test_options_rejects_unknown_actor_keys():
    import ray_tpu as rt

    @rt.remote
    class A:
        def m(self):
            return 1

    with pytest.raises(ValueError) as excinfo:
        A.options(max_restart=1)  # rt: noqa[RT102] — the raise IS the test
    msg = str(excinfo.value)
    assert "max_restart" in msg
    assert "max_restarts" in msg and "namespace" in msg

    assert A.options(max_restarts=1).actor_options["max_restarts"] == 1


def test_decorator_rejects_unknown_keys():
    import ray_tpu as rt

    with pytest.raises(ValueError, match="num_gpu"):

        @rt.remote(num_gpu=1)  # rt: noqa[RT102] — the raise IS the test
        def f():
            return 1

    with pytest.raises(ValueError, match="concurrency_group\\b"):

        @rt.remote(concurrency_group={"io": 1})  # plural is the key  # rt: noqa[RT102]
        class A:
            pass


def test_internal_skip_pg_rewrite_key_still_accepted():
    """placement_groups.py submits its marker task with the internal
    _skip_pg_rewrite key — documented in the universe, not rejected."""
    import ray_tpu as rt

    @rt.remote
    def marker():
        return 1

    clone = marker.options(num_cpus=0, _skip_pg_rewrite=True)
    assert clone.task_options["_skip_pg_rewrite"] is True


def test_schema_registry_has_has_schema():
    from ray_tpu._private import wire

    assert wire.has_schema("kv_put")
    assert not wire.has_schema("definitely_not_a_method")
