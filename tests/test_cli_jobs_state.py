"""CLI / job submission / state API tests (reference test models:
python/ray/tests/test_cli.py, dashboard/modules/job/tests,
python/ray/tests/test_state_api.py)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_state_api_lists(rt_session):
    rt = rt_session
    from ray_tpu.util import state

    @rt.remote
    def f():
        return 1

    @rt.remote
    class A:
        def ping(self):
            return "pong"

    ref = rt.put(list(range(100)))
    rt.get(f.remote(), timeout=20)
    a = A.remote()
    rt.get(a.ping.remote(), timeout=20)

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["is_head"]
    actors = state.list_actors()
    assert any(x["class_name"] == "A" for x in actors)
    tasks = state.list_tasks()
    assert any(t["name"] == "f" for t in tasks)
    objects = state.list_objects()
    assert len(objects) >= 1
    assert state.summarize()


def test_list_tasks_newest_first_under_limit(rt_session):
    """`limit` keeps the NEWEST tasks: the old dict-order truncation
    dropped an arbitrary slice of the table."""
    rt = rt_session
    from ray_tpu.util import state

    @rt.remote
    def tick(i):
        return i

    for i in range(6):
        rt.get(tick.remote(i), timeout=20)
    all_rows = state.list_tasks()
    times = [float(r.get("time", 0.0)) for r in all_rows]
    assert times == sorted(times, reverse=True)
    newest_two = state.list_tasks(limit=2)
    assert [r["task_id"] for r in newest_two] == [
        r["task_id"] for r in all_rows[:2]
    ]


def test_job_submission_end_to_end(rt_session, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "job_script.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import ray_tpu as rt\n"
        "rt.init()\n"  # picks up RT_ADDRESS from the job env
        "@rt.remote\n"
        "def f(x):\n"
        "    return x * 3\n"
        "print('job result:', rt.get(f.remote(14)))\n"
    )
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} {script}",
        metadata={"who": "test"},
    )
    status = client.wait_until_finished(job_id, timeout=120)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job result: 42" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(rt_session):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'"
    )
    assert client.wait_until_finished(job_id, 60) == JobStatus.FAILED
    assert client.get_job_info(job_id)["exit_code"] == 3


@pytest.mark.slow
def test_cli_start_status_submit_stop(tmp_path):
    """Full CLI lifecycle against a real head process."""
    info = str(tmp_path / "cluster.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)
    head = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "ray_tpu",
            "--cluster-info",
            info,
            "start",
            "--head",
            "--num-cpus",
            "2",
            "--num-tpus",
            "0",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(info):
            time.sleep(0.2)
        assert os.path.exists(info), "head never wrote cluster info"

        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "--cluster-info",
                info,
                "status",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "nodes: 1" in out.stdout

        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "--cluster-info",
                info,
                "submit",
                "--",
                sys.executable,
                "-c",
                "print(6*7)",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "42" in out.stdout

        subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "--cluster-info",
                info,
                "stop",
            ],
            env=env,
            capture_output=True,
            timeout=60,
        )
        assert head.wait(timeout=30) is not None
    finally:
        if head.poll() is None:
            head.send_signal(signal.SIGKILL)


def test_cli_state_ls_and_metrics(tmp_path):
    """`ray_tpu state ls` + `ray_tpu metrics scrape/snapshot` against
    a real head process: JSON contract, exit codes, Prometheus text."""
    info = str(tmp_path / "cluster.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)
    env["RT_metrics_timeseries_interval_s"] = "0.2"
    head = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu",
            "--cluster-info", info,
            "start", "--head", "--num-cpus", "2", "--num-tpus", "0",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(info):
            time.sleep(0.2)
        assert os.path.exists(info), "head never wrote cluster info"

        def run(*argv, timeout=60):
            return subprocess.run(
                [
                    sys.executable, "-m", "ray_tpu",
                    "--cluster-info", info, *argv,
                ],
                env=env, capture_output=True, text=True,
                timeout=timeout,
            )

        out = run("state", "ls", "nodes", "--json")
        assert out.returncode == 0, out.stdout + out.stderr
        rows = json.loads(out.stdout)
        assert len(rows) == 1 and rows[0]["is_head"]

        # Human mode renders a header table; exit code stays 0 even
        # when a kind is empty.
        out = run("state", "ls", "pgs")
        assert out.returncode == 0, out.stdout + out.stderr
        assert "no pgs" in out.stdout

        out = run("state", "ls", "tasks", "--json", "--limit", "5")
        assert out.returncode == 0, out.stdout + out.stderr
        assert isinstance(json.loads(out.stdout), list)

        # Unknown kinds fail with argparse's usage exit code (2),
        # matching the lint/check CLI contract.
        out = run("state", "ls", "bogus")
        assert out.returncode == 2

        out = run("metrics", "scrape")
        assert out.returncode == 0, out.stdout + out.stderr
        assert "# TYPE rt_nodes_alive gauge" in out.stdout
        assert 'rt_nodes_alive{node="' in out.stdout

        # Snapshot ring fills at 0.2 s/tick (env above); poll briefly.
        deadline = time.time() + 30
        snaps = []
        while time.time() < deadline:
            out = run("metrics", "snapshot", "--limit", "2")
            assert out.returncode == 0, out.stdout + out.stderr
            snaps = json.loads(out.stdout)
            if len(snaps) >= 2:
                break
            time.sleep(0.3)
        assert len(snaps) == 2
        assert "metrics" in snaps[0] and "time" in snaps[0]
    finally:
        if head.poll() is None:
            head.send_signal(signal.SIGKILL)


def test_cli_dashboard_serves(tmp_path):
    """`python -m ray_tpu dashboard` attaches to a running cluster and
    serves the SPA + API."""
    import json
    import signal
    import subprocess
    import sys
    import threading
    import time
    import urllib.request

    import ray_tpu as rt

    rt.init(num_cpus=1)
    try:
        address = rt.api._session.daemon.socket_path
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu", "dashboard",
                "--address", address, "--port", "0",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        try:
            # readline blocks without a timeout — scan on a thread so
            # a wedged subprocess yields a diagnostic, not a hang.
            found = {"url": None, "out": []}
            ready = threading.Event()

            def scan():
                for line in proc.stdout:
                    found["out"].append(line)
                    if "dashboard:" in line:
                        found["url"] = line.split("dashboard:")[1].strip()
                        ready.set()
                        return

            t = threading.Thread(target=scan, daemon=True)
            t.start()
            assert ready.wait(30), (
                f"dashboard never came up: {found['out'][-5:]}"
            )
            nodes = json.loads(
                urllib.request.urlopen(
                    found["url"] + "/api/nodes", timeout=10
                ).read()
            )
            assert len(nodes) >= 1
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10)
    finally:
        rt.shutdown()


def test_cli_up_down_memory_timeline(tmp_path):
    """`up` boots an autoscaling cluster from a config file, the
    state-backed commands (`memory`, `timeline`) run against it, and
    `down` stops it via the cluster-info file (reference: `ray up`/
    `ray down`/`ray memory`/`ray timeline`)."""
    info = str(tmp_path / "cluster.json")
    config = tmp_path / "cluster.yaml"
    config.write_text(
        "cluster_name: cli-test\n"
        "provider:\n  type: fake\n"
        "head_resources: {CPU: 2.0}\n"
        "worker_node_types:\n"
        "  cpu-worker:\n"
        "    resources: {CPU: 2.0}\n"
        "    min_workers: 0\n"
        "    max_workers: 2\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)
    up = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
         "up", str(config)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline and not os.path.exists(info):
            time.sleep(0.2)
        assert os.path.exists(info), "up never wrote cluster info"

        script = tmp_path / "job.py"
        script.write_text(
            "import ray_tpu as rt\n"
            "rt.init()\n"
            "print('mem-probe', rt.get(rt.put(b'x' * 100000))[:1])\n"
        )
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "submit", "--timeout", "120", "--",
             sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "SUCCEEDED" in out.stdout

        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "memory"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "objects" in out.stdout

        trace_out = tmp_path / "trace.json"
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "timeline", "--out", str(trace_out)],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert trace_out.exists()

        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "down"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert up.wait(timeout=30) == 0
    finally:
        if up.poll() is None:
            up.kill()
            up.wait(timeout=10)


def test_cli_serve_run_status_shutdown(tmp_path):
    """`serve run module:app` deploys and serves over HTTP; `serve
    status` reports it; `serve shutdown` tears it down (reference:
    serve/scripts.py run/status/shutdown)."""
    import urllib.request

    info = str(tmp_path / "cluster.json")
    app_py = tmp_path / "cli_app.py"
    app_py.write_text(
        "from ray_tpu import serve\n"
        "@serve.deployment\n"
        "class Hello:\n"
        "    def __call__(self, request):\n"
        "        return {'hello': request.query_params.get('q', '')}\n"
        "app = Hello.bind()\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
         "start", "--head", "--num-cpus", "4", "--num-tpus", "0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    srv = None
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(info):
            time.sleep(0.2)
        assert os.path.exists(info)

        import socket as socklib

        with socklib.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "serve", "run", "cli_app:app", "--port", str(port)],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        deadline = time.time() + 60
        body = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/?q=cli", timeout=5
                ) as resp:
                    body = resp.read()
                break
            except Exception:
                assert srv.poll() is None, srv.stdout.read().decode()
                time.sleep(0.5)
        assert body is not None and b"cli" in body, body

        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "serve", "status"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "default" in out.stdout

        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--cluster-info", info,
             "serve", "shutdown"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
    finally:
        for proc in (srv, head):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
