"""Distributed spans with OTLP export (reference slot:
python/ray/util/tracing — OTel spans around submission/execution with
remote context propagation; §5.1)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.util import tracing


@pytest.fixture
def session():
    rt.init(num_cpus=2)
    yield
    rt.shutdown()


def test_remote_task_spans_link_to_caller(session):
    @rt.remote
    def child():
        with tracing.span("inside-child", flavor="work"):
            time.sleep(0.01)
        return 1

    with tracing.span("driver-root") as root:
        assert rt.get(child.remote(), timeout=30) == 1

    deadline = time.time() + 10
    spans = []
    while time.time() < deadline:
        otlp = tracing.export_otlp()
        spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        if len(spans) >= 3:
            break
        time.sleep(0.2)
    by_name = {s["name"]: s for s in spans}
    assert {"driver-root", "task:child", "inside-child"} <= set(by_name)
    # One trace, parented: root -> task:child -> inside-child.
    assert all(
        s["traceId"] == by_name["driver-root"]["traceId"]
        for s in by_name.values()
    )
    assert (
        by_name["task:child"]["parentSpanId"]
        == by_name["driver-root"]["spanId"]
    )
    assert (
        by_name["inside-child"]["parentSpanId"]
        == by_name["task:child"]["spanId"]
    )
    assert "parentSpanId" not in by_name["driver-root"]
    # OTLP shape: ns timestamps as strings, attributes as kv list.
    child_span = by_name["inside-child"]
    assert int(child_span["endTimeUnixNano"]) > int(
        child_span["startTimeUnixNano"]
    )
    assert {"key": "flavor", "value": {"stringValue": "work"}} in (
        child_span["attributes"]
    )


def test_untraced_tasks_create_no_spans(session):
    @rt.remote
    def plain():
        return 1

    assert rt.get(plain.remote(), timeout=30) == 1
    time.sleep(0.5)
    otlp = tracing.export_otlp()
    spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert not [s for s in spans if s["name"] == "task:plain"]


def test_error_recorded_on_span(session):
    with pytest.raises(ValueError):
        with tracing.span("fails"):
            raise ValueError("boom")
    deadline = time.time() + 10
    while time.time() < deadline:
        otlp = tracing.export_otlp()
        spans = [
            s
            for s in otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
            if s["name"] == "fails"
        ]
        if spans:
            break
        time.sleep(0.2)
    assert spans
    attrs = {a["key"]: a["value"]["stringValue"] for a in spans[0]["attributes"]}
    assert "boom" in attrs.get("error", "")


def test_failed_task_span_records_error(session):
    @rt.remote
    def dies():
        raise RuntimeError("task-went-boom")

    with tracing.span("root-f"):
        with pytest.raises(Exception):
            rt.get(dies.remote(), timeout=30)
    deadline = time.time() + 10
    task_spans = []
    while time.time() < deadline:
        otlp = tracing.export_otlp()
        task_spans = [
            s
            for s in otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
            if s["name"] == "task:dies"
        ]
        if task_spans:
            break
        time.sleep(0.2)
    assert task_spans
    attrs = {
        a["key"]: a["value"]["stringValue"]
        for a in task_spans[0]["attributes"]
    }
    assert "task-went-boom" in attrs.get("error", "")


def test_actor_creation_links_to_caller(session):
    @rt.remote
    class Traced:
        def __init__(self):
            with tracing.span("init-work"):
                pass

        def ping(self):
            return 1

    with tracing.span("actor-root") as root:
        a = Traced.remote()
        assert rt.get(a.ping.remote(), timeout=30) == 1
        root_trace = root.trace_id
    deadline = time.time() + 10
    by_name = {}
    while time.time() < deadline:
        otlp = tracing.export_otlp()
        by_name = {
            s["name"]: s
            for s in otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        }
        if "init-work" in by_name:
            break
        time.sleep(0.2)
    assert by_name["init-work"]["traceId"] == root_trace
