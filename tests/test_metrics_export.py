"""Metrics-export + request-path observability tests (ISSUE 7):
Prometheus text-format rendering, the head time-series ring, goodput
classification, and the 2-node serve e2e that ties /metrics,
/api/serve and /api/timeseries together.
"""

import json
import time
import urllib.request

import pytest

from ray_tpu._private.step_telemetry import goodput_from_records
from ray_tpu._private.timeseries import TimeSeriesStore, compact_summary
from ray_tpu.util.prometheus import render_prometheus


# ---------------------------------------------------------------------------
# Prometheus rendering (pure-function unit tests)
# ---------------------------------------------------------------------------


def test_prometheus_escaping_and_sanitization():
    text = render_prometheus(
        {
            "legacy.dotted-name": {
                "kind": "counter",
                "description": 'has "quotes" and\nnewline \\ slash',
                "total": 3.0,
                "by_tags": {
                    'path=/a"b\\c\nd': {"total": 3.0},
                },
            },
        }
    )
    # Name sanitized, HELP escaped (newline survives as literal \n).
    assert "# HELP legacy_dotted_name" in text
    assert r"newline \\ slash" in text
    assert "\nnewline" not in text.split("# HELP", 1)[1].split("\n")[0]
    # Label values escape quote, backslash, newline.
    assert r'path="/a\"b\\c\nd"' in text
    assert text.endswith("\n")


def test_prometheus_counter_gauge_series_rules():
    text = render_prometheus(
        {
            "rt_workers_alive": {
                "kind": "gauge",
                "description": "workers",
                "value": 5.0,
                "by_node": {"aa": 2.0, "bb": 3.0},
            },
            "plain_total": {"kind": "counter", "total": 2.0},
            "tagged_total": {
                "kind": "counter",
                "total": 7.0,
                "by_tags": {
                    "app=x|deployment=y": {"total": 4.0},
                    "app=x|deployment=z": {"total": 3.0},
                },
            },
        }
    )
    lines = text.splitlines()
    # by_node: ONLY per-node series (no unlabeled double-count line).
    assert 'rt_workers_alive{node="aa"} 2.0' in lines
    assert 'rt_workers_alive{node="bb"} 3.0' in lines
    assert "rt_workers_alive 5.0" not in lines
    # bare counter renders unlabeled; tagged one per tag set, no
    # aggregate line.
    assert "plain_total 2.0" in lines
    assert 'tagged_total{app="x",deployment="y"} 4.0' in lines
    assert 'tagged_total{app="x",deployment="z"} 3.0' in lines
    assert "tagged_total 7.0" not in lines
    assert "# TYPE tagged_total counter" in lines


def _parse_bucket_lines(text, name):
    """[(labels-dict, value)] for every `<name>_bucket` line."""
    out = []
    for line in text.splitlines():
        if not line.startswith(name + "_bucket"):
            continue
        labels_part = line[line.index("{") + 1 : line.rindex("}")]
        labels = {}
        for item in labels_part.split('",'):
            key, _, value = item.partition("=")
            labels[key.strip()] = value.strip('"')
        out.append((labels, float(line.rsplit(" ", 1)[1])))
    return out


def test_prometheus_histogram_le_monotonic_inf_sum_count():
    entry = {
        "kind": "histogram",
        "description": "latency",
        "count": 9,
        "sum": 123.5,
        "buckets": {"le_1": 2, "le_5": 5, "le_25": 8, "inf": 9},
        "by_tags": {
            "app=a|deployment=d": {
                "count": 9,
                "sum": 123.5,
                "buckets": {
                    "le_1": 2,
                    "le_5": 5,
                    "le_25": 8,
                    "inf": 9,
                },
            }
        },
    }
    text = render_prometheus({"serve_request_latency_ms": entry})
    assert "# TYPE serve_request_latency_ms histogram" in text
    buckets = _parse_bucket_lines(text, "serve_request_latency_ms")
    assert buckets, text
    # Cumulative counts nondecreasing in le order; +Inf == _count.
    values = [v for _labels, v in buckets]
    assert values == sorted(values)
    assert buckets[-1][0]["le"] == "+Inf"
    assert buckets[-1][1] == 9.0
    assert (
        'serve_request_latency_ms_sum{app="a",deployment="d"} 123.5'
        in text
    )
    assert (
        'serve_request_latency_ms_count{app="a",deployment="d"} 9.0'
        in text
    )
    # Deployment label rides every bucket line.
    assert all(
        labels.get("deployment") == "d" for labels, _v in buckets
    )


def test_prometheus_histogram_without_boundaries_gets_inf_bucket():
    text = render_prometheus(
        {"h": {"kind": "histogram", "count": 4, "sum": 8.0}}
    )
    assert 'h_bucket{le="+Inf"} 4.0' in text
    assert "h_sum 8.0" in text
    assert "h_count 4.0" in text


# ---------------------------------------------------------------------------
# time-series ring (store unit tests)
# ---------------------------------------------------------------------------


def test_timeseries_ring_bounds_and_eviction():
    store = TimeSeriesStore(max_snapshots=5)
    for i in range(12):
        store.append({"m": {"kind": "counter", "total": float(i)}},
                     now=1000.0 + i)
    assert len(store) == 5
    snaps = store.query()
    # Oldest evicted: only the newest 5 survive, oldest first.
    assert [s["time"] for s in snaps] == [
        1007.0, 1008.0, 1009.0, 1010.0, 1011.0
    ]
    assert snaps[0]["metrics"]["m"]["total"] == 7.0


def test_timeseries_query_filters():
    store = TimeSeriesStore(max_snapshots=10)
    store.append({"a": {"kind": "gauge", "value": 1.0}}, now=10.0)
    store.append(
        {
            "a": {"kind": "gauge", "value": 2.0},
            "b": {"kind": "counter", "total": 5.0},
        },
        now=20.0,
    )
    # since: strictly newer.
    assert [s["time"] for s in store.query(since=10.0)] == [20.0]
    # name: filters each snapshot; snapshots missing the series are
    # skipped entirely.
    only_b = store.query(name="b")
    assert len(only_b) == 1 and set(only_b[0]["metrics"]) == {"b"}
    # limit keeps the NEWEST.
    assert [s["time"] for s in store.query(limit=1)] == [20.0]


def test_compact_summary_strips_heavy_fields():
    compact = compact_summary(
        {
            "h": {
                "kind": "histogram",
                "description": "x",
                "count": 3,
                "sum": 6.0,
                "p50": 2.0,
                "p99": 3.0,
                "buckets": {"le_1": 1, "inf": 3},
                "by_tags": {
                    "app=a": {
                        "count": 3,
                        "p99": 3.0,
                        "buckets": {"inf": 3},
                    }
                },
            }
        }
    )
    entry = compact["h"]
    assert entry["count"] == 3 and entry["p99"] == 3.0
    assert "buckets" not in entry and "description" not in entry
    assert entry["by_tags"]["app=a"] == {"count": 3, "p99": 3.0}


# ---------------------------------------------------------------------------
# goodput classification (pure arithmetic)
# ---------------------------------------------------------------------------


def _rec(job="j1", wall=100.0, step=70.0, data=20.0, h2d=5.0,
         ckpt=0.0, warmup=False):
    rec = {
        "job": job,
        "wall_ms": wall,
        "step_ms": step,
        "data_wait_ms": data,
        "h2d_ms": h2d,
        "ckpt_block_ms": ckpt,
    }
    if warmup:
        rec["warmup"] = True
    return rec


def test_goodput_basic_classification():
    rows = goodput_from_records(
        [_rec(), _rec(wall=100.0, step=80.0, data=10.0, h2d=10.0)]
    )
    row = rows["j1"]
    assert row["steps"] == 2
    assert row["wall_ms"] == 200.0
    assert row["productive_ms"] == 150.0
    assert row["stall_ms"] == 45.0
    assert row["idle_ms"] == 5.0
    # Partition is exact: productive + stall + idle == wall.
    assert (
        row["productive_ms"] + row["stall_ms"] + row["idle_ms"]
        == row["wall_ms"]
    )
    assert row["goodput"] == 0.75
    assert row["stalls"]["data_wait_ms"] == 30.0


def test_goodput_caps_and_skips():
    rows = goodput_from_records(
        [
            _rec(warmup=True),  # warmup: skipped
            {"job": "j1", "step_ms": 50.0},  # no wall: skipped
            # Overreported phases: stall capped at wall, productive
            # capped at the remainder — the partition stays exact.
            _rec(wall=100.0, step=90.0, data=80.0, h2d=40.0),
        ]
    )
    row = rows["j1"]
    assert row["steps"] == 1
    assert row["wall_ms"] == 100.0
    assert row["stall_ms"] == 100.0  # 80 + capped-to-20 h2d
    assert row["stalls"]["h2d_ms"] == 20.0
    assert row["productive_ms"] == 0.0
    assert row["goodput"] == 0.0
    assert (
        row["productive_ms"] + row["stall_ms"] + row["idle_ms"]
        == row["wall_ms"]
    )


def test_goodput_keeps_jobs_apart():
    rows = goodput_from_records(
        [_rec(job="a", step=90.0, data=10.0, h2d=0.0),
         _rec(job="b", step=10.0, data=90.0, h2d=0.0)]
    )
    assert rows["a"]["goodput"] == 0.9
    assert rows["b"]["goodput"] == 0.1


# ---------------------------------------------------------------------------
# live-cluster integration
# ---------------------------------------------------------------------------


def test_goodput_in_doctor_and_step_summary(rt_session):
    """Acceptance: the doctor's per-job goodput fraction classifies
    productive + stall to the reported step wall within 5%."""
    rt = rt_session
    from ray_tpu._private.step_telemetry import add_phase, report_step
    from ray_tpu.util import metrics

    for step in range(1, 4):
        add_phase("data_wait_ms", 30.0)
        add_phase("h2d_ms", 10.0)
        report_step(step, rank=0, wall_ms=100.0)
    metrics.flush()
    summary = rt.api._worker().call("step_summary")["summary"]
    goodput = summary["goodput"]
    assert len(goodput) == 1
    row = next(iter(goodput.values()))
    assert row["steps"] == 3
    assert row["goodput"] == pytest.approx(0.6, abs=0.01)
    total = row["productive_ms"] + row["stall_ms"] + row["idle_ms"]
    assert total == pytest.approx(row["wall_ms"], rel=0.05)
    # Same numbers through the doctor verdict.
    verdict = rt.diagnose(capture_stacks=False)
    doctor_row = next(iter(verdict["steps"]["goodput"].values()))
    assert doctor_row["goodput"] == row["goodput"]


def test_timeseries_live_ring_and_endpoint():
    """Head snapshot loop + /api/timeseries: bounded history spanning
    >= 2 snapshot intervals, counter trend visible by differencing."""
    import ray_tpu as rt

    rt.init(
        num_cpus=2,
        _system_config={"metrics_timeseries_interval_s": 0.2},
    )
    try:
        from ray_tpu.util.metrics import (
            Counter,
            flush,
            metrics_timeseries,
        )

        counter = Counter("ts_probe_total")
        counter.inc(1.0)
        flush()
        deadline = time.time() + 30
        snaps = []
        while time.time() < deadline:
            snaps = metrics_timeseries(name="ts_probe_total")
            if len(snaps) >= 2:
                break
            counter.inc(1.0)
            flush()
            time.sleep(0.1)
        assert len(snaps) >= 2, "ring never spanned two intervals"
        totals = [
            s["metrics"]["ts_probe_total"]["total"] for s in snaps
        ]
        assert totals == sorted(totals)  # counter never goes down
        assert totals[-1] >= 1.0
        # HTTP surface agrees (query-param filtered).
        from ray_tpu.dashboard import start_dashboard

        dash = start_dashboard(port=0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/api/timeseries"
                "?name=ts_probe_total&limit=2",
                timeout=30,
            ) as resp:
                payload = json.loads(resp.read())
        finally:
            dash.stop()
        assert len(payload) == 2
        assert "ts_probe_total" in payload[-1]["metrics"]
    finally:
        rt.shutdown()


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_serve_request_path_e2e_two_nodes():
    """2-node cluster, HTTP traffic through a serve deployment:
    /metrics exposes parseable per-deployment request-latency
    histograms, /api/serve reports consistent counts and non-zero
    percentiles, and request ids round-trip as headers."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        head_resources={"CPU": 3.0},
        system_config={"metrics_timeseries_interval_s": 0.2},
    )
    try:
        cluster.add_node(num_cpus=2)
        cluster.wait_for_nodes(2, timeout=60)
        rt.init(address=cluster.address)
        import ray_tpu.serve as serve

        @serve.deployment(num_replicas=2)
        class Echo:
            def __call__(self, request):
                time.sleep(0.005)
                return {"path": request.path}

        try:
            port = serve.start(http_port=0)
            serve.run(Echo.bind(), name="app", route_prefix="/")
            n_requests = 20
            for i in range(n_requests):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/echo/{i}",
                    headers={"x-request-id": f"req-{i:04d}"},
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    assert resp.status == 200
                    # The id the client sent comes back.
                    assert (
                        resp.headers.get("x-request-id")
                        == f"req-{i:04d}"
                    )

            # Wait until every replica's records reached the head.
            deadline = time.time() + 60
            detail = {}
            while time.time() < deadline:
                detail = serve.status_detail().get("app/Echo", {})
                if detail.get("requests_total", 0) >= n_requests:
                    break
                time.sleep(0.25)
            assert detail.get("requests_total", 0) >= n_requests, (
                detail
            )
            assert detail["errors_total"] == 0
            assert detail["p50_ms"] > 0
            assert detail["p99_ms"] >= detail["p50_ms"]
            assert detail["replicas"] == 2
            assert "queue_depth" in detail and "in_flight" in detail

            from ray_tpu.dashboard import start_dashboard

            dash = start_dashboard(port=0)
            try:
                def fetch(path):
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{dash.port}{path}",
                        timeout=30,
                    ) as resp:
                        return resp.read().decode()

                prom = fetch("/metrics")
                # Parseable: every non-comment line is `series value`.
                for line in prom.splitlines():
                    if not line or line.startswith("#"):
                        continue
                    series, _, value = line.rpartition(" ")
                    assert series, line
                    float(value)  # must parse
                assert (
                    "# TYPE serve_request_latency_ms histogram"
                    in prom
                )
                assert 'deployment="Echo"' in prom
                assert 'le="+Inf"' in prom
                # /metrics and /api/serve agree on completed counts.
                prom_total = sum(
                    float(line.rsplit(" ", 1)[1])
                    for line in prom.splitlines()
                    if line.startswith("serve_requests_total{")
                    and 'deployment="Echo"' in line
                )
                api_detail = json.loads(fetch("/api/serve"))[
                    "app/Echo"
                ]
                assert prom_total == api_detail["requests_total"]
                assert api_detail["p50_ms"] > 0

                # Bounded history across >= 2 snapshot intervals.
                deadline = time.time() + 30
                snaps = []
                while time.time() < deadline:
                    snaps = json.loads(
                        fetch(
                            "/api/timeseries"
                            "?name=serve_requests_total"
                        )
                    )
                    if len(snaps) >= 2:
                        break
                    time.sleep(0.2)
                assert len(snaps) >= 2
            finally:
                dash.stop()
        finally:
            serve.shutdown()
    finally:
        rt.shutdown()
        cluster.shutdown()
