"""Unit tests for the shared-memory store, serialization, IDs, and the
resource arithmetic (reference analogs: plasma store tests, FixedPoint
tests in src/ray/common/scheduling)."""

import numpy as np
import pytest

from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.object_store import (
    ObjectStoreFullError,
    SharedMemoryStore,
)
from ray_tpu._private.scheduler import ResourceSet
from ray_tpu._private.serialization import SerializationContext


def _oid(i=1):
    return ObjectID.for_return(TaskID.from_random(), i)


class TestIDs:
    def test_object_id_embeds_task(self):
        t = TaskID.from_random()
        o = ObjectID.for_return(t, 3)
        assert o.task_id() == t
        assert o.index() == 3

    def test_task_id_deterministic(self):
        job = JobID.from_int(1)
        parent = TaskID.for_driver(job)
        a = TaskID.for_task(job, parent, 7)
        b = TaskID.for_task(job, parent, 7)
        c = TaskID.for_task(job, parent, 8)
        assert a == b
        assert a != c

    def test_hex_roundtrip(self):
        a = ActorID.of(JobID.from_int(9))
        assert ActorID.from_hex(a.hex()) == a
        assert a.job_id() == JobID.from_int(9)


class TestSerialization:
    def test_roundtrip_plain(self):
        ctx = SerializationContext()
        data = ctx.serialize({"x": 1, "y": [1, 2]}).to_bytes()
        assert ctx.deserialize(data) == {"x": 1, "y": [1, 2]}

    def test_numpy_out_of_band_zero_copy(self):
        ctx = SerializationContext()
        arr = np.arange(10_000, dtype=np.float64)
        serialized = ctx.serialize(arr)
        # Large arrays must go out-of-band, not through the pickle
        # stream (zero-copy requirement).
        assert len(serialized.buffers) == 1
        assert serialized.buffers[0].nbytes == arr.nbytes
        out = ctx.deserialize(serialized.to_bytes())
        np.testing.assert_array_equal(out, arr)

    def test_nested_arrays(self):
        ctx = SerializationContext()
        value = {"a": np.ones(5000), "b": [np.zeros(3000), "meta"]}
        out = ctx.deserialize(ctx.serialize(value).to_bytes())
        np.testing.assert_array_equal(out["a"], value["a"])
        np.testing.assert_array_equal(out["b"][0], value["b"][0])


class TestSharedMemoryStore:
    def test_create_seal_get(self):
        store = SharedMemoryStore("deadbeef", 1 << 20)
        oid = _oid()
        buf = store.create(oid, 5)
        buf[:5] = b"hello"
        assert not store.contains(oid)
        store.seal(oid)
        assert store.contains(oid)
        assert bytes(store.get(oid)[:5]) == b"hello"
        store.shutdown()

    def test_get_blocks_until_seal(self):
        import threading

        store = SharedMemoryStore("deadbee2", 1 << 20)
        oid = _oid()

        def writer():
            import time

            time.sleep(0.1)
            buf = store.create(oid, 3)
            buf[:3] = b"abc"
            store.seal(oid)

        threading.Thread(target=writer).start()
        view = store.get(oid, timeout=5)
        assert bytes(view[:3]) == b"abc"
        store.shutdown()

    def test_capacity_and_eviction(self):
        store = SharedMemoryStore("deadbee3", 4096 * 4)
        oids = [_oid(i + 1) for i in range(4)]
        for oid in oids:
            store.put(oid, b"x" * 4096)
        # Store is full; the next create evicts the LRU object.
        store.put(_oid(99), b"y" * 4096)
        assert not store.contains(oids[0])
        store.shutdown()

    def test_pinned_objects_not_evicted(self):
        store = SharedMemoryStore("deadbee4", 4096 * 2)
        first = _oid(1)
        store.put(first, b"x" * 4096)
        store.pin(first)
        with pytest.raises(ObjectStoreFullError):
            store.put(_oid(2), b"y" * 8192)
        assert store.contains(first)
        store.shutdown()

    def test_cross_instance_open(self):
        # Two store instances with the same node prefix model two
        # processes mapping the same segments.
        producer = SharedMemoryStore("deadbee5", 1 << 20)
        consumer = SharedMemoryStore("deadbee5", 1 << 20)
        oid = _oid()
        producer.put(oid, b"shared-bytes")
        view = consumer.open_remote(oid, 12)
        assert bytes(view[:12]) == b"shared-bytes"
        consumer.shutdown(unlink=False)
        producer.shutdown()


class TestSpillRestoreConcurrency:
    """Spill→restore under concurrency (ISSUE 14 satellite): the
    restore path must serve many concurrent consumers of the same
    spilled object exactly once each, with intact bytes — concurrent
    `rt.get`s race the `_restore_spilled` re-create and must all
    converge on one healthy copy."""

    @pytest.fixture
    def pressure_session(self):
        import ray_tpu as rt

        MB = 1024 * 1024
        rt.init(
            num_cpus=2,
            _system_config={
                "object_store_memory": 24 * MB,
                "object_spilling_threshold": 0.8,
                "object_eviction_check_interval_s": 0.1,
                "memory_report_interval_s": 0.2,
            },
        )
        yield rt
        rt.shutdown()

    def test_concurrent_gets_during_restore(self, pressure_session):
        import threading
        import time

        rt = pressure_session
        import ray_tpu.api as api

        daemon = api._session.daemon
        chunks = [
            np.full(1024 * 1024, i, dtype=np.uint32) for i in range(12)
        ]
        refs = [rt.put(c) for c in chunks]  # 48MB through 24MB store
        deadline = time.time() + 20
        while time.time() < deadline:
            if daemon.spill.stats()["spilled_objects"] > 0:
                break
            time.sleep(0.1)
        assert daemon.spill.stats()["spilled_objects"] > 0
        # The oldest objects spilled first; hammer one from many
        # threads so the gets race one in-flight restore.
        results = [None] * 8
        errors = []

        def fetch(slot):
            try:
                results[slot] = rt.get(refs[0], timeout=60)
            except Exception as e:  # noqa: BLE001 — collected below
                errors.append(e)

        threads = [
            threading.Thread(target=fetch, args=(i,))
            for i in range(len(results))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        assert not errors, errors
        for got in results:
            assert got is not None
            assert np.array_equal(got, chunks[0])
        # The restore bumped the counter the ledger rates ride on.
        assert daemon.core_counters.restores >= 1
        # And the spilled copies stay attributed in the ledger.
        from ray_tpu.util.state import memory_summary

        owners = memory_summary()["owners"]
        assert any(r["spilled_bytes"] > 0 for r in owners), owners


class TestResourceSet:
    def test_fits_and_subtract(self):
        total = ResourceSet({"CPU": 4, "TPU": 8})
        req = ResourceSet({"CPU": 0.5, "TPU": 1})
        assert req.fits_in(total)
        left = total.subtract(req)
        assert left.get("CPU") == 3.5
        assert left.get("TPU") == 7

    def test_fractional_exact(self):
        total = ResourceSet({"CPU": 1})
        third = ResourceSet({"CPU": 0.333})
        left = total.subtract(third).subtract(third).subtract(third)
        assert left.get("CPU") == pytest.approx(0.001)

    def test_missing_resource_does_not_fit(self):
        assert not ResourceSet({"TPU": 1}).fits_in(ResourceSet({"CPU": 4}))
