"""Named concurrency groups (reference: core_worker/transport/
concurrency_group_manager.h — each group is an independent executor of
declared width; methods bind to groups at definition time via
ray.method or per-call via .options)."""

import threading
import time

import pytest

import ray_tpu as rt


def test_groups_isolate_blocked_group(rt_session):
    """A call blocked in one group must not stall calls in another
    group or the default pool — the deadlock below resolves ONLY if
    `release` (default group) runs while `hold` (io group) is parked
    in its own pool."""

    @rt.remote(concurrency_groups={"io": 1})
    class A:
        def __init__(self):
            self.event = threading.Event()

        def hold(self):
            # Parks the io group's only thread until release() runs.
            assert self.event.wait(timeout=30)
            return "held"

        def release(self):
            self.event.set()
            return "released"

    a = A.remote()
    held = a.hold.options(concurrency_group="io").remote()
    time.sleep(0.2)  # hold() is parked in the io pool
    assert rt.get(a.release.remote(), timeout=30) == "released"
    assert rt.get(held, timeout=30) == "held"


def test_group_width_bounds_parallelism(rt_session):
    """Group width caps in-flight calls in that group, and width > 1
    genuinely overlaps them (both observed via an in-actor counter —
    pool threads share the instance)."""

    @rt.remote(concurrency_groups={"par": 2})
    class A:
        def __init__(self):
            self.lock = threading.Lock()
            self.active = 0
            self.peak = 0

        def work(self):
            with self.lock:
                self.active += 1
                self.peak = max(self.peak, self.active)
            time.sleep(0.3)
            with self.lock:
                self.active -= 1

        def peak_seen(self):
            return self.peak

    a = A.remote()
    rt.get(
        [
            a.work.options(concurrency_group="par").remote()
            for _ in range(4)
        ],
        timeout=60,
    )
    peak = rt.get(a.peak_seen.remote(), timeout=30)
    assert peak == 2, f"width-2 group should run exactly 2 at once: {peak}"


def test_method_decorator_binds_group(rt_session):
    """@rt.method(concurrency_group=...) routes calls without per-call
    options; group pool threads are observable by name."""

    @rt.remote(concurrency_groups={"io": 2})
    class A:
        @rt.method(concurrency_group="io")
        def fetch(self):
            return threading.current_thread().name

        def plain(self):
            return threading.current_thread().name

    a = A.remote()
    io_thread = rt.get(a.fetch.remote(), timeout=30)
    plain_thread = rt.get(a.plain.remote(), timeout=30)
    assert io_thread.startswith("rt-actor-io"), io_thread
    assert not plain_thread.startswith("rt-actor-io"), plain_thread


def test_unknown_group_rejected(rt_session):
    @rt.remote(concurrency_groups={"io": 1})
    class A:
        def f(self):
            return 1

    a = A.remote()
    with pytest.raises(ValueError, match="unknown concurrency group"):
        a.f.options(concurrency_group="nope").remote()  # rt: noqa[RT106] — submit raises; no ref exists

    with pytest.raises(ValueError, match="unknown concurrency group"):
        @rt.remote(concurrency_groups={"io": 1})
        class B:
            @rt.method(concurrency_group="gpu")
            def g(self):
                return 2

        B.remote()


def test_group_declaration_validated(rt_session):
    @rt.remote(concurrency_groups={"bad": 0})
    class A:
        def f(self):
            return 1

    with pytest.raises(ValueError, match="positive int"):
        A.remote()


def test_options_preserves_method_defaults(rt_session):
    """options(concurrency_group=...) must not reset an
    @rt.method(num_returns=...) definition-time default (review r5:
    the asymmetric merge silently dropped it)."""

    @rt.remote(concurrency_groups={"io": 1})
    class A:
        @rt.method(num_returns=2)
        def pair(self):
            return 1, 2

    a = A.remote()
    r1, r2 = a.pair.options(concurrency_group="io").remote()
    assert rt.get([r1, r2], timeout=30) == [1, 2]
