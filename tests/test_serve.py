"""Serve tests (reference test model: python/ray/serve/tests/ —
deploy/handle calls, composition, scaling, redeploy, HTTP ingress,
batching)."""

import json
import threading
import time
import urllib.request

import pytest


@pytest.fixture
def serve_session(rt_session):
    import ray_tpu.serve as serve

    yield rt_session, serve
    serve.shutdown()


def test_deploy_and_handle_call(serve_session):
    rt, serve = serve_session

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

        def triple(self, x):
            return 3 * x

    handle = serve.run(Doubler.bind(), name="app1", route_prefix=None)
    assert handle.remote(21).result(timeout=30) == 42
    assert handle.triple.remote(7).result(timeout=30) == 21


def test_composition_with_downstream_handle(serve_session):
    rt, serve = serve_session

    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def __call__(self, x):
            return x + self.increment

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            partial = self.adder.remote(x).result(timeout=30)
            return partial * 10

    handle = serve.run(
        Ingress.bind(Adder.bind(5)), name="app2", route_prefix=None
    )
    assert handle.remote(1).result(timeout=30) == 60


def test_multiple_replicas_share_load(serve_session):
    rt, serve = serve_session

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self, _):
            import os
            import time as _t

            _t.sleep(0.2)
            return os.getpid()

    handle = serve.run(WhoAmI.bind(), name="app3", route_prefix=None)
    responses = [handle.remote(i) for i in range(9)]
    pids = {r.result(timeout=60) for r in responses}
    assert len(pids) >= 2


def test_error_propagates(serve_session):
    rt, serve = serve_session

    @serve.deployment
    class Boom:
        def __call__(self, x):
            raise ValueError("kapow")

    handle = serve.run(Boom.bind(), name="app4", route_prefix=None)
    with pytest.raises(Exception, match="kapow"):
        handle.remote(1).result(timeout=30)


def test_redeploy_new_version(serve_session):
    rt, serve = serve_session

    @serve.deployment(version="1")
    class Model:
        def __call__(self, x):
            return "v1"

    h1 = serve.run(Model.bind(), name="app5", route_prefix=None)
    assert h1.remote(0).result(timeout=30) == "v1"

    @serve.deployment(name="Model", version="2")
    class Model2:
        def __call__(self, x):
            return "v2"

    h2 = serve.run(Model2.bind(), name="app5", route_prefix=None)
    deadline = time.time() + 15
    while time.time() < deadline:
        if h2.remote(0).result(timeout=30) == "v2":
            break
        time.sleep(0.2)
    assert h2.remote(0).result(timeout=30) == "v2"


def test_http_ingress(serve_session):
    rt, serve = serve_session
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    @serve.deployment
    class Api:
        def __call__(self, request):
            if request.method == "GET":
                return {
                    "path": request.path,
                    "q": request.query_params.get("q"),
                }
            data = request.json()
            return {"sum": data["a"] + data["b"]}

    serve.run(Api.bind(), name="default", route_prefix="/api")
    serve.start(http_port=port)

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/hello?q=1", timeout=30
    ) as resp:
        body = json.loads(resp.read())
    assert body == {"path": "/hello", "q": "1"}

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api",
        data=json.dumps({"a": 2, "b": 3}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert json.loads(resp.read()) == {"sum": 5}

    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=30
        )


def test_batching_groups_requests(serve_session):
    rt, serve = serve_session

    @serve.deployment
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def predict(self, items):
            self.batch_sizes.append(len(items))
            return [x * 10 for x in items]

        def seen(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="app6", route_prefix=None)
    responses = [handle.predict.remote(i) for i in range(8)]
    values = sorted(r.result(timeout=30) for r in responses)
    assert values == [i * 10 for i in range(8)]
    sizes = handle.seen.remote().result(timeout=30)
    assert max(sizes) > 1  # at least one real batch formed


def test_autoscaling_scales_up(serve_session):
    rt, serve = serve_session

    @serve.deployment(
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "target_ongoing_requests": 1.0,
            "upscale_delay_s": 0.3,
            "downscale_delay_s": 60.0,
        }
    )
    class Slow:
        def __call__(self, _):
            import time as _t

            _t.sleep(0.4)
            return 1

    handle = serve.run(Slow.bind(), name="app7", route_prefix=None)
    assert serve.status()["app7"]["deployments"]["Slow"]["replicas"] == 1

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                handle.remote(0).result(timeout=30)
            except Exception:
                return

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 20
        scaled = False
        while time.time() < deadline:
            replicas = serve.status()["app7"]["deployments"]["Slow"][
                "replicas"
            ]
            if replicas >= 2:
                scaled = True
                break
            time.sleep(0.25)
        assert scaled, "deployment never scaled past 1 replica"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)


def test_redeploy_pushed_to_idle_handle(serve_session):
    """Long-poll push (reference: long_poll.py): an IDLE handle's
    replica cache updates when the controller reconciles a new
    version — no request needed, no TTL window. The old TTL router
    only refreshed on calls, so this distinguishes push from poll."""
    rt, serve = serve_session

    @serve.deployment(version="v1")
    class Svc:
        def __call__(self, x):
            return "v1"

    handle = serve.run(Svc.bind(), name="pushapp", route_prefix=None)
    assert handle.remote(0).result(timeout=30) == "v1"
    with handle._lock:
        old_ids = {r["id"] for r in handle._state["replicas"]}

    @serve.deployment(version="v2")
    class Svc2:
        def __call__(self, x):
            return "v2"

    serve.run(
        Svc2.options(name=Svc.name).bind(),
        name="pushapp",
        route_prefix=None,
    )
    # The handle is idle; only the push can change its cache.
    deadline = time.time() + 5
    while time.time() < deadline:
        with handle._lock:
            new_ids = {r["id"] for r in handle._state["replicas"]}
        if new_ids and not (new_ids & old_ids):
            break
        time.sleep(0.02)
    assert new_ids and not (new_ids & old_ids), (
        f"push never replaced replicas: {old_ids} -> {new_ids}"
    )
    assert handle.remote(0).result(timeout=30) == "v2"


def test_streaming_handle_and_http(serve_session):
    """Generator ingress streams: chunks arrive AS the replica yields
    (reference: serve streaming responses / LLM token output). Both
    the handle path (DeploymentResponseGenerator) and the HTTP path
    (chunked transfer-encoding) must deliver incrementally."""
    rt, serve = serve_session

    @serve.deployment
    class Tokens:
        def __call__(self, request):
            for i in range(5):
                time.sleep(0.15)
                yield f"tok{i} "

    serve.run(Tokens.bind(), name="stream", route_prefix="/gen")
    port = serve.start(per_node=False)

    # Handle path: first chunk must land before the generator could
    # have finished (5 x 0.15s), proving incremental delivery.
    handle = serve.get_app_handle("stream")
    t0 = time.time()
    chunks, stamps = [], []
    for chunk in handle.options(stream=True).remote(None):
        chunks.append(chunk)
        stamps.append(time.time() - t0)
    assert chunks == [f"tok{i} " for i in range(5)]
    assert stamps[0] < 0.60, f"first chunk too late: {stamps}"

    # HTTP path: chunked transfer, read incrementally.
    t0 = time.time()
    response = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/gen", timeout=30
    )
    assert response.headers.get("Transfer-Encoding") == "chunked"
    first = response.read(5)
    first_at = time.time() - t0
    rest = response.read()
    assert (first + rest).decode() == "tok0 tok1 tok2 tok3 tok4 "
    assert first_at < 0.60, f"first HTTP chunk too late: {first_at}"


def test_interleaved_streams_not_serialized(serve_session):
    """Two token streams from ONE replica must progress concurrently
    — neither may head-of-line block the other in _stream_response /
    DeploymentResponseGenerator (ISSUE 10 satellite: a batched
    continuous-batching replica serves many interleaved streams; if
    stream B's chunks only arrive after stream A finishes, batching
    is dead on arrival)."""
    rt, serve = serve_session

    @serve.deployment
    class Paced:
        def __call__(self, request):
            for i in range(6):
                time.sleep(0.2)
                yield f"t{i} "

    handle = serve.run(Paced.bind(), name="pair", route_prefix=None)
    gen_a = handle.options(stream=True).remote(None)
    gen_b = handle.options(stream=True).remote(None)
    events = []

    def consume(tag, gen):
        for _chunk in gen:
            events.append((tag, time.time()))

    threads = [
        threading.Thread(target=consume, args=("a", gen_a)),
        threading.Thread(target=consume, args=("b", gen_b)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    a_times = [ts for tag, ts in events if tag == "a"]
    b_times = [ts for tag, ts in events if tag == "b"]
    assert len(a_times) == 6 and len(b_times) == 6
    # Interleaved, not serialized: each stream starts before the
    # other finishes.
    assert b_times[0] < a_times[-1], "stream b waited for stream a"
    assert a_times[0] < b_times[-1], "stream a waited for stream b"


def test_abandoned_stream_cancels_replica_side(serve_session):
    """Closing a DeploymentResponseGenerator mid-stream propagates a
    best-effort cancel to the replica (Replica.cancel_stream ->
    __serve_cancel_stream__), so producers that can stop do — the
    LLM engine frees the request's KV slot instead of decoding the
    whole budget for nobody."""
    rt, serve = serve_session

    @serve.deployment
    class Cancellable:
        def __init__(self):
            self.cancelled = []

        def __serve_cancel_stream__(self, request_id):
            self.cancelled.append(request_id)
            return True

        def seen_cancels(self):
            return list(self.cancelled)

        def __call__(self, request):
            from ray_tpu.serve.observability import get_request_id

            rid = get_request_id()
            for i in range(200):
                if rid in self.cancelled:
                    return
                time.sleep(0.05)
                yield f"c{i} "

    handle = serve.run(Cancellable.bind(), name="cancl", route_prefix=None)
    gen = handle.options(stream=True).remote(None)
    assert next(gen)  # stream is live
    gen.close()  # abandoned mid-stream
    deadline = time.time() + 20
    seen = []
    while time.time() < deadline and not seen:
        seen = handle.seen_cancels.remote().result(timeout=30)
        time.sleep(0.1)
    assert seen, "cancel_stream never reached the replica"


def test_streaming_error_truncates_chunked_body(serve_session):
    """A replica generator that raises mid-stream must NOT produce a
    well-formed chunked body: the proxy aborts the socket without the
    terminal 0-chunk so the client sees a protocol-level truncation
    (http.client raises IncompleteRead/connection error) rather than a
    clean 200 with silently missing content (reference: ASGI proxies
    surface mid-stream failure by killing the connection — the
    response is unrecoverable once the 200 status line is out)."""
    import http.client

    rt, serve = serve_session

    @serve.deployment
    class Flaky:
        def __call__(self, request):
            yield "good "
            raise RuntimeError("replica exploded mid-stream")

    serve.run(Flaky.bind(), name="flaky", route_prefix="/flaky")
    port = serve.start(per_node=False)

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", "/flaky")
        resp = conn.getresponse()
        assert resp.status == 200  # headers were already committed
        with pytest.raises(
            (http.client.IncompleteRead, ConnectionError, OSError)
        ):
            resp.read()
    finally:
        conn.close()


def test_per_node_proxies_route_local_first():
    """serve.start places a proxy on EVERY node (reference:
    proxy_state.py), and each proxy's router prefers replicas on its
    own node (reference: pow_2 locality-aware candidates)."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 2.0})
    cluster.add_node(num_cpus=2.0)
    cluster.wait_for_nodes(2, timeout=60)
    rt.init(address=cluster.address)
    try:
        @serve.deployment(num_replicas=2)
        class WhereAmI:
            def __call__(self, request):
                return rt.get_runtime_context().get_node_id()

        serve.run(WhereAmI.bind(), name="local", route_prefix="/where")
        serve.start(http_port=0, per_node=True)
        ports = serve.proxy_ports()
        assert len(ports) == 2, f"expected 2 proxies: {ports}"

        # Replicas must have landed on both nodes for the locality
        # check to mean anything (2 CPUs/node, 1 CPU/replica, head
        # also hosts controller workers — verify, don't assume).
        controller = rt.get_actor("SERVE_CONTROLLER", namespace="serve")
        replicas = rt.get(
            controller.get_replicas.remote("local", "WhereAmI"),
            timeout=30,
        )
        replica_nodes = {r["node_id"] for r in replicas}
        if len(replica_nodes) == 2:
            # Each node's proxy should answer with ITS node's replica.
            for node_id, port in ports.items():
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/where", timeout=30
                ).read().decode().strip('"')
                assert body == node_id, (
                    f"proxy on {node_id[:8]} answered from {body[:8]}"
                )
        else:
            # Both replicas packed one node: proxies must still serve.
            for node_id, port in ports.items():
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/where", timeout=30
                ).read()
    finally:
        serve.shutdown()
        rt.shutdown()
        cluster.shutdown()


def test_grpc_ingress_round_trip(serve_session):
    """gRPC ingress beside the HTTP proxy (reference: proxy.py:431
    gRPCProxy): a generic bytes-unary client calls
    /ray.serve.RayServeAPIService/Predict with the application in call
    metadata and gets the deployment's reply; Healthz and
    ListApplications serve the built-in API surface."""
    import json as _json

    grpc = pytest.importorskip("grpc")
    rt, serve = serve_session
    from ray_tpu.serve.grpc_ingress import grpc_methods

    @serve.deployment
    class Echo:
        def __call__(self, payload: bytes):
            return b"grpc:" + payload

    serve.run(Echo.bind(), name="gapp", route_prefix="/gapp")
    serve.start(per_node=False, grpc_port=0)
    port = serve.local_grpc_port()
    assert port

    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    predict, healthz, list_apps = grpc_methods(channel)
    try:
        assert healthz(b"") == b"success"
        apps = _json.loads(list_apps(b""))
        assert "gapp" in apps
        reply = predict(
            b"hello", metadata=[("application", "gapp")]
        )
        assert reply == b"grpc:hello"
        with pytest.raises(grpc.RpcError):
            predict(b"x", metadata=[("application", "missing")])
    finally:
        channel.close()


def test_multiplexed_lru_and_router_warmth(serve_session):
    """@serve.multiplexed (reference: serve/multiplex.py + api.py:559):
    each replica holds at most max_num_models_per_replica models in an
    LRU; serve.get_multiplexed_model_id() exposes the request's model;
    and the router prefers replicas already holding the model (warm
    routing) once the controller pushes holder sets."""
    import time as _time

    rt, serve = serve_session

    @serve.deployment(num_replicas=2)
    class Multi:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            self.loads.append(model_id)
            return f"model-{model_id}"

        def __call__(self, request):
            model_id = serve.get_multiplexed_model_id()
            model = self.get_model(model_id)
            import os

            return {
                "model": model,
                "model_id": model_id,
                "pid": os.getpid(),
                "loads": list(self.loads),
            }

    serve.run(Multi.bind(), name="multi", route_prefix="/multi")
    handle = serve.get_app_handle("multi")

    # First call for m1 loads it somewhere.
    out = handle.options(multiplexed_model_id="m1").remote(
        None
    ).result(timeout=60)
    assert out["model"] == "model-m1"
    assert out["model_id"] == "m1"
    warm_pid = out["pid"]

    # Give the controller push a moment, then hammer m1: every call
    # should land on the warm replica (no second replica load).
    deadline = _time.time() + 10
    routed_warm = False
    while _time.time() < deadline:
        out = handle.options(multiplexed_model_id="m1").remote(
            None
        ).result(timeout=60)
        if out["pid"] == warm_pid:
            routed_warm = True
            if out["loads"].count("m1") == 1:
                break
        _time.sleep(0.1)
    assert routed_warm
    assert out["loads"].count("m1") == 1, (
        f"warm replica reloaded m1: {out['loads']}"
    )

    # LRU bound: push three models through ONE replica's cache and
    # assert the cap held (loads grow, cache doesn't).
    for model_id in ("m2", "m3", "m4"):
        res = handle.options(
            multiplexed_model_id=model_id
        ).remote(None).result(timeout=60)
        assert res["model"] == f"model-{model_id}"

    # Inspect replica-side cache sizes via the controller's view.
    controller = rt.get_actor("SERVE_CONTROLLER", namespace="serve")
    deadline = _time.time() + 10
    ok = False
    while _time.time() < deadline:
        replicas = rt.get(
            controller.get_replicas.remote("multi", "Multi"),
            timeout=30,
        )
        sizes = [len(r.get("model_ids", [])) for r in replicas]
        if any(sizes) and all(size <= 2 for size in sizes):
            ok = True
            break
        _time.sleep(0.2)
    assert ok, f"replica model sets never bounded: {sizes}"


def test_proxy_admission_control_and_keepalive():
    """Ingress hardening (VERDICT r4 weak #6): the proxy bounds
    in-flight requests (immediate 503 + Retry-After past the cap, no
    unbounded thread stacking) and connections (raw 503 before a
    handler thread spawns); keep-alive connections serve multiple
    requests. Unit-level: the Proxy is driven directly with a stubbed
    dispatch, no controller needed."""
    import http.client
    import socket as socklib
    import threading
    import time as timelib

    from ray_tpu.serve.proxy import Proxy

    proxy = Proxy(0, max_concurrent_requests=2, max_connections=4)
    try:
        port = proxy.port

        # keep-alive: two sequential requests over ONE connection.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        for _ in range(2):
            conn.request("GET", "/-/healthz")
            resp = conn.getresponse()
            assert resp.status == 200
            assert b"shed_requests" in resp.read()
        conn.close()

        # request saturation: 2 slots, 6 concurrent slow requests.
        proxy._dispatch = (
            lambda handler: (timelib.sleep(0.6), (200, b"ok", "text/plain"))[1]
        )
        statuses = []
        lock = threading.Lock()

        def hit():
            c = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=10
            )
            try:
                c.request("GET", "/x")
                r = c.getresponse()
                body = r.read()
                with lock:
                    statuses.append((r.status, body))
            finally:
                c.close()

        threads = [threading.Thread(target=hit) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        codes = sorted(s for s, _ in statuses)
        assert codes.count(200) >= 2, codes
        assert codes.count(503) >= 1, codes
        assert proxy.shed_requests >= 1

        # connection cap: hold 4 idle keep-alive connections open,
        # the 5th gets an immediate raw 503 + close.
        proxy._dispatch = lambda handler: (200, b"ok", "text/plain")
        held = []
        for _ in range(4):
            c = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=10
            )
            c.request("GET", "/x")
            assert c.getresponse().read() == b"ok"
            held.append(c)  # keep-alive: still counted
        extra = socklib.create_connection(("127.0.0.1", port), timeout=10)
        try:
            extra.sendall(b"GET /x HTTP/1.1\r\nHost: h\r\n\r\n")
            head = extra.recv(64)
            assert b"503" in head, head
        finally:
            extra.close()
        assert proxy.shed_connections >= 1
        for c in held:
            c.close()
    finally:
        proxy.stop()
