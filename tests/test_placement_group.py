"""Placement-group tests (reference test model:
python/ray/tests/test_placement_group*.py — creation/ready, strategy
semantics across nodes, bundle-index targeting, removal releasing
resources, rescheduling on node death)."""

import time

import pytest


@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    yield c
    c.shutdown()


@pytest.fixture
def rt_cluster(cluster):
    import ray_tpu as rt

    rt.init(address=cluster.address)
    yield rt, cluster
    rt.shutdown()


def test_create_wait_ready_and_schedule(rt_session):
    rt = rt_session
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
    )

    pg = placement_group([{"CPU": 1.0}, {"CPU": 1.0}], strategy="PACK")
    assert pg.wait(10)
    assert rt.get(pg.ready(), timeout=10) is True

    @rt.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg
        ),
    )
    def inside():
        return "ok"

    assert rt.get(inside.remote(), timeout=10) == "ok"


def test_pg_pending_until_feasible(rt_cluster):
    rt, cluster = rt_cluster
    from ray_tpu.util import placement_group

    # Head has 2 CPU; a 4-CPU bundle can't exist yet.
    pg = placement_group([{"CPU": 4.0}], strategy="PACK")
    assert not pg.wait(0.5)
    assert pg.state() == "PENDING"
    cluster.add_node(num_cpus=4)
    assert pg.wait(10)


def test_strict_spread_lands_on_distinct_nodes(rt_cluster):
    rt, cluster = rt_cluster
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        placement_group_table,
    )

    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(3)
    pg = placement_group(
        [{"CPU": 1.0}, {"CPU": 1.0}, {"CPU": 1.0}], strategy="STRICT_SPREAD"
    )
    assert pg.wait(10)
    [entry] = [
        e
        for e in placement_group_table()
        if e["placement_group_id"] == pg.id
    ]
    assert entry["state"] == "CREATED"
    assert len(set(entry["bundle_nodes"])) == 3

    # Bundle-index targeting pins tasks to the bundle's node.
    @rt.remote(num_cpus=1)
    def where():
        import os

        return os.environ.get("RT_SOCKET", "")

    sockets = set()
    for index in range(3):
        strat = PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=index
        )
        sockets.add(
            rt.get(where.options(scheduling_strategy=strat).remote(),
                   timeout=30)
        )
    assert len(sockets) == 3


def test_strict_pack_on_one_node(rt_cluster):
    rt, cluster = rt_cluster
    from ray_tpu.util import placement_group, placement_group_table

    cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(2)
    pg = placement_group(
        [{"CPU": 2.0}, {"CPU": 2.0}], strategy="STRICT_PACK"
    )
    assert pg.wait(10)
    [entry] = [
        e
        for e in placement_group_table()
        if e["placement_group_id"] == pg.id
    ]
    assert len(set(entry["bundle_nodes"])) == 1


def test_remove_releases_resources(rt_session):
    rt = rt_session
    from ray_tpu.util import placement_group, remove_placement_group

    before = rt.available_resources().get("CPU", 0.0)
    pg = placement_group([{"CPU": 2.0}], strategy="PACK")
    assert pg.wait(10)
    during = rt.available_resources().get("CPU", 0.0)
    assert during == pytest.approx(before - 2.0)
    remove_placement_group(pg)
    deadline = time.time() + 5
    while time.time() < deadline:
        if rt.available_resources().get("CPU", 0.0) == pytest.approx(before):
            break
        time.sleep(0.05)
    assert rt.available_resources().get("CPU", 0.0) == pytest.approx(before)


def test_actor_in_placement_group(rt_session):
    rt = rt_session
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
    )

    pg = placement_group([{"CPU": 1.0}], strategy="PACK")
    assert pg.wait(10)

    @rt.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        ),
    )
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert rt.get(c.bump.remote(), timeout=15) == 1


def test_pg_rescheduled_after_node_death(rt_cluster):
    rt, cluster = rt_cluster
    from ray_tpu.util import placement_group, placement_group_table

    victim = cluster.add_node(num_cpus=4, resources={"big": 4.0})
    cluster.wait_for_nodes(2)
    # Bundle only fits on the worker node (head has 2 CPU).
    pg = placement_group([{"CPU": 3.0}], strategy="PACK")
    assert pg.wait(10)
    cluster.remove_node(victim)
    # Group goes to RESCHEDULING; a replacement node revives it.
    deadline = time.time() + 10
    while time.time() < deadline:
        [entry] = [
            e
            for e in placement_group_table()
            if e["placement_group_id"] == pg.id
        ]
        if entry["state"] == "RESCHEDULING":
            break
        time.sleep(0.05)
    assert entry["state"] == "RESCHEDULING"
    cluster.add_node(num_cpus=4)
    assert pg.wait(15)


def test_capture_child_tasks(rt_session):
    """Children of a capturing task inherit the group (reference:
    placement_group_capture_child_tasks)."""
    rt = rt_session
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
    )

    pg = placement_group([{"CPU": 2.0}], strategy="PACK")
    assert pg.wait(10)

    @rt.remote(num_cpus=1)
    def child():
        return "child-done"

    @rt.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg,
            placement_group_capture_child_tasks=True,
        ),
    )
    def parent():
        import ray_tpu as rt_inner

        ref = child.remote()
        return rt_inner.get(ref, timeout=20)

    assert rt.get(parent.remote(), timeout=30) == "child-done"
    # The child consumed group resources: with capture, both parent and
    # child fit only because the bundle has 2 CPUs.


def test_head_only_pending_pg_retries_on_capacity_free(rt_session):
    """A PENDING group on a single-node cluster is retried when running
    tasks release their resources (no heartbeat traffic exists)."""
    rt = rt_session
    import threading

    from ray_tpu.util import placement_group

    release = threading.Event()

    @rt.remote(num_cpus=3)
    def hog():
        import time as _t

        _t.sleep(1.0)
        return "done"

    ref = hog.remote()
    import time as _t

    # Wait until hog's resources are actually RESERVED (lease grants
    # reserve at worker registration, not submit — a fixed sleep races
    # worker spawn latency).
    deadline = _t.time() + 10
    while _t.time() < deadline:
        if rt.available_resources().get("CPU", 4.0) <= 1.0:
            break
        _t.sleep(0.05)
    assert rt.available_resources().get("CPU", 4.0) <= 1.0
    pg = placement_group([{"CPU": 3.0}], strategy="PACK")
    assert pg.state() == "PENDING"
    assert rt.get(ref, timeout=20) == "done"
    assert pg.wait(10)


def test_remove_pg_fails_queued_tasks(rt_session):
    """Tasks queued on a removed group's resources fail instead of
    hanging."""
    rt = rt_session
    import ray_tpu.exceptions as exc
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1.0}], strategy="PACK")
    assert pg.wait(10)

    @rt.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg
        ),
    )
    def slow():
        import time as _t

        _t.sleep(3.0)
        return "first"

    first = slow.remote()
    second = slow.remote()  # queued behind first in the 1-CPU bundle
    import time as _t

    _t.sleep(0.5)
    remove_placement_group(pg)
    with pytest.raises(Exception):
        rt.get(second, timeout=10)


def test_named_pg_lookup_and_duplicate_rejection(rt_session):
    rt = rt_session
    from ray_tpu.util import get_placement_group, placement_group

    pg = placement_group([{"CPU": 1.0}], strategy="PACK", name="gang")
    assert pg.wait(10)
    found = get_placement_group("gang")
    assert found.id == pg.id
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1.0}], name="gang")
