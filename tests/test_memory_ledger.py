"""Cluster memory & per-job usage ledger tests (ISSUE 14).

Covers the pure fold (`memory_ledger.build_node_report`), the head
aggregation (byte·s integration, spill/restore rates, the
`verdict.memory` gates), live-session attribution end to end (seal →
report → `memory_summary` → `rt_job_*`/`rt_object_owner_*` Prometheus
series → time-series ring), the leak-suspect path (killed actor owner
flips `doctor` to exit 1 naming the object), the size-descending
state-API fix, and — slow-marked — the 2-node `ray_tpu memory --json`
CLI smoke with the exit-code contract.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.memory_ledger import (
    MemoryLedger,
    build_node_report,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MB = 1024 * 1024


def _oid(i: int) -> ObjectID:
    return ObjectID.for_return(TaskID.from_random(), i)


def _entry(
    i,
    size,
    job="job1",
    owner="driver",
    owner_pid=1,
    created_ts=100.0,
    pinned=True,
    spilled=False,
    in_shm=True,
):
    return (
        _oid(i), size, job, owner, owner_pid, created_ts, pinned,
        spilled, in_shm,
    )


# ---------------------------------------------------------------------------
# pure fold
# ---------------------------------------------------------------------------


class TestBuildNodeReport:
    def test_owner_attribution_and_topk(self):
        entries = [
            _entry(1, 40, job="a", owner="driver"),
            _entry(2, 30, job="a", owner="task:t1", pinned=False),
            _entry(3, 20, job="b", owner="actor:x1"),
            _entry(4, 10, job="", owner=""),  # unattributed
        ]
        report = build_node_report(
            "node1",
            entries,
            {"used": 110, "capacity": 200, "num_objects": 4},
            {"spilled_bytes": 0, "spilled_objects": 0},
            topk=2,
            now=200.0,
            pid_alive=lambda pid: True,
        )
        assert report["attributed_bytes"] == 90
        assert report["attribution_fraction"] == pytest.approx(
            90 / 110, abs=1e-3
        )
        owners = report["owners"]
        assert owners["a|driver"]["bytes"] == 40
        assert owners["a|driver"]["pinned_objects"] == 1
        assert owners["a|task:t1"]["bytes"] == 30
        assert owners["b|actor:x1"]["bytes"] == 20
        # Top-K is size-descending and bounded.
        top = report["top_objects"]
        assert [r["size"] for r in top] == [40, 30]
        assert top[0]["age_s"] == pytest.approx(100.0)

    def test_dead_owner_candidates(self):
        entries = [
            _entry(1, 50, owner="actor:a1", owner_pid=111),
            _entry(2, 40, owner="task:t1", owner_pid=222),
        ]
        report = build_node_report(
            "node1",
            entries,
            {"used": 90, "capacity": 100},
            topk=5,
            now=200.0,
            pid_alive=lambda pid: pid != 111,
        )
        dead = report["dead_owner_objects"]
        assert len(dead) == 1
        assert dead[0]["owner"] == "actor:a1"
        assert dead[0]["owner_alive"] is False
        # The same object in top_objects carries the liveness flag.
        flags = {
            r["owner"]: r["owner_alive"] for r in report["top_objects"]
        }
        assert flags == {"actor:a1": False, "task:t1": True}

    def test_spilled_objects_attributed_without_shm_bytes(self):
        entries = [
            _entry(1, 60, spilled=True, in_shm=False),
            _entry(2, 40),
        ]
        report = build_node_report(
            "node1",
            entries,
            {"used": 40, "capacity": 100},
            {"spilled_bytes": 60, "spilled_objects": 1},
            now=200.0,
            pid_alive=lambda pid: True,
        )
        row = report["owners"]["job1|driver"]
        assert row["bytes"] == 40  # arena bytes only
        assert row["spilled_bytes"] == 60
        assert report["spilled_objects"] == 1


def test_report_fold_overhead_invisible_at_10k_objects():
    """The per-tick fold at 10k live objects must cost <1% of the
    default report interval (the PR 5 flight-recorder bar) so the
    report loop can never surface in bench step medians — the
    committed `memory_report_ms` microbench tracks the same fold."""
    from ray_tpu._private.config import Config

    task = TaskID.from_random()
    entries = [
        (
            ObjectID.for_return(task, i + 1),
            (i % 64 + 1) * 4096,
            f"{i % 8:08x}",
            f"task:{i % 200:040x}",
            0,
            100.0,
            i % 3 == 0,
            i % 17 == 0,
            True,
        )
        for i in range(10_000)
    ]
    size_info = {"used": 1 << 30, "capacity": 1 << 34}
    best_ms = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        build_node_report(
            "n", entries, size_info, topk=20, now=200.0,
            pid_alive=lambda pid: True,
        )
        best_ms = min(best_ms, (time.perf_counter() - t0) * 1e3)
    budget_ms = 0.01 * Config().memory_report_interval_s * 1000.0
    assert best_ms < budget_ms, (
        f"fold {best_ms:.1f} ms exceeds 1% of the "
        f"{Config().memory_report_interval_s:g}s report interval"
    )


# ---------------------------------------------------------------------------
# head ledger
# ---------------------------------------------------------------------------


def _report(node, t, job_bytes, spill_ops=0, restore_ops=0, **kw):
    owners = {
        f"{job}|driver": {
            "job": job,
            "owner": "driver",
            "bytes": size,
            "objects": 1,
            "pinned_objects": 1,
            "spilled_bytes": 0,
        }
        for job, size in job_bytes.items()
    }
    used = sum(job_bytes.values())
    report = {
        "node": node,
        "time": t,
        "arena_used": used,
        "arena_capacity": kw.get("capacity", 1000),
        "tracked_objects": len(job_bytes),
        "spilled_bytes": 0,
        "spilled_objects": 0,
        "spill_ops_total": spill_ops,
        "restore_ops_total": restore_ops,
        "owners": owners,
        "attributed_bytes": used,
        "attribution_fraction": 1.0,
        "top_objects": kw.get("top_objects", []),
        "dead_owner_objects": kw.get("dead_owner_objects", []),
    }
    return report


class TestMemoryLedger:
    def test_byte_seconds_integrate_over_report_intervals(self):
        ledger = MemoryLedger()
        ledger.fold(_report("n1", 100.0, {"a": 50}))
        ledger.fold(_report("n1", 110.0, {"a": 50}))
        jobs = ledger.jobs()
        assert jobs["a"]["object_bytes"] == 50
        assert jobs["a"]["object_byte_seconds"] == pytest.approx(500.0)
        # Second interval with half the bytes integrates half as fast.
        ledger.fold(_report("n1", 120.0, {"a": 25}))
        assert ledger.jobs()["a"]["object_byte_seconds"] == pytest.approx(
            1000.0
        )

    def test_chip_seconds_from_step_records(self):
        ledger = MemoryLedger()
        # Accumulated once per record at APPEND time (daemon
        # _apply_metric_record) — warmup records are setup wall, not
        # chip work, and never bill.
        for record in (
            {"time": 1.0, "job": "a", "step_ms": 500.0},
            {"time": 2.0, "job": "a", "step_ms": 500.0},
            {"time": 2.0, "job": "b", "step_ms": 250.0},
            {"time": 2.5, "job": "a", "warmup": True, "step_ms": 99.0},
            {"time": 3.0, "job": "", "step_ms": 99.0},
        ):
            ledger.add_step(record)
        jobs = ledger.jobs()
        assert jobs["a"]["chip_seconds"] == pytest.approx(1.0)
        assert jobs["b"]["chip_seconds"] == pytest.approx(0.25)
        assert "" not in jobs

    def test_accumulator_eviction_never_starves_new_job(self):
        """A full accumulator table evicts the SMALLEST other row, not
        the key just bumped — otherwise every new job past the bound
        would have its first (smallest) row popped on insert and never
        accumulate anything."""
        from ray_tpu._private import memory_ledger as ml

        ledger = MemoryLedger()
        for i in range(ml._MAX_JOBS):
            ledger.add_step({"job": f"j{i}", "step_ms": 1000.0 * (i + 2)})
        # Table is full; the newest job is also the smallest row.
        ledger.add_step({"job": "late", "step_ms": 1000.0})
        jobs = ledger.jobs()
        assert jobs["late"]["chip_seconds"] == pytest.approx(1.0)
        # The smallest pre-existing row (j0) was the victim instead.
        assert "j0" not in jobs

    def test_metric_entries_shape(self):
        ledger = MemoryLedger()
        ledger.fold(_report("n1", 100.0, {"a": 50, "b": 30}))
        ledger.fold(_report("n1", 101.0, {"a": 50, "b": 30}))
        entries = ledger.metric_entries()
        assert entries["rt_job_object_bytes"]["by_tags"]["job=a"] == {
            "value": 50
        }
        assert (
            entries["rt_job_object_byte_seconds_total"]["kind"]
            == "counter"
        )
        owner_tags = entries["rt_object_owner_bytes"]["by_tags"]
        assert owner_tags["job=a|owner=driver"] == {"value": 50}

    def test_owner_metric_labels_collapse_to_kind(self):
        """The exported owner label is the owning-context KIND, never
        a per-entity id: two task owners in one job must merge into
        one bounded `owner=task` series (per-id labels are the RT010
        bug class — even a top-K cut churns the Prometheus label set
        over the cluster's lifetime)."""
        ledger = MemoryLedger()
        report = _report("n1", 100.0, {})
        report["owners"] = {
            "a|task:" + "1" * 40: {
                "job": "a", "owner": "task:" + "1" * 40,
                "bytes": 30, "objects": 1, "pinned_objects": 0,
                "spilled_bytes": 0,
            },
            "a|task:" + "2" * 40: {
                "job": "a", "owner": "task:" + "2" * 40,
                "bytes": 20, "objects": 1, "pinned_objects": 0,
                "spilled_bytes": 0,
            },
            "a|actor:" + "3" * 40: {
                "job": "a", "owner": "actor:" + "3" * 40,
                "bytes": 10, "objects": 1, "pinned_objects": 0,
                "spilled_bytes": 0,
            },
        }
        report["arena_used"] = report["attributed_bytes"] = 60
        ledger.fold(report)
        owner_tags = ledger.metric_entries()["rt_object_owner_bytes"][
            "by_tags"
        ]
        assert owner_tags == {
            "job=a|owner=task": {"value": 50},
            "job=a|owner=actor": {"value": 10},
        }
        # The full per-owner map stays id-resolved for /api/memory.
        assert {r["owner"] for r in ledger.owners()} == {
            "task:" + "1" * 40,
            "task:" + "2" * 40,
            "actor:" + "3" * 40,
        }

    def test_verdict_near_capacity_and_thrash(self):
        ledger = MemoryLedger()
        ledger.fold(
            _report("n1", 100.0, {"a": 950}, capacity=1000, spill_ops=0)
        )
        ledger.fold(
            _report(
                "n1",
                105.0,
                {"a": 950},
                capacity=1000,
                spill_ops=10,
                restore_ops=8,
            )
        )
        verdict = ledger.verdict(leak_age_s=300.0, now=105.0)
        assert len(verdict["near_capacity"]) == 1
        assert verdict["near_capacity"][0]["node"] == "n1"
        assert len(verdict["spill_thrash"]) == 1
        assert "restore rate" in verdict["spill_thrash"][0]["detail"]
        # Cold-data spilling (few restores) is NOT thrash.
        ledger.fold(
            _report(
                "n1", 110.0, {"a": 100}, spill_ops=20, restore_ops=9
            )
        )
        verdict = ledger.verdict(leak_age_s=300.0, now=110.0)
        assert verdict["spill_thrash"] == []
        assert verdict["near_capacity"] == []

    def test_verdict_leak_gates_on_age_and_owner_death(self):
        dead_row = {
            "object_id": "ab" * 20,
            "size": 100,
            "job": "a",
            "owner": "actor:x",
            "owner_alive": False,
            "age_s": 400.0,
            "pinned": True,
        }
        young = dict(dead_row, object_id="cd" * 20, age_s=5.0)
        ledger = MemoryLedger()
        ledger.fold(
            _report(
                "n1",
                100.0,
                {"a": 100},
                dead_owner_objects=[dead_row, young],
            )
        )
        verdict = ledger.verdict(leak_age_s=300.0)
        assert [s["object_id"] for s in verdict["leak_suspects"]] == [
            "ab" * 20
        ]
        # A looser deadline convicts the young one too; a stricter
        # one convicts neither.
        assert len(ledger.verdict(leak_age_s=1.0)["leak_suspects"]) == 2
        assert ledger.verdict(leak_age_s=500.0)["leak_suspects"] == []

    def test_verdict_leak_on_ended_job(self):
        row = {
            "object_id": "ef" * 20,
            "size": 100,
            "job": "gone",
            "owner": "driver",
            "owner_alive": True,
            "age_s": 400.0,
            "pinned": True,
        }
        ledger = MemoryLedger()
        ledger.fold(_report("n1", 100.0, {"gone": 100}, top_objects=[row]))
        verdict = ledger.verdict(
            leak_age_s=300.0, job_ended=lambda job: job == "gone"
        )
        assert len(verdict["leak_suspects"]) == 1
        assert "job already ended" in verdict["leak_suspects"][0]["detail"]
        assert ledger.verdict(leak_age_s=300.0)["leak_suspects"] == []

    def test_dead_node_report_dropped(self):
        ledger = MemoryLedger()
        ledger.fold(_report("n1", 100.0, {"a": 50}))
        ledger.fold(_report("n2", 100.0, {"a": 30}))
        assert ledger.jobs()["a"]["object_bytes"] == 80
        ledger.drop_node("n2")
        assert ledger.jobs()["a"]["object_bytes"] == 50


# ---------------------------------------------------------------------------
# live session: attribution, series, leak doctor, state API
# ---------------------------------------------------------------------------


@pytest.fixture
def ledger_session():
    import ray_tpu as rt

    rt.init(
        num_cpus=2,
        _system_config={
            "memory_report_interval_s": 0.2,
            "metrics_timeseries_interval_s": 0.3,
        },
    )
    yield rt
    rt.shutdown()


def test_put_bytes_attributed_to_job_and_exported(ledger_session):
    """Acceptance core: ≥95% of reported arena-used bytes attribute
    to a (job, owner) pair, and the ledger's `rt_job_*` /
    `rt_object_owner_*` series render on the Prometheus surface and
    land in consecutive time-series snapshots."""
    rt = ledger_session
    refs = [
        rt.put(np.ones(500_000, dtype=np.float64)) for _ in range(3)
    ]
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util.state import memory_summary

    job_hex = global_worker().job_id.hex()
    mem = memory_summary()
    totals = mem["totals"]
    assert totals["arena_used"] > 0
    assert totals["attribution_fraction"] >= 0.95, totals
    assert mem["jobs"][job_hex]["object_bytes"] >= 3 * 4_000_000
    assert mem["jobs"][job_hex]["pinned_objects"] == 3
    owner_rows = [
        r for r in mem["owners"] if r["job"] == job_hex
    ]
    assert owner_rows and owner_rows[0]["owner"] == "driver"
    # Prometheus exposition carries the per-job and per-owner series.
    from ray_tpu.util.metrics import metrics_summary
    from ray_tpu.util.prometheus import render_prometheus

    text = render_prometheus(metrics_summary())
    assert f'rt_job_object_bytes{{job="{job_hex}"}}' in text
    assert "rt_object_owner_bytes{" in text
    # Two consecutive snapshot-ring entries carry the series (the
    # trend survives the live window).
    from ray_tpu.util.metrics import metrics_timeseries

    deadline = time.time() + 15
    snaps = []
    while time.time() < deadline and len(snaps) < 2:
        snaps = metrics_timeseries(name="rt_job_object_bytes")
        time.sleep(0.2)
    assert len(snaps) >= 2, "series never reached 2 snapshots"
    for snap in snaps[-2:]:
        by_tags = snap["metrics"]["rt_job_object_bytes"]["by_tags"]
        assert by_tags[f"job={job_hex}"]["value"] > 0
    # Step telemetry feeds the per-job chip·s counter: after a few
    # reported steps the series appears in consecutive snapshots too.
    from ray_tpu.train import telemetry
    from ray_tpu.util import metrics as um

    for step in range(1, 4):
        telemetry.report_step(
            step, rank=0, step_ms=100.0, wall_ms=110.0
        )
    um.flush()
    deadline = time.time() + 15
    chip_snaps = []
    while time.time() < deadline and len(chip_snaps) < 2:
        chip_snaps = metrics_timeseries(
            name="rt_job_chip_seconds_total"
        )
        time.sleep(0.2)
    assert len(chip_snaps) >= 2, "chip·s series never snapshotted"
    latest = chip_snaps[-1]["metrics"]["rt_job_chip_seconds_total"]
    assert latest["by_tags"][f"job={job_hex}"]["total"] == pytest.approx(
        0.3
    )
    del refs


def test_interval_zero_is_a_real_kill_switch():
    """`memory_report_interval_s=0` stands the ledger down WHOLE:
    no on-demand head folds, no rt_job_* series, and the summary says
    `disabled` — a head-only fold would dress a half-blind ledger up
    as cluster truth (worker nodes aren't reporting)."""
    import ray_tpu as rt

    rt.init(
        num_cpus=1, _system_config={"memory_report_interval_s": 0}
    )
    try:
        _ = rt.put(np.ones(500_000, dtype=np.float64))
        from ray_tpu.train import telemetry
        from ray_tpu.util import metrics as um
        from ray_tpu.util.state import memory_summary

        telemetry.report_step(1, rank=0, step_ms=100.0, wall_ms=110.0)
        um.flush()
        mem = memory_summary()
        assert mem.get("disabled") is True
        assert mem["jobs"] == {}
        assert mem["totals"]["arena_used"] == 0
        ms = um.metrics_summary()
        assert "rt_job_object_bytes" not in ms
        assert "rt_job_chip_seconds_total" not in ms
    finally:
        rt.shutdown()


def test_actor_put_attributed_to_actor_owner(ledger_session):
    rt = ledger_session

    @rt.remote
    class Producer:
        def make(self):
            self.ref = rt.put(np.ones(500_000, dtype=np.float64))
            return self.ref

    producer = Producer.remote()
    ref = rt.get(producer.make.remote(), timeout=60)
    from ray_tpu.util.state import memory_summary

    deadline = time.time() + 15
    actor_rows = []
    while time.time() < deadline and not actor_rows:
        actor_rows = [
            r
            for r in memory_summary()["owners"]
            if r["owner"].startswith("actor:")
        ]
        time.sleep(0.2)
    assert actor_rows, "actor-owned bytes never attributed"
    assert actor_rows[0]["bytes"] >= 4_000_000
    del ref


def test_killed_actor_owner_becomes_leak_suspect(ledger_session):
    """The CI leak scenario: an actor creates and holds a large
    object, the actor's worker is killed, the object stays held
    (driver ref + primary pin) — doctor names it under
    `verdict.memory` once it outlives the leak deadline, and the
    healthy 300s default stays quiet."""
    rt = ledger_session

    @rt.remote
    class Holder:
        def hold(self):
            self.ref = rt.put(np.ones(500_000, dtype=np.float64))
            return self.ref

    holder = Holder.remote()
    ref = rt.get(holder.hold.remote(), timeout=60)
    rt.kill(holder, no_restart=True)
    deadline = time.time() + 30
    leaks = []
    while time.time() < deadline and not leaks:
        time.sleep(0.4)
        verdict = rt.diagnose(capture_stacks=False, leak_age_s=0.5)
        leaks = [
            p
            for p in verdict["problems"]
            if p["kind"] == "object_leak"
        ]
    assert leaks, "killed pinning owner never flagged"
    assert leaks[0]["object_id"] == ref.hex()
    assert leaks[0]["owner"].startswith("actor:")
    assert verdict["memory"]["leak_suspects"]
    # Default deadline (300s): same cluster is healthy.
    assert rt.diagnose(capture_stacks=False)["healthy"] is True


def test_list_objects_size_descending_with_ledger_columns(
    ledger_session,
):
    rt = ledger_session
    small = rt.put(np.ones(200_000, dtype=np.float64))  # 1.6 MB
    big = rt.put(np.ones(800_000, dtype=np.float64))  # 6.4 MB
    from ray_tpu.util.state import list_objects

    rows = list_objects()
    sizes = [r["size"] for r in rows]
    assert sizes == sorted(sizes, reverse=True)
    top = rows[0]
    assert top["object_id"] == big.hex()
    # The ledger columns ride every row.
    for column in ("job", "owner", "age_s", "spilled", "pinned"):
        assert column in top, top
    assert top["owner"] == "driver"
    assert top["pinned"] is True
    # `limit` keeps the LARGEST rows, not an arbitrary dict slice.
    assert list_objects(limit=1)[0]["object_id"] == big.hex()
    del small, big


# ---------------------------------------------------------------------------
# CI smoke: 2-node cluster, CLI surfaces, exit-code contract
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_memory_cli_smoke_two_nodes(tmp_path):
    """Satellite CI smoke: a 2-node cluster where one job holds
    pinned objects. `ray_tpu memory --json` (a separate process, as
    an operator runs it) attributes ≥95% of arena-used bytes to the
    job and exits 0; the Prometheus scrape renders `rt_job_*` /
    `rt_object_owner_*`; a synthetic leak (killed pinning worker)
    flips `doctor --json` to exit 1 naming the object."""
    from ray_tpu.cluster_utils import Cluster

    import ray_tpu as rt

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)

    c = Cluster(
        initialize_head=True,
        head_resources={"CPU": 2.0},
        system_config={"memory_report_interval_s": 0.2},
    )
    c.add_node(num_cpus=2, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:
        from ray_tpu._private.worker import global_worker

        job_hex = global_worker().job_id.hex()

        @rt.remote(resources={"remote_node": 1.0})
        def produce():
            return np.ones(500_000, dtype=np.float64)

        local_refs = [
            rt.put(np.ones(500_000, dtype=np.float64))
            for _ in range(2)
        ]
        remote_ref = produce.remote()
        _ = rt.get(remote_ref, timeout=90)
        time.sleep(1.0)  # ≥1 report tick from both nodes

        out = subprocess.run(
            [
                sys.executable, "-m", "ray_tpu", "memory", "--json",
                "--address", c.address,
            ],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        mem = json.loads(out.stdout)
        assert mem["totals"]["attribution_fraction"] >= 0.95, mem[
            "totals"
        ]
        assert mem["jobs"][job_hex]["object_bytes"] >= 8_000_000
        assert len(mem["nodes"]) == 2
        # The producing task's bytes attribute to a task owner on
        # the remote node.
        assert any(
            r["owner"].startswith("task:")
            for r in mem["owners"]
            if r["job"] == job_hex
        ), mem["owners"]

        scrape = subprocess.run(
            [
                sys.executable, "-m", "ray_tpu", "metrics", "scrape",
                "--address", c.address,
            ],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert scrape.returncode == 0, scrape.stdout + scrape.stderr
        assert f'rt_job_object_bytes{{job="{job_hex}"}}' in scrape.stdout
        assert "rt_object_owner_bytes{" in scrape.stdout

        # Synthetic leak: kill the actor worker holding an object.
        @rt.remote
        class Holder:
            def hold(self):
                self.ref = rt.put(
                    np.ones(500_000, dtype=np.float64)
                )
                return self.ref

        holder = Holder.remote()
        leak_ref = rt.get(holder.hold.remote(), timeout=60)
        rt.kill(holder, no_restart=True)
        deadline = time.time() + 60
        doctor = None
        while time.time() < deadline:
            time.sleep(1.0)
            doctor = subprocess.run(
                [
                    sys.executable, "-m", "ray_tpu", "doctor",
                    "--json", "--address", c.address,
                    "--leak-age-s", "0.5", "--no-stacks",
                ],
                env=env, capture_output=True, text=True, timeout=120,
            )
            if doctor.returncode == 1:
                verdict = json.loads(doctor.stdout)
                leaks = [
                    p
                    for p in verdict["problems"]
                    if p["kind"] == "object_leak"
                ]
                if leaks:
                    break
        assert doctor is not None and doctor.returncode == 1, (
            doctor.stdout + doctor.stderr if doctor else "no run"
        )
        assert [p["object_id"] for p in leaks] == [leak_ref.hex()]
        assert verdict["memory"]["leak_suspects"]
        del local_refs, remote_ref
    finally:
        rt.shutdown()
        c.shutdown()
