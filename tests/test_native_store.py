"""Native arena store tests (reference test model:
src/ray/object_manager/plasma tests — create/seal/get lifecycle,
eviction, cross-process visibility, allocator reuse)."""

import multiprocessing
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    __import__("ray_tpu._native", fromlist=["load_library"]).load_library()
    is None,
    reason="native store toolchain unavailable",
)


@pytest.fixture
def arena(tmp_path):
    from ray_tpu._native import NativeArena

    path = str(tmp_path / "arena")
    store = NativeArena(path, capacity=1 << 20, num_slots=1024)
    yield store
    store.close(unlink=True)


def _oid(i: int) -> bytes:
    return i.to_bytes(4, "big") + b"\0" * 16


def test_create_seal_get_roundtrip(arena):
    payload = os.urandom(1000)
    buf, evicted = arena.create(_oid(1), len(payload))
    assert evicted == []
    assert arena.get(_oid(1)) is None  # unsealed: invisible
    buf[:] = payload
    arena.seal(_oid(1))
    view = arena.get(_oid(1))
    assert view is not None and bytes(view) == payload
    stats = arena.stats()
    assert stats["num_objects"] == 1
    assert stats["used"] >= 1000


def test_duplicate_create_rejected(arena):
    arena.create(_oid(2), 10)
    with pytest.raises(ValueError):
        arena.create(_oid(2), 10)


def test_delete_frees_and_allocator_reuses(arena):
    for i in range(10):
        buf, _ = arena.create(_oid(10 + i), 50_000)
        buf[:4] = b"abcd"
        arena.seal(_oid(10 + i))
    used_before = arena.stats()["used"]
    for i in range(10):
        assert arena.delete(_oid(10 + i))
    assert arena.stats()["used"] == 0
    # Freed ranges coalesce: a single allocation of nearly the whole
    # arena must now succeed.
    big, _ = arena.create(_oid(99), (1 << 20) - 4096)
    assert len(big) == (1 << 20) - 4096
    assert used_before > 0


def test_lru_eviction_returns_victims(arena):
    # Fill with 4 sealed objects of ~quarter capacity each.
    quarter = (1 << 18) - 1024
    for i in range(4):
        buf, _ = arena.create(_oid(100 + i), quarter)
        arena.seal(_oid(100 + i))
    # Touch object 0 so object 1 is LRU.
    assert arena.get(_oid(100)) is not None
    buf, evicted = arena.create(_oid(200), quarter)
    assert evicted, "expected eviction"
    assert evicted[0] == _oid(101)
    assert arena.get(_oid(101)) is None


def test_pinned_objects_survive_eviction(arena):
    quarter = (1 << 18) - 1024
    indices = {}
    for i in range(4):
        buf, _ = arena.create(_oid(300 + i), quarter)
        arena.seal(_oid(300 + i))
        pinned = arena.try_pin(_oid(300 + i))
        assert pinned is not None
        indices[i] = pinned[0]
    with pytest.raises(MemoryError):
        arena.create(_oid(400), quarter)
    arena.unpin_idx(indices[0])
    _, evicted = arena.create(_oid(400), quarter)
    assert evicted == [_oid(300)]


def _child_reads(path, oid, expected, q):
    from ray_tpu._native import NativeArena

    store = NativeArena(path, capacity=1 << 20, num_slots=1024,
                        create=False)
    try:
        view = store.get(oid)
        q.put(bytes(view) == expected if view is not None else False)
    finally:
        store.close()


def test_cross_process_visibility(tmp_path):
    from ray_tpu._native import NativeArena

    path = str(tmp_path / "arena2")
    store = NativeArena(path, capacity=1 << 20, num_slots=1024)
    try:
        payload = os.urandom(4096)
        buf, _ = store.create(_oid(7), len(payload))
        buf[:] = payload
        store.seal(_oid(7))
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        child = ctx.Process(
            target=_child_reads, args=(path, _oid(7), payload, q)
        )
        child.start()
        assert q.get(timeout=60) is True
        child.join(timeout=30)
    finally:
        store.close(unlink=True)


def test_session_runs_on_native_store():
    """Full runtime session with the arena as the object store:
    puts/gets/tasks/actors flow through native code."""
    import numpy as np

    import ray_tpu as rt

    rt.init(
        num_cpus=3,
        _system_config={"use_native_object_store": True},
    )
    try:
        big = np.arange(500_000, dtype=np.float64)  # > inline cutoff
        ref = rt.put(big)
        back = rt.get(ref, timeout=30)
        np.testing.assert_array_equal(back, big)

        @rt.remote
        def produce(n):
            return np.ones(n, dtype=np.float32) * 7

        arr = rt.get(produce.remote(400_000), timeout=60)
        assert arr.shape == (400_000,)
        assert float(arr[123]) == 7.0

        @rt.remote
        class Holder:
            def __init__(self):
                self.data = None

            def store(self, x):
                self.data = x
                return x.nbytes

            def fetch(self):
                return self.data

        holder = Holder.remote()
        nbytes = rt.get(holder.store.remote(big), timeout=60)
        assert nbytes == big.nbytes
        np.testing.assert_array_equal(
            rt.get(holder.fetch.remote(), timeout=60), big
        )
    finally:
        rt.shutdown()


def test_numpy_zero_copy_alignment(arena):
    arr = np.arange(1024, dtype=np.float64)
    raw = arr.tobytes()
    buf, _ = arena.create(_oid(8), len(raw))
    buf[:] = raw
    arena.seal(_oid(8))
    view = arena.get(_oid(8))
    # 64-byte aligned payloads reinterpret in place.
    back = np.frombuffer(view, dtype=np.float64)
    np.testing.assert_array_equal(back, arr)


def test_delete_deferred_while_pinned(arena):
    """delete() with live reader pins must not free the range (the
    reader's zero-copy view would be silently overwritten); the free
    happens at the last unpin, and the object is invisible meanwhile."""
    payload = os.urandom(4096)
    buf, _ = arena.create(_oid(9), len(payload))
    buf[:] = payload
    arena.seal(_oid(9))
    pinned = arena.try_pin(_oid(9))
    assert pinned is not None
    pin_idx, view = pinned
    objs_before = arena.stats()["num_objects"]
    arena.delete(_oid(9))
    # Doomed: invisible to new readers, not yet freed.
    assert arena.get(_oid(9)) is None
    assert arena.try_pin(_oid(9)) is None
    assert bytes(view) == payload  # old view still intact
    # A new allocation must not reuse the pinned range.
    buf2, _ = arena.create(_oid(10), 4096)
    buf2[:] = b"\xaa" * 4096
    arena.seal(_oid(10))
    assert bytes(view) == payload
    # The doomed slot must not block re-creating the same oid (lineage
    # reconstruction re-puts deleted objects).
    buf3, _ = arena.create(_oid(9), 128)
    buf3[:] = b"\xcc" * 128
    arena.seal(_oid(9))
    assert bytes(arena.get(_oid(9))) == b"\xcc" * 128
    assert bytes(view) == payload  # still the old bytes
    arena.delete(_oid(9))
    view.release()
    arena.unpin_idx(pin_idx)  # last pin drops -> doomed slot freed
    assert arena.stats()["num_objects"] <= objs_before


def test_get_pins_against_eviction(tmp_path):
    """get() returns a pinned view: creates that would evict the object
    pick another victim (or fail) while the view is held."""
    from ray_tpu._native import NativeArena

    store = NativeArena(str(tmp_path / "a2"), capacity=1 << 16,
                        num_slots=64)
    try:
        first = os.urandom(1 << 14)
        buf, _ = store.create(_oid(20), len(first))
        buf[:] = first
        store.seal(_oid(20))
        pinned = store.try_pin(_oid(20))
        assert pinned is not None
        pin_idx, view = pinned
        # Fill the arena: evictions must skip the pinned object.
        for i in range(21, 40):
            try:
                b, _ = store.create(_oid(i), 1 << 13)
            except MemoryError:
                break
            b[:] = b"\xbb" * (1 << 13)
            store.seal(_oid(i))
        assert bytes(view) == first
        view.release()
        store.unpin_idx(pin_idx)
    finally:
        store.close(unlink=True)


def _pin_and_die(p, oid):
    from ray_tpu._native import NativeArena as NA

    s = NA(p, capacity=1 << 20, num_slots=256, create=False)
    s.try_pin(oid)
    os.kill(os.getpid(), 9)  # die without unpinning


def test_dead_process_pins_reaped(tmp_path):
    """Pins held by a SIGKILLed reader are reclaimed by
    reap_dead_pins so the slot becomes evictable/deletable again."""
    from ray_tpu._native import NativeArena

    path = str(tmp_path / "a3")
    store = NativeArena(path, capacity=1 << 20, num_slots=256)
    try:
        buf, _ = store.create(_oid(50), 1024)
        buf[:] = b"\xdd" * 1024
        store.seal(_oid(50))

        proc = multiprocessing.get_context("spawn").Process(
            target=_pin_and_die, args=(path, _oid(50))
        )
        proc.start()
        proc.join(timeout=30)
        # Object is pinned by a dead pid: delete defers to kDoomed.
        store.delete(_oid(50))
        assert store.get(_oid(50)) is None
        before = store.stats()["num_objects"]
        assert store.reap_dead_pins() >= 1
        assert store.stats()["num_objects"] == before - 1
    finally:
        store.close(unlink=True)


def test_zero_copy_value_keeps_pin_until_buffers_die(tmp_path):
    """End-to-end: a numpy array fetched zero-copy from the native
    store stays valid even when the store deletes the object and new
    objects are created — the reader pin follows the buffer."""
    import gc

    import ray_tpu as rt

    rt.init(
        num_cpus=2,
        _system_config={
            "use_native_object_store": True,
            # Small store so reuse-after-free would be observable.
            "object_store_memory": 8 * 1024 * 1024,
        },
    )
    try:
        src = np.arange(250_000, dtype=np.float64)  # ~2MB, > inline
        ref = rt.put(src)
        arr = rt.get(ref, timeout=30)
        np.testing.assert_array_equal(arr, src)
        del ref  # refcount zero -> daemon deletes the object
        # Churn the store: without the pin these creates could reuse
        # the freed range and corrupt `arr`.
        for i in range(6):
            rt.get(rt.put(np.full(250_000, i, dtype=np.float64)),
                   timeout=30)
        gc.collect()
        np.testing.assert_array_equal(arr, src)
    finally:
        rt.shutdown()
