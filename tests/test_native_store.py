"""Native arena store tests (reference test model:
src/ray/object_manager/plasma tests — create/seal/get lifecycle,
eviction, cross-process visibility, allocator reuse)."""

import multiprocessing
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    __import__("ray_tpu._native", fromlist=["load_library"]).load_library()
    is None,
    reason="native store toolchain unavailable",
)


@pytest.fixture
def arena(tmp_path):
    from ray_tpu._native import NativeArena

    path = str(tmp_path / "arena")
    store = NativeArena(path, capacity=1 << 20, num_slots=1024)
    yield store
    store.close(unlink=True)


def _oid(i: int) -> bytes:
    return i.to_bytes(4, "big") + b"\0" * 16


def test_create_seal_get_roundtrip(arena):
    payload = os.urandom(1000)
    buf, evicted = arena.create(_oid(1), len(payload))
    assert evicted == []
    assert arena.get(_oid(1)) is None  # unsealed: invisible
    buf[:] = payload
    arena.seal(_oid(1))
    view = arena.get(_oid(1))
    assert view is not None and bytes(view) == payload
    stats = arena.stats()
    assert stats["num_objects"] == 1
    assert stats["used"] >= 1000


def test_duplicate_create_rejected(arena):
    arena.create(_oid(2), 10)
    with pytest.raises(ValueError):
        arena.create(_oid(2), 10)


def test_delete_frees_and_allocator_reuses(arena):
    for i in range(10):
        buf, _ = arena.create(_oid(10 + i), 50_000)
        buf[:4] = b"abcd"
        arena.seal(_oid(10 + i))
    used_before = arena.stats()["used"]
    for i in range(10):
        assert arena.delete(_oid(10 + i))
    assert arena.stats()["used"] == 0
    # Freed ranges coalesce: a single allocation of nearly the whole
    # arena must now succeed.
    big, _ = arena.create(_oid(99), (1 << 20) - 4096)
    assert len(big) == (1 << 20) - 4096
    assert used_before > 0


def test_lru_eviction_returns_victims(arena):
    # Fill with 4 sealed objects of ~quarter capacity each.
    quarter = (1 << 18) - 1024
    for i in range(4):
        buf, _ = arena.create(_oid(100 + i), quarter)
        arena.seal(_oid(100 + i))
    # Touch object 0 so object 1 is LRU.
    assert arena.get(_oid(100)) is not None
    buf, evicted = arena.create(_oid(200), quarter)
    assert evicted, "expected eviction"
    assert evicted[0] == _oid(101)
    assert arena.get(_oid(101)) is None


def test_pinned_objects_survive_eviction(arena):
    quarter = (1 << 18) - 1024
    for i in range(4):
        buf, _ = arena.create(_oid(300 + i), quarter)
        arena.seal(_oid(300 + i))
        arena.pin(_oid(300 + i))
    with pytest.raises(MemoryError):
        arena.create(_oid(400), quarter)
    arena.unpin(_oid(300))
    _, evicted = arena.create(_oid(400), quarter)
    assert evicted == [_oid(300)]


def _child_reads(path, oid, expected, q):
    from ray_tpu._native import NativeArena

    store = NativeArena(path, capacity=1 << 20, num_slots=1024,
                        create=False)
    try:
        view = store.get(oid)
        q.put(bytes(view) == expected if view is not None else False)
    finally:
        store.close()


def test_cross_process_visibility(tmp_path):
    from ray_tpu._native import NativeArena

    path = str(tmp_path / "arena2")
    store = NativeArena(path, capacity=1 << 20, num_slots=1024)
    try:
        payload = os.urandom(4096)
        buf, _ = store.create(_oid(7), len(payload))
        buf[:] = payload
        store.seal(_oid(7))
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        child = ctx.Process(
            target=_child_reads, args=(path, _oid(7), payload, q)
        )
        child.start()
        assert q.get(timeout=60) is True
        child.join(timeout=30)
    finally:
        store.close(unlink=True)


def test_session_runs_on_native_store():
    """Full runtime session with the arena as the object store:
    puts/gets/tasks/actors flow through native code."""
    import numpy as np

    import ray_tpu as rt

    rt.init(
        num_cpus=3,
        _system_config={"use_native_object_store": True},
    )
    try:
        big = np.arange(500_000, dtype=np.float64)  # > inline cutoff
        ref = rt.put(big)
        back = rt.get(ref, timeout=30)
        np.testing.assert_array_equal(back, big)

        @rt.remote
        def produce(n):
            return np.ones(n, dtype=np.float32) * 7

        arr = rt.get(produce.remote(400_000), timeout=60)
        assert arr.shape == (400_000,)
        assert float(arr[123]) == 7.0

        @rt.remote
        class Holder:
            def __init__(self):
                self.data = None

            def store(self, x):
                self.data = x
                return x.nbytes

            def fetch(self):
                return self.data

        holder = Holder.remote()
        nbytes = rt.get(holder.store.remote(big), timeout=60)
        assert nbytes == big.nbytes
        np.testing.assert_array_equal(
            rt.get(holder.fetch.remote(), timeout=60), big
        )
    finally:
        rt.shutdown()


def test_numpy_zero_copy_alignment(arena):
    arr = np.arange(1024, dtype=np.float64)
    raw = arr.tobytes()
    buf, _ = arena.create(_oid(8), len(raw))
    buf[:] = raw
    arena.seal(_oid(8))
    view = arena.get(_oid(8))
    # 64-byte aligned payloads reinterpret in place.
    back = np.frombuffer(view, dtype=np.float64)
    np.testing.assert_array_equal(back, arr)
