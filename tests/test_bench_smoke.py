"""CI gate for the bench harness itself: `bench.py --smoke` must run
the whole bench surface (train step, fixed-cost attribution, async-
checkpoint overhead) in seconds on CPU and emit one well-formed JSON
line — so a broken bench is caught by the test suite, not discovered
at measurement time."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# slow: ~90s of jit compiles on a loaded CPU box — the smoke gate
# belongs in the slow tier, not displacing tier-1 wall-clock.
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_bench_smoke_emits_composite_json():
    # Drop the suite's forced 8-host-device XLA_FLAGS: the smoke gate
    # mirrors `python bench.py --smoke` as a user runs it (1 CPU
    # device), and CPU SPMD across forced devices is pathologically
    # slow.
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # Keep the checkpoint-overhead phase short: this test checks the
    # bench RUNS and emits the right shape, not the numbers.
    env.setdefault("RT_BENCH_SMOKE_CKPT_STEPS", "6")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "bench.py"),
            "--smoke",
            "--skip-micro",
        ],
        capture_output=True,
        text=True,
        timeout=390,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [
        ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
    ][-1]
    out = json.loads(line)

    assert out["smoke"] is True
    assert out["vs_baseline"] == 0.0  # smoke numbers never count
    assert out["train"]["cpu_fallback"] is True

    breakdown = out["fixed_ms_breakdown"]
    for key in (
        "fixed_step_ms_0l",
        "optimizer_ms",
        "embed_lm_head_ms",
        "dispatch_ms",
        "host_sync_ms",
        "input_stall_ms",
    ):
        assert isinstance(breakdown[key], (int, float)), key
        assert breakdown[key] >= 0, (key, breakdown[key])

    ckpt = out["ckpt_overhead"]
    assert ckpt["every"] == 10
    assert ckpt["base_wall_s"] > 0
    assert ckpt["ckpt_wall_s"] > 0
    assert isinstance(ckpt["ckpt_overhead_pct"], (int, float))
