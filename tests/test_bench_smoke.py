"""CI gate for the bench harness itself: `bench.py --smoke` must run
the whole bench surface (train step, fixed-cost attribution, async-
checkpoint overhead) in seconds on CPU and emit one well-formed JSON
line — so a broken bench is caught by the test suite, not discovered
at measurement time."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# slow: ~90s of jit compiles on a loaded CPU box — the smoke gate
# belongs in the slow tier, not displacing tier-1 wall-clock.
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_bench_smoke_emits_composite_json():
    # Drop the suite's forced 8-host-device XLA_FLAGS: the smoke gate
    # mirrors `python bench.py --smoke` as a user runs it (1 CPU
    # device), and CPU SPMD across forced devices is pathologically
    # slow.
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # Keep the checkpoint-overhead phase short: this test checks the
    # bench RUNS and emits the right shape, not the numbers.
    env.setdefault("RT_BENCH_SMOKE_CKPT_STEPS", "6")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "bench.py"),
            "--smoke",
            "--skip-micro",
        ],
        capture_output=True,
        text=True,
        timeout=390,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [
        ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
    ][-1]
    out = json.loads(line)

    assert out["smoke"] is True
    assert out["vs_baseline"] == 0.0  # smoke numbers never count
    assert out["train"]["cpu_fallback"] is True

    breakdown = out["fixed_ms_breakdown"]
    for key in (
        "fixed_step_ms_0l",
        "optimizer_ms",
        "embed_lm_head_ms",
        "dispatch_ms",
        "host_sync_ms",
        "input_stall_ms",
    ):
        assert isinstance(breakdown[key], (int, float)), key
        assert breakdown[key] >= 0, (key, breakdown[key])

    ckpt = out["ckpt_overhead"]
    assert ckpt["every"] == 10
    assert ckpt["base_wall_s"] > 0
    assert ckpt["ckpt_wall_s"] > 0
    assert isinstance(ckpt["ckpt_overhead_pct"], (int, float))


# slow: two pipeline builds + the single-program baseline compiles.
@pytest.mark.slow
@pytest.mark.timeout(600)
def test_bench_pipeline_smoke_efficiency_and_parity():
    """`bench.py --mode pipeline --smoke` must run the MPMD 1F1B
    bench end to end on CPU (2 stages x tiny model): efficiency /
    bubble fields render, per-stage send/recv wait is visible, and
    the MPMD loss matches the single-program GPipe baseline at
    identical geometry."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "bench.py"),
            "--mode",
            "pipeline",
            "--smoke",
        ],
        capture_output=True,
        text=True,
        timeout=570,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [
        ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
    ][-1]
    out = json.loads(line)

    assert out["smoke"] is True
    assert out["metric"] == "mpmd_pipeline_tokens_per_s"
    assert out["points"], "no pipeline points measured"
    for point in out["points"]:
        # Efficiency/bubble fields render and are sane.
        assert 0.0 < point["pipeline_efficiency"] <= 1.2
        assert 0.0 < point["theoretical_bound"] <= 1.0
        assert point["bound_ratio"] > 0
        assert point["tokens_per_s"] > 0
        # 1F1B invariant visible in telemetry.
        assert all(
            s["stash_peak"] <= point["stash_bound"]
            for s in point["stages"]
        )
        # Per-stage send/recv wait breakdown present.
        for stage in point["stages"]:
            assert "send_wait_ms" in stage
            assert "recv_wait_ms" in stage
        # Loss parity with the single-program GPipe baseline.
        assert point["loss_matches_baseline"] is True
    # The baseline comparison renders at every compared geometry.
    # (Which side wins at SMOKE scale is box-dependent: on one CPU
    # core the fused program's lower per-op dispatch usually beats
    # MPMD's per-op overhead at tiny compute — the committed
    # PIPEBENCH.json `large` point is where the structural win
    # shows. Parity above is the correctness gate.)
    assert all(
        p["vs_single_program"] > 0
        for p in out["points"]
        if "vs_single_program" in p
    )
