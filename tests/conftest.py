"""Shared fixtures.

JAX tests run hermetically on a virtual 8-device CPU mesh (the
reference's analogous trick is the multi-raylet-in-one-box Cluster
fixture + fake accelerator managers, SURVEY.md §4): sharding/pjit
code paths compile and run without TPU hardware.
"""

import os

# Must be set before jax is imported anywhere in the test process.
# Force CPU (the machine's env may point JAX at a TPU plugin): tests
# must run hermetically on a virtual 8-device CPU mesh.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# A site-installed TPU plugin may force platform selection via
# jax.config at interpreter start; override it back to CPU here, before
# any test imports jax.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import signal

import pytest

#: Hard per-test wall-clock cap (VERDICT r2 weak #8: a wedged session
#: must FAIL the test, not hang the suite; faulthandler_timeout only
#: dumps). SIGALRM raises in the main thread, which interrupts Python
#: code and most blocking socket/lock waits. Slow-marked tests get 4x.
_HARD_TIMEOUT = int(os.environ.get("RT_TEST_TIMEOUT", "120"))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    # Wraps setup+call+teardown: a hang in rt.init()/shutdown() inside
    # a fixture must fail too, not just hangs in the test body.
    if not hasattr(signal, "SIGALRM"):
        yield
        return
    timeout = _HARD_TIMEOUT * (4 if item.get_closest_marker("slow") else 1)
    marker = item.get_closest_marker("timeout")
    if marker and marker.args:
        timeout = int(marker.args[0])

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"{item.nodeid} exceeded the {timeout}s hard test timeout"
        )

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(timeout)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def rt_session():
    """A fresh single-node session per test (reference fixture:
    ray_start_regular, python/ray/tests/conftest.py:463)."""
    import ray_tpu as rt

    session = rt.init(num_cpus=4, ignore_reinit_error=False)
    yield rt
    # Workers crashing BEFORE registering are never a legitimate test
    # outcome (tests that kill workers kill REGISTERED ones): a
    # nonzero startup-failure count is the crash-loop-under-load bug
    # class (VERDICT r4 weak #7) and must fail the test that hit it,
    # with a pointer at the worker logs carrying the traceback.
    try:
        daemon = rt.api._session.daemon
        failures = daemon._spawn_crash_total
        session_dir = daemon.session_dir
    except Exception:
        failures, session_dir = 0, "?"
    rt.shutdown()
    assert failures == 0, (
        f"{failures} worker(s) crashed at startup during this test — "
        f"see {session_dir}/worker-*.out"
    )


@pytest.fixture(scope="module")
def rt_shared():
    """Module-scoped session for cheap read-only tests (reference:
    ray_start_regular_shared)."""
    import ray_tpu as rt

    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    rt.shutdown()
