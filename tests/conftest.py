"""Shared fixtures.

JAX tests run hermetically on a virtual 8-device CPU mesh (the
reference's analogous trick is the multi-raylet-in-one-box Cluster
fixture + fake accelerator managers, SURVEY.md §4): sharding/pjit
code paths compile and run without TPU hardware.
"""

import os

# Must be set before jax is imported anywhere in the test process.
# Force CPU (the machine's env may point JAX at a TPU plugin): tests
# must run hermetically on a virtual 8-device CPU mesh.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# A site-installed TPU plugin may force platform selection via
# jax.config at interpreter start; override it back to CPU here, before
# any test imports jax.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest


@pytest.fixture
def rt_session():
    """A fresh single-node session per test (reference fixture:
    ray_start_regular, python/ray/tests/conftest.py:463)."""
    import ray_tpu as rt

    session = rt.init(num_cpus=4, ignore_reinit_error=False)
    yield rt
    rt.shutdown()


@pytest.fixture(scope="module")
def rt_shared():
    """Module-scoped session for cheap read-only tests (reference:
    ray_start_regular_shared)."""
    import ray_tpu as rt

    rt.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    rt.shutdown()
