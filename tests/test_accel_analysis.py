"""Accelerator hot-path analysis tests (`ray_tpu devtools accel`,
devtools/accel.py rules RT301-RT306) and the static<->runtime bridge
into the compile watch (`compile_watch.load_inventory`/`static_hint`).

Every rule has a seeded-bug fixture (must fire) and a corrected twin
(must stay quiet); the repo analyzes itself clean — package, tests AND
bench.py — so every jit wrap site is either registered with
`compile_watch.instrument` or carries an explicit, reviewed
`# rt: noqa[RT3xx]`. Also here: the noqa-hygiene contract shared by
all four passes (RT090/RT190/RT290/RT390 — a suppression naming a
nonexistent rule, or one that never fires on its line, is itself a
finding), regression tests for the convictions this pass produced
(generate/rl/train registration, the engine mixed-generation host-sync
fix), the program-inventory JSON shape, and the doctor correlation: a
live recompile storm's problem record carries a `static_hint` naming
the static RT302 site.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu.devtools.accel import (
    RULES,
    accel_paths,
    accel_sources,
    build_inventory,
    build_inventory_sources,
    main as accel_main,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")
TESTS = os.path.dirname(os.path.abspath(__file__))
BENCH = os.path.join(REPO, "bench.py")


def fired(source: str, path: str = "mod.py"):
    return {
        f.rule
        for f in accel_sources([(path, textwrap.dedent(source))])
    }


# ---------------------------------------------------------------------------
# one seeded-bug fixture + one corrected twin per rule
# ---------------------------------------------------------------------------

CASES = [
    (
        "RT301",
        # jit wrapper minted inside the loop: every iteration re-traces.
        """
        import jax

        def run_epoch(params, batches):
            out = []
            for batch in batches:
                step = jax.jit(lambda p, b: (p * b).sum())
                out.append(step(params, batch))
            return out
        """,
        True,
    ),
    (
        "RT301",
        # corrected twin: module-level wrap, loop reuses the cache.
        """
        import jax

        _step = jax.jit(lambda p, b: (p * b).sum())

        def run_epoch(params, batches):
            return [_step(params, batch) for batch in batches]
        """,
        False,
    ),
    (
        "RT302",
        # len() reaches a static position: one compile per batch size.
        """
        import jax

        _tail = jax.jit(lambda x, n: x[:n], static_argnums=(1,))

        def run(rows, batch):
            for x in rows:
                _tail(x, len(batch))
        """,
        True,
    ),
    (
        "RT302",
        # corrected twin: the bound is a hashable config constant.
        """
        import jax

        _tail = jax.jit(lambda x, n: x[:n], static_argnums=(1,))

        MAX_ROWS = 128

        def run(rows):
            for x in rows:
                _tail(x, MAX_ROWS)
        """,
        False,
    ),
    (
        "RT303",
        # float() on a device value inside the jit-stepped hot loop:
        # one blocking D2H round trip per iteration.
        """
        import jax

        _step = jax.jit(lambda x: (x * 2).sum())

        def train(batches):
            total = 0.0
            for batch in batches:
                loss = _step(batch)
                total += float(loss)
            return total
        """,
        True,
    ),
    (
        "RT303",
        # corrected twin: accumulate on device, sync once after.
        """
        import jax

        _step = jax.jit(lambda x: (x * 2).sum())

        def train(batches):
            total = None
            for batch in batches:
                loss = _step(batch)
                total = loss if total is None else total + loss
            return float(total)
        """,
        False,
    ),
    (
        "RT304",
        # state is donated to the update, then read again.
        """
        import jax

        _update = jax.jit(lambda s, g: s - g, donate_argnums=(0,))

        def apply(state, grads):
            new_state = _update(state, grads)
            drift = new_state - state
            return new_state, drift
        """,
        True,
    ),
    (
        "RT304",
        # corrected twin: the donated name is rebound, never re-read.
        """
        import jax

        _update = jax.jit(lambda s, g: s - g, donate_argnums=(0,))

        def apply(state, grads):
            state = _update(state, grads)
            return state
        """,
        False,
    ),
    (
        "RT305",
        # clock read right after an async dispatch: measures dispatch,
        # not the computation.
        """
        import time
        import jax

        _step = jax.jit(lambda x: (x * 2).sum())

        def bench(batch):
            t0 = time.perf_counter()
            out = _step(batch)
            elapsed = time.perf_counter() - t0
            return elapsed, out
        """,
        True,
    ),
    (
        "RT305",
        # corrected twin: block_until_ready fences before the clock.
        """
        import time
        import jax

        _step = jax.jit(lambda x: (x * 2).sum())

        def bench(batch):
            t0 = time.perf_counter()
            out = _step(batch)
            jax.block_until_ready(out)
            elapsed = time.perf_counter() - t0
            return elapsed, out
        """,
        False,
    ),
    (
        "RT306",
        # jit invisible to the compile watch: its compiles land in the
        # "(unregistered)" ledger where no storm can be attributed.
        """
        import jax

        _step = jax.jit(lambda x: x + 1)
        """,
        True,
    ),
    (
        "RT306",
        # corrected twin: registered by name.
        """
        import jax

        from ray_tpu._private import compile_watch

        _step = compile_watch.instrument(
            "mod.step", jax.jit(lambda x: x + 1)
        )
        """,
        False,
    ),
]


@pytest.mark.parametrize(
    "rule,source,expect",
    CASES,
    ids=[
        f"{rule}-{'seeded' if expect else 'corrected'}"
        for rule, _, expect in CASES
    ],
)
def test_rule_fixtures(rule, source, expect):
    rules = fired(source)
    if expect:
        assert rule in rules, f"{rule} did not fire:\n{source}"
    else:
        assert rule not in rules, f"{rule} fired on the corrected twin"


def test_test_files_exempt_from_hot_path_rules():
    """RT303/RT305/RT306 are about production hot loops; test files
    sync and time deliberately, so only the universal rules
    (RT301/RT302/RT304) apply there."""
    sync_in_loop = """
        import jax

        _step = jax.jit(lambda x: (x * 2).sum())

        def train(batches):
            total = 0.0
            for batch in batches:
                total += float(_step(batch))
            return total
    """
    assert "RT303" in fired(sync_in_loop, path="pkg/mod.py")
    assert fired(sync_in_loop, path="tests/test_mod.py") == set()
    # ...but a donation bug in a test is still a bug.
    donate = """
        import jax

        _up = jax.jit(lambda s: s * 2, donate_argnums=(0,))

        def helper(state):
            out = _up(state)
            return out + state
    """
    assert "RT304" in fired(donate, path="tests/test_mod.py")


# ---------------------------------------------------------------------------
# shared suppression contract + noqa hygiene (all four passes)
# ---------------------------------------------------------------------------

SEEDED_306 = """
    import jax

    _step = jax.jit(lambda x: x + 1)
"""


def test_noqa_suppresses_on_the_flagged_line():
    src = textwrap.dedent(
        """
        import jax

        _step = jax.jit(lambda x: x + 1)  # rt: noqa[RT306] — probe
        """
    )
    assert "RT306" not in {
        f.rule for f in accel_sources([("mod.py", src)])
    }


def test_noqa_must_name_the_rule():
    src = textwrap.dedent(
        """
        import jax

        _step = jax.jit(lambda x: x + 1)  # rt: noqa[RT301]
        """
    )
    rules = {f.rule for f in accel_sources([("mod.py", src)])}
    # The finding survives a suppression naming a different rule...
    assert "RT306" in rules
    # ...and the useless suppression is itself reported (RT301 never
    # fires on that line).
    assert "RT390" in rules


def test_bare_noqa_suppresses_everything_quietly():
    src = textwrap.dedent(
        """
        import jax

        _step = jax.jit(lambda x: x + 1)  # rt: noqa
        """
    )
    assert {f.rule for f in accel_sources([("mod.py", src)])} == set()


def test_hygiene_catches_unknown_rule_id():
    src = textwrap.dedent(
        """
        import jax

        _step = jax.jit(lambda x: x + 1)  # rt: noqa[RT306,RT399]
        """
    )
    findings = accel_sources([("mod.py", src)])
    assert {f.rule for f in findings} == {"RT390"}
    assert any("RT399" in f.message for f in findings)


def test_hygiene_is_not_suppressible():
    src = textwrap.dedent(
        """
        import jax

        _step = jax.jit(lambda x: x + 1)  # rt: noqa[RT301,RT390]
        """
    )
    rules = {f.rule for f in accel_sources([("mod.py", src)])}
    assert "RT390" in rules


def test_hygiene_ignores_string_literals():
    """Only real comments are audited — analysis-test fixtures hold
    noqa text in string literals and must not trip the hygiene."""
    src = '''
SRC = """
x = 1  # rt: noqa[RT301]
"""
'''
    assert {f.rule for f in accel_sources([("mod.py", src)])} == set()


def test_hygiene_in_sibling_passes():
    """Satellite: the same audit runs in lint (RT090), check (RT190)
    and race (RT290) — one contract across all four passes."""
    from ray_tpu.devtools.check import check_sources
    from ray_tpu.devtools.concurrency import race_sources
    from ray_tpu.devtools.lint import lint_source

    stale = "x = 1  # rt: noqa[RT004]\n"
    assert "RT090" in {f.rule for f in lint_source(stale, "mod.py")}
    stale_check = "x = 1  # rt: noqa[RT102]\n"
    assert "RT190" in {
        f.rule for f in check_sources([("mod.py", stale_check)])
    }
    stale_race = "x = 1  # rt: noqa[RT203]\n"
    assert "RT290" in {
        f.rule for f in race_sources([("mod.py", stale_race)])
    }
    # Cross-family ownership: a stale RT2xx suppression is the race
    # pass's to report, not lint's or accel's.
    assert "RT090" not in {
        f.rule for f in lint_source(stale_race, "mod.py")
    }
    assert "RT390" not in {
        f.rule for f in accel_sources([("mod.py", stale_race)])
    }


# ---------------------------------------------------------------------------
# CLI contract: exit codes, --json, --rules, --list-rules, --inventory
# ---------------------------------------------------------------------------


def test_main_exit_codes_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(SEEDED_306))
    assert accel_main([str(bad), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out[0]["rule"] == "RT306"
    assert out[0]["path"] == str(bad)

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert accel_main([str(clean)]) == 0
    assert accel_main([str(tmp_path / "missing.py")]) == 2
    assert accel_main([str(bad), "--rules", "RT999"]) == 2


def test_list_rules(capsys):
    assert accel_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULES:
        assert rule_id in out
    assert "RT390" in out


def test_rules_filter(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(SEEDED_306))
    assert accel_main([str(bad), "--rules", "RT301"]) == 0
    assert accel_main([str(bad), "--rules", "RT306"]) == 1


def test_parse_error_is_rt000(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings = accel_paths([str(bad)])
    assert [f.rule for f in findings] == ["RT000"]


# ---------------------------------------------------------------------------
# the program inventory (the doctor bridge's static half)
# ---------------------------------------------------------------------------


def test_inventory_shape_and_hazard_attachment():
    src = textwrap.dedent(
        """
        import jax

        from ray_tpu._private import compile_watch

        _tail = compile_watch.instrument(
            "mod.tail",
            jax.jit(lambda x, n: x[:n], static_argnums=(1,)),
        )
        _anon = jax.jit(lambda x: x + 1)

        def run(rows, batch):
            for x in rows:
                _tail(x, len(batch))
        """
    )
    inv = build_inventory_sources([("mod.py", src)])
    assert inv["version"] == 1
    by_name = {p["program"]: p for p in inv["programs"] if p["program"]}
    tail = by_name["mod.tail"]
    assert tail["registered"] is True
    assert tail["name_kind"] == "literal"
    assert tail["static_argnums"] == [1]
    assert tail["hazards"], "RT302 hazard missing from inventory"
    hazard = tail["hazards"][0]
    assert hazard["rule"] == "RT302"
    assert hazard["path"] == "mod.py"
    assert "len(" in hazard["message"]
    # The anonymous jit lands in the unregistered worklist.
    assert len(inv["unregistered"]) == 1


def test_cli_inventory_mode(tmp_path, capsys):
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent(SEEDED_306))
    assert accel_main([str(mod), "--inventory"]) == 0
    inv = json.loads(capsys.readouterr().out)
    assert inv["version"] == 1
    assert len(inv["programs"]) == 1


def test_static_hint_resolves_literal_and_pattern(tmp_path, monkeypatch):
    from ray_tpu._private import compile_watch as cw

    inv = {
        "version": 1,
        "programs": [
            {
                "program": "train.step",
                "name_kind": "literal",
                "path": "pkg/train.py",
                "line": 10,
                "registered": True,
                "hazards": [
                    {
                        "rule": "RT302",
                        "path": "pkg/loop.py",
                        "line": 44,
                        "message": "run: static argument 1 derives "
                        "from len(...)",
                    }
                ],
            },
            {
                "program": "engine.run[*]",
                "name_kind": "pattern",
                "path": "pkg/engine.py",
                "line": 77,
                "registered": True,
                "hazards": [],
            },
        ],
        "unregistered": [],
    }
    path = tmp_path / "inventory.json"
    path.write_text(json.dumps(inv))
    monkeypatch.setenv("RT_accel_inventory", str(path))
    try:
        cw.load_inventory(refresh=True)
        hint = cw.static_hint("train.step")
        assert "pkg/loop.py:44" in hint
        assert "RT302" in hint
        # f-string program names were inventoried as fnmatch patterns.
        hint2 = cw.static_hint("engine.run[gen3]")
        assert "pkg/engine.py:77" in hint2
        assert cw.static_hint("nope") is None
    finally:
        monkeypatch.delenv("RT_accel_inventory")
        cw.load_inventory(refresh=True)


def test_package_inventory_has_no_unregistered_programs():
    """Satellite: every jit wrap site in the shipped package is
    registered with compile_watch.instrument — the static proof that
    "(unregistered)" compile counts stay zero."""
    inv = build_inventory([PKG])
    assert inv["unregistered"] == []
    names = {p["program"] for p in inv["programs"] if p["program"]}
    # The convictions fixed in this PR, by name.
    for prog in (
        "generate.decode_step",
        "generate.prefill",
        "generate.paged_prefill",
        "generate.paged_decode_step",
        "generate.generate",
        "rl.sample_actions",
        "rl.dqn.td_update",
        "rl.ppo.minibatch_update",
        "rl.policy_program",
        "train.init_params",
        "train.pipeline.init_params",
    ):
        assert prog in names, f"{prog} missing from inventory"


# ---------------------------------------------------------------------------
# the repo holds itself to the rules
# ---------------------------------------------------------------------------


def test_repo_analyzes_clean():
    findings = accel_paths([PKG, TESTS, BENCH])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_devtools_all_includes_accel(tmp_path):
    from ray_tpu.devtools import all_main

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(SEEDED_306))
    out_path = tmp_path / "out.json"
    with open(out_path, "w") as fh:
        rc = all_main([str(bad), "--json"], out=fh)
    assert rc == 1
    rules = {f["rule"] for f in json.loads(out_path.read_text())}
    assert "RT306" in rules


# ---------------------------------------------------------------------------
# regression tests for the convictions this pass produced
# ---------------------------------------------------------------------------


def test_generate_wraps_registered_and_callable():
    """The five generate.py jits register by name and still work; the
    module-level `generate` rebind survives pickling by reference."""
    import pickle

    import jax.numpy as jnp
    import numpy as np

    from ray_tpu._private.compile_watch import WatchedFunction
    from ray_tpu.models import generate as g
    from ray_tpu.models.llama import LlamaConfig, init_params

    assert isinstance(g.generate, WatchedFunction)
    assert g.generate.name == "generate.generate"
    # Importable call sites pickle the NAME, not the wrapper.
    assert pickle.loads(pickle.dumps(g.decode_step)) is not None

    cfg = LlamaConfig.tiny()
    import jax

    params = init_params(jax.random.PRNGKey(0), cfg)
    prompts = jnp.asarray(np.full((2, 4), 3, np.int32))
    lengths = jnp.asarray(np.array([4, 4], np.int32))
    tokens, out_lengths = g.generate(
        params, prompts, lengths, cfg, max_new_tokens=3
    )
    assert tokens.shape == (2, 3)
    assert g.generate.stats()["compiles"] >= 1


def test_engine_mixed_generation_merge_stays_on_device():
    """The mixed-generation decode window used to np.asarray each
    group's tokens inside the loop (RT303); it now merges on device
    and syncs once. Static regression: the engine analyzes clean."""
    path = os.path.join(PKG, "llm", "engine.py")
    findings = [
        f
        for f in accel_paths([path])
        if f.rule == "RT303"
    ]
    assert findings == [], "\n".join(f.render() for f in findings)


def test_rl_and_train_programs_compile_under_their_names():
    """Run a registered rl program and assert the compile lands in
    the NAMED ledger. (Eager ops — jnp.asarray, PRNG setup — still
    compile anonymously on first touch; the zero-anonymous bar is a
    steady-state property and bench --smoke enforces it there.)"""
    import jax
    import numpy as np

    from ray_tpu._private import compile_watch as cw
    from ray_tpu.rl.models import init_policy_params, sample_actions

    params = init_policy_params(jax.random.PRNGKey(0), 4, 2)
    key = jax.random.PRNGKey(1)
    sample_actions(params, np.zeros((3, 4), np.float32), key)
    # Steady state: a second call with the same shapes must not
    # compile again — named or anonymous.
    snap0 = cw.snapshot()
    sample_actions(params, np.zeros((3, 4), np.float32), key)
    snap1 = cw.snapshot()
    assert snap1["rl.sample_actions"]["compiles"] >= 1
    assert (
        snap1["rl.sample_actions"]["compiles"]
        == snap0["rl.sample_actions"]["compiles"]
    )
    unreg0 = snap0.get("(unregistered)", {}).get("compiles", 0)
    unreg1 = snap1.get("(unregistered)", {}).get("compiles", 0)
    assert unreg1 == unreg0, "steady-state call compiled anonymously"


def test_stale_noqa_hygiene_keeps_repo_clean():
    """The audit that removed daemon/worker's stale suppressions is a
    live gate: the whole tree carries zero stale/unknown noqas."""
    from ray_tpu.devtools import (
        check_paths,
        lint_paths,
        race_paths,
    )

    hygiene = {"RT090", "RT190", "RT290", "RT390"}
    findings = [
        f
        for f in (
            lint_paths([PKG])
            + check_paths([PKG, TESTS])
            + race_paths([PKG, TESTS])
            + accel_paths([PKG, TESTS, BENCH])
        )
        if f.rule in hygiene
    ]
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# doctor correlation: live storm -> static site (the bridge, end to end)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_storm_problem_carries_static_hint_two_nodes(tmp_path):
    """A 2-node cluster, a worker-side drifting jit registered under a
    name the static inventory knows: `ray_tpu doctor --json` must
    report the recompile storm WITH a `static_hint` naming the RT302
    source site — the bridge from runtime symptom to static fix."""
    inventory = {
        "version": 1,
        "programs": [
            {
                "program": "test.storm_step",
                "name_kind": "literal",
                "path": "ray_tpu/models/generate.py",
                "line": 241,
                "registered": True,
                "hazards": [
                    {
                        "rule": "RT302",
                        "path": "pkg/train_loop.py",
                        "line": 88,
                        "message": "train_loop: static argument 1 "
                        "derives from len(...)",
                    }
                ],
            }
        ],
        "unregistered": [],
    }
    inv_path = tmp_path / "inventory.json"
    inv_path.write_text(json.dumps(inventory))
    os.environ["RT_accel_inventory"] = str(inv_path)
    try:
        from ray_tpu.cluster_utils import Cluster

        import ray_tpu as rt

        c = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
        c.add_node(num_cpus=2, resources={"remote_node": 4.0})
        c.wait_for_nodes(2)
        rt.init(address=c.address)
        try:

            @rt.remote
            def drifting(n):
                import jax
                import jax.numpy as jnp
                import numpy as np

                from ray_tpu._private import compile_watch as cw
                from ray_tpu.util import metrics

                fn = cw.instrument(
                    "test.storm_step",
                    jax.jit(lambda x: (x * 2 + 1).sum()),  # rt: noqa[RT301] — fixture exists to provoke recompiles
                )
                for i in range(2, n + 2):
                    fn(jnp.asarray(np.zeros((4, i), np.float32)))
                metrics.flush()
                return n

            assert rt.get(
                drifting.options(
                    resources={"remote_node": 1.0}
                ).remote(12),
                timeout=120,
            ) == 12

            env = dict(os.environ)
            env["PYTHONPATH"] = (
                REPO + os.pathsep + env.get("PYTHONPATH", "")
            )
            env.pop("RT_ADDRESS", None)
            out = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "ray_tpu",
                    "doctor",
                    "--json",
                    "--address",
                    c.address,
                    "--no-stacks",
                ],
                capture_output=True,
                text=True,
                timeout=120,
                env=env,
            )
            assert out.returncode == 1, out.stdout + out.stderr
            verdict = json.loads(out.stdout)
            storms = [
                p
                for p in verdict["problems"]
                if p["kind"] == "recompile_storm"
            ]
            assert storms, verdict["problems"]
            storm = storms[0]
            assert storm["program"] == "test.storm_step"
            # The bridge: the live symptom names the static fix site.
            assert "pkg/train_loop.py:88" in storm["static_hint"]
            assert "RT302" in storm["static_hint"]
        finally:
            rt.shutdown()
            c.shutdown()
    finally:
        os.environ.pop("RT_accel_inventory", None)
        from ray_tpu._private import compile_watch as cw

        cw.load_inventory(refresh=True)
