"""Observability tests (reference test models: metric export tests,
ray.timeline chrome trace, dashboard HTTP API)."""

import json
import time
import urllib.request

import pytest


def test_metrics_counter_gauge_histogram(rt_session):
    rt = rt_session
    from ray_tpu.util.metrics import (
        Counter,
        Gauge,
        Histogram,
        metrics_summary,
    )

    requests = Counter("app_requests", tag_keys=("route",))
    temperature = Gauge("app_temperature")
    latency = Histogram("app_latency_ms")

    requests.inc(1, tags={"route": "a"})
    requests.inc(2, tags={"route": "b"})
    temperature.set(21.5)
    for v in (5.0, 10.0, 15.0):
        latency.observe(v)

    deadline = time.time() + 10
    while time.time() < deadline:
        metrics = metrics_summary()
        if "app_requests" in metrics and metrics["app_requests"].get(
            "total"
        ) == 3.0 and metrics.get("app_latency_ms", {}).get("count") == 3:
            break
        time.sleep(0.2)
    metrics = metrics_summary()
    assert metrics["app_requests"]["total"] == 3.0
    assert metrics["app_requests"]["by_tags"]["route=b"]["total"] == 2.0
    assert metrics["app_temperature"]["value"] == 21.5
    hist = metrics["app_latency_ms"]
    assert hist["count"] == 3 and hist["sum"] == 30.0
    assert hist["min"] == 5.0 and hist["max"] == 15.0


def test_metrics_from_tasks(rt_session):
    rt = rt_session
    from ray_tpu.util.metrics import Counter, metrics_summary

    @rt.remote
    def work(i):
        from ray_tpu.util.metrics import Counter, flush

        Counter("task_side_counter").inc(1)
        flush()
        return i

    rt.get([work.remote(i) for i in range(5)], timeout=60)
    deadline = time.time() + 10
    while time.time() < deadline:
        metrics = metrics_summary()
        if metrics.get("task_side_counter", {}).get("total") == 5.0:
            break
        time.sleep(0.2)
    assert metrics_summary()["task_side_counter"]["total"] == 5.0


def test_chrome_trace_export(rt_session, tmp_path):
    rt = rt_session
    from ray_tpu.util.tracing import export_timeline

    @rt.remote
    def traced(x):
        return x + 1

    rt.get([traced.remote(i) for i in range(3)], timeout=30)
    path = str(tmp_path / "trace.json")
    trace = export_timeline(path)
    assert len(trace) >= 3
    with open(path) as f:
        loaded = json.load(f)
    slices = [e for e in loaded if e["name"] == "traced"]
    assert len(slices) == 3
    for event in slices:
        assert event["ph"] == "X" and event["dur"] >= 1


def test_dashboard_endpoints(rt_session):
    rt = rt_session
    import socket

    from ray_tpu.dashboard import start_dashboard

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    dash = start_dashboard(port)
    try:

        @rt.remote
        class Marker:
            def ping(self):
                return 1

        marker = Marker.remote()
        rt.get(marker.ping.remote(), timeout=30)

        def fetch(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30
            ) as resp:
                return resp.read()

        nodes = json.loads(fetch("/api/nodes"))
        assert len(nodes) == 1
        actors = json.loads(fetch("/api/actors"))
        assert any(a["class_name"] == "Marker" for a in actors)
        resources = json.loads(fetch("/api/resources"))
        assert "CPU" in resources["total"]
        html = fetch("/").decode()
        # SPA shell: data is client-rendered from /api/* (asserted
        # above); the page just needs to serve with its poller.
        assert "ray_tpu" in html and "/api/" in html

        from ray_tpu.util.metrics import Counter, flush

        Counter("dash_metric").inc(2)
        flush()
        time.sleep(0.3)
        prom = fetch("/metrics").decode()
        assert "dash_metric 2.0" in prom
    finally:
        dash.stop()


def test_event_stats_per_handler_timing(rt_session):
    """Per-handler RPC timing stats accumulate on the daemon
    (reference: event_stats.cc — count + execution + queueing delay
    per asio handler). After real traffic, the handlers that ran must
    show up with sane numbers."""
    rt = rt_session
    from ray_tpu.util import state

    @rt.remote
    def f(x):
        return x + 1

    assert rt.get([f.remote(i) for i in range(20)], timeout=60) == list(
        range(1, 21)
    )
    stats = state.event_stats()
    # direct transport routes tasks via leases; registration always
    # hits the daemon regardless of transport
    assert "register_client" in stats, sorted(stats)
    assert stats["register_client"]["count"] >= 1
    busiest = max(stats.values(), key=lambda r: r["count"])
    assert busiest["count"] >= 5
    for row in stats.values():
        assert row["max_exec_ms"] >= row["mean_exec_ms"] >= 0
        assert row["max_queue_ms"] >= row["mean_queue_ms"] >= 0
        assert row["errors"] >= 0
    # errors asserted only on a handler THIS test exercised — other
    # handlers may legitimately carry errors from session traffic.
    assert stats["register_client"]["errors"] == 0
