"""Observability tests (reference test models: metric export tests,
ray.timeline chrome trace, dashboard HTTP API)."""

import json
import time
import urllib.request

import pytest


def test_metrics_counter_gauge_histogram(rt_session):
    rt = rt_session
    from ray_tpu.util.metrics import (
        Counter,
        Gauge,
        Histogram,
        metrics_summary,
    )

    requests = Counter("app_requests", tag_keys=("route",))
    temperature = Gauge("app_temperature")
    latency = Histogram("app_latency_ms")

    requests.inc(1, tags={"route": "a"})
    requests.inc(2, tags={"route": "b"})
    temperature.set(21.5)
    for v in (5.0, 10.0, 15.0):
        latency.observe(v)

    deadline = time.time() + 10
    while time.time() < deadline:
        metrics = metrics_summary()
        if "app_requests" in metrics and metrics["app_requests"].get(
            "total"
        ) == 3.0 and metrics.get("app_latency_ms", {}).get("count") == 3:
            break
        time.sleep(0.2)
    metrics = metrics_summary()
    assert metrics["app_requests"]["total"] == 3.0
    assert metrics["app_requests"]["by_tags"]["route=b"]["total"] == 2.0
    assert metrics["app_temperature"]["value"] == 21.5
    hist = metrics["app_latency_ms"]
    assert hist["count"] == 3 and hist["sum"] == 30.0
    assert hist["min"] == 5.0 and hist["max"] == 15.0


def test_metrics_from_tasks(rt_session):
    rt = rt_session
    from ray_tpu.util.metrics import Counter, metrics_summary

    @rt.remote
    def work(i):
        from ray_tpu.util.metrics import Counter, flush

        Counter("task_side_counter").inc(1)
        flush()
        return i

    rt.get([work.remote(i) for i in range(5)], timeout=60)
    deadline = time.time() + 10
    while time.time() < deadline:
        metrics = metrics_summary()
        if metrics.get("task_side_counter", {}).get("total") == 5.0:
            break
        time.sleep(0.2)
    assert metrics_summary()["task_side_counter"]["total"] == 5.0


def test_chrome_trace_export(rt_session, tmp_path):
    rt = rt_session
    from ray_tpu.util.tracing import export_timeline

    @rt.remote
    def traced(x):
        return x + 1

    rt.get([traced.remote(i) for i in range(3)], timeout=30)
    path = str(tmp_path / "trace.json")
    trace = export_timeline(path)
    assert len(trace) >= 3
    with open(path) as f:
        loaded = json.load(f)
    slices = [e for e in loaded if e["name"] == "traced"]
    assert len(slices) == 3
    for event in slices:
        assert event["ph"] == "X" and event["dur"] >= 1


def test_timeline_slice_excludes_queue_time():
    """The chrome slice runs from the first RUNNING-adjacent state to
    the terminal state; queue time (PENDING_*/FORWARDED) is reported
    as args.queued_us, not billed as runtime (satellite fix: the dead
    _BEGIN_STATES/_END_STATES are now load-bearing)."""
    from ray_tpu.util.tracing import timeline_to_chrome_trace

    t0 = 1000.0
    events = [
        {
            "task_id": "t1",
            "name": "queued_task",
            "kind": "normal",
            "state": state,
            "time": t0 + dt,
        }
        for state, dt in (
            ("PENDING_NODE_ASSIGNMENT", 0.0),
            ("FORWARDED", 2.0),
            ("RUNNING", 5.0),
            ("FINISHED", 6.0),
        )
    ]
    (slice_,) = timeline_to_chrome_trace(events)
    assert slice_["ts"] == pytest.approx((t0 + 5.0) * 1e6)
    assert slice_["dur"] == pytest.approx(1e6)
    assert slice_["args"]["queued_us"] == pytest.approx(5e6)
    assert slice_["args"]["final_state"] == "FINISHED"

    # A task with only queued states (never ran) still gets a slice —
    # a 1 us marker at submission with the whole span reported as
    # queue time, so none of it reads as execution.
    (queued_only,) = timeline_to_chrome_trace(events[:2])
    assert queued_only["ts"] == pytest.approx(t0 * 1e6)
    assert queued_only["dur"] == pytest.approx(1.0)
    assert queued_only["args"]["queued_us"] == pytest.approx(2e6)
    assert queued_only["args"]["final_state"] == "FORWARDED"


def test_timeline_retry_splits_into_attempts():
    """A re-queue transition (RETRY/RECONSTRUCTING) splits the task
    into per-attempt slices: the reschedule wait must be billed as
    that attempt's queue time, never as runtime."""
    from ray_tpu.util.tracing import timeline_to_chrome_trace

    t0 = 1000.0
    events = [
        {
            "task_id": "t1",
            "name": "retried",
            "kind": "normal",
            "state": state,
            "time": t0 + dt,
        }
        for state, dt in (
            ("PENDING_NODE_ASSIGNMENT", 0.0),
            ("RUNNING", 1.0),
            ("RETRY", 2.0),
            ("FORWARDED", 3.0),
            ("RUNNING", 62.0),
            ("FINISHED", 63.0),
        )
    ]
    first, second = timeline_to_chrome_trace(events)
    # Attempt 1: ran 1s (RUNNING@1 -> RETRY@2 closes the attempt).
    assert first["ts"] == pytest.approx((t0 + 1.0) * 1e6)
    assert first["dur"] == pytest.approx(1e6)
    assert first["args"]["attempt"] == 1
    # Attempt 2: the 60s reschedule wait is queue time, runtime is
    # the 1s second execution.
    assert second["ts"] == pytest.approx((t0 + 62.0) * 1e6)
    assert second["dur"] == pytest.approx(1e6)
    assert second["args"]["queued_us"] == pytest.approx(60e6)
    assert second["args"]["final_state"] == "FINISHED"
    assert second["args"]["attempts"] == 2


def test_requeue_truncation_keeps_boundary_declares():
    """A head outage long enough to overflow the requeue cap must not
    age out the one record carrying a histogram's boundaries — the
    head could never bucket that histogram again."""
    from ray_tpu.util import metrics

    buf = metrics._Buffer()
    try:
        declare = ("histogram", "h", 1.0, (), (10.0, 100.0))
        buf.push(declare)
        for _ in range(metrics._MAX_BUFFERED + 5):
            buf.push(("counter", "c", 1.0, ()))
        # No session: delivery fails, the sealed batch stays trimmed.
        buf.flush(raise_on_error=False)
        with buf.records_lock:
            buffered = [
                r for _, batch in buf._sealed for r in batch
            ]
        assert declare in buffered
        assert len(buffered) <= metrics._MAX_BUFFERED + 1
    finally:
        buf._stop.set()


def test_metrics_redelivery_does_not_double_count(rt_session):
    """Sealed batches retry until acknowledged; a batch whose reply
    was lost arrives twice and must be folded in exactly once. Uses a
    synthetic sender so the live driver's dedup state is untouched."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util.metrics import metrics_summary

    worker = global_worker()
    batch = [("counter", "dedup_total", 5.0, ())]
    worker.call(
        "metrics_record", records=batch, sender="t-sender", seq=7
    )
    assert metrics_summary()["dedup_total"]["total"] == 5.0
    # The lost-reply retry: same (sender, seq) redelivered — dropped.
    worker.call(
        "metrics_record", records=batch, sender="t-sender", seq=7
    )
    assert metrics_summary()["dedup_total"]["total"] == 5.0
    # A NEW seq from the same sender still lands.
    worker.call(
        "metrics_record",
        records=[("counter", "dedup_total", 2.0, ())],
        sender="t-sender",
        seq=8,
    )
    assert metrics_summary()["dedup_total"]["total"] == 7.0


def test_merged_chrome_trace_has_all_three_streams(tmp_path):
    """doctor --trace artifact: task slices + spans + per-rank step
    phases in one chrome trace, phases laid sequentially inside the
    step's wall window."""
    from ray_tpu.util.tracing import merge_chrome_trace

    t0 = 2000.0
    task_events = [
        {
            "task_id": "t1",
            "name": "task_a",
            "kind": "normal",
            "state": "RUNNING",
            "time": t0,
        },
        {
            "task_id": "t1",
            "name": "task_a",
            "kind": "normal",
            "state": "FINISHED",
            "time": t0 + 1.0,
        },
    ]
    spans = [
        {
            "name": "span_a",
            "trace_id": "ab" * 16,
            "span_id": "cd" * 8,
            "parent_span_id": "",
            "start_ns": int(t0 * 1e9),
            "end_ns": int((t0 + 0.5) * 1e9),
            "attributes": {"flavor": "x"},
        }
    ]
    steps = [
        {
            "step": 7,
            "rank": 0,
            "time": t0 + 1.0,
            "wall_ms": 1000.0,
            "data_wait_ms": 200.0,
            "step_ms": 800.0,
        }
    ]
    path = tmp_path / "merged.json"
    trace = merge_chrome_trace(task_events, spans, steps, str(path))
    assert json.load(open(path)) == trace
    by_cat = {}
    for event in trace:
        by_cat.setdefault(event["cat"], []).append(event)
    assert {"normal", "span", "step"} <= set(by_cat)
    # Step phases: sequential layout filling the wall window.
    wait, step = sorted(by_cat["step"], key=lambda e: e["ts"])
    assert wait["name"] == "step 7 data_wait"
    assert step["name"] == "step 7 step"
    assert wait["ts"] == pytest.approx((t0 + 1.0 - 1.0) * 1e6)
    assert step["ts"] == pytest.approx(wait["ts"] + wait["dur"])
    assert step["dur"] == pytest.approx(800e3)
    assert wait["tid"] == "rank 0" and wait["pid"] == "steps"


def test_dashboard_endpoints(rt_session):
    rt = rt_session
    import socket

    from ray_tpu.dashboard import start_dashboard

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    dash = start_dashboard(port)
    try:

        @rt.remote
        class Marker:
            def ping(self):
                return 1

        marker = Marker.remote()
        rt.get(marker.ping.remote(), timeout=30)

        def fetch(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30
            ) as resp:
                return resp.read()

        nodes = json.loads(fetch("/api/nodes"))
        assert len(nodes) == 1
        actors = json.loads(fetch("/api/actors"))
        assert any(a["class_name"] == "Marker" for a in actors)
        resources = json.loads(fetch("/api/resources"))
        assert "CPU" in resources["total"]
        html = fetch("/").decode()
        # SPA shell: data is client-rendered from /api/* (asserted
        # above); the page just needs to serve with its poller.
        assert "ray_tpu" in html and "/api/" in html

        from ray_tpu.util.metrics import Counter, flush

        Counter("dash_metric").inc(2)
        flush()
        time.sleep(0.3)
        prom = fetch("/metrics").decode()
        assert "dash_metric 2.0" in prom
    finally:
        dash.stop()


def test_histogram_boundaries_buckets_and_percentiles(rt_session):
    """Satellite: declared boundaries are real — the head buckets
    observations (cumulative le_* counts) and reports p50/p95/p99
    from its sample reservoir."""
    rt = rt_session
    from ray_tpu.util.metrics import Histogram, metrics_summary

    lat = Histogram(
        "bucketed_ms", boundaries=[10, 100, 1000], tag_keys=("op",)
    )
    for v in (5.0, 50.0, 50.0, 500.0, 2000.0):
        lat.observe(v, tags={"op": "rpc"})

    deadline = time.time() + 10
    while time.time() < deadline:
        hist = metrics_summary().get("bucketed_ms", {})
        if hist.get("count") == 5:
            break
        time.sleep(0.2)
    assert hist["count"] == 5
    assert hist["buckets"] == {
        "le_10": 1,
        "le_100": 3,
        "le_1000": 4,
        "inf": 5,
    }
    assert hist["p50"] == 50.0
    assert hist["p95"] == 2000.0
    assert hist["p99"] == 2000.0
    # Per-tag buckets too, and no internal reservoir keys on the wire.
    tagged = hist["by_tags"]["op=rpc"]
    assert tagged["buckets"]["inf"] == 5
    assert not any(k.startswith("_") for k in hist)
    assert not any(k.startswith("_") for k in tagged)


def test_metrics_buffer_resets_on_shutdown():
    """Satellite: the _Buffer singleton + flusher thread die with
    ray_tpu.shutdown(); re-init binds a fresh buffer to the new
    session instead of leaking records at the dead one."""
    import ray_tpu as rt
    from ray_tpu.util.metrics import Counter, _Buffer, metrics_summary

    rt.init(num_cpus=2)
    try:
        Counter("lifecycle_counter").inc(1.0)
        first = _Buffer.get()
        assert first.thread.is_alive()
    finally:
        rt.shutdown()
    assert _Buffer._instance is None
    first.thread.join(timeout=5)
    assert not first.thread.is_alive()

    rt.init(num_cpus=2)
    try:
        second = _Buffer.get()
        assert second is not first
        Counter("lifecycle_counter").inc(41.0)
        deadline = time.time() + 10
        total = None
        while time.time() < deadline:
            total = (
                metrics_summary()
                .get("lifecycle_counter", {})
                .get("total")
            )
            if total == 41.0:
                break
            time.sleep(0.2)
        # Fresh cluster: only the post-re-init increment exists.
        assert total == 41.0
    finally:
        rt.shutdown()


def test_metrics_flush_raises_without_session():
    """Satellite: an explicit flush() surfaces delivery failure
    (RayTpuError) instead of silently swallowing it; the records
    stay buffered for a later retry."""
    import ray_tpu.exceptions as exc
    from ray_tpu.util.metrics import _Buffer, flush

    _Buffer.reset()  # known-clean start regardless of test order
    buf = _Buffer.get()
    try:
        buf.push(("counter", "orphan_metric", 1.0, ()))
        with pytest.raises(exc.RayTpuError):
            flush()
        with buf.records_lock:
            buffered = [
                r for _, batch in buf._sealed for r in batch
            ]
        assert buffered, "failed flush must keep the batch, not drop"
    finally:
        _Buffer.reset()


def test_event_stats_per_handler_timing(rt_session):
    """Per-handler RPC timing stats accumulate on the daemon
    (reference: event_stats.cc — count + execution + queueing delay
    per asio handler). After real traffic, the handlers that ran must
    show up with sane numbers."""
    rt = rt_session
    from ray_tpu.util import state

    @rt.remote
    def f(x):
        return x + 1

    assert rt.get([f.remote(i) for i in range(20)], timeout=60) == list(
        range(1, 21)
    )
    stats = state.event_stats()
    # direct transport routes tasks via leases; registration always
    # hits the daemon regardless of transport
    assert "register_client" in stats, sorted(stats)
    assert stats["register_client"]["count"] >= 1
    busiest = max(stats.values(), key=lambda r: r["count"])
    assert busiest["count"] >= 5
    for row in stats.values():
        assert row["max_exec_ms"] >= row["mean_exec_ms"] >= 0
        assert row["max_queue_ms"] >= row["mean_queue_ms"] >= 0
        assert row["errors"] >= 0
    # errors asserted only on a handler THIS test exercised — other
    # handlers may legitimately carry errors from session traffic.
    assert stats["register_client"]["errors"] == 0
