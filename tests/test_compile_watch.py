"""XLA-layer observability tests (ISSUE 15).

Covers the compile watcher end to end: a deliberately shape-unstable
jitted function must be convicted by `doctor --json` verdict.compile
(exit 1, program + drifting shape dimension named) while a
shape-stable loop stays clean; compile_ms bills as a step stall phase
only on the step that actually compiled; HBM fields are ABSENT (not
zero/fake) on CPU backends; the hot-path overhead holds the <1%-of-
smoke-step bar; and a 2-node coordinated gang profile merges per-rank
sampler slices with step phases into one chrome trace on one clock.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Each test starts with an empty per-process compile registry
    (the head-side table is fresh per rt.init already)."""
    from ray_tpu._private import compile_watch

    compile_watch.reset()
    yield
    compile_watch.reset()


# ---------------------------------------------------------------------------
# digests / shape deltas (pure host-side, no cluster)
# ---------------------------------------------------------------------------


def test_arg_digest_keys_on_shape_and_dtype():
    import numpy as np

    from ray_tpu._private import compile_watch as cw

    a32 = np.zeros((4, 8), np.float32)
    b32 = np.ones((4, 8), np.float32)  # same shape/dtype, other values
    wide = np.zeros((4, 16), np.float32)
    a16 = np.zeros((4, 8), np.float16)
    assert cw.arg_digest((a32,), {}) == cw.arg_digest((b32,), {})
    assert cw.arg_digest((a32,), {}) != cw.arg_digest((wide,), {})
    assert cw.arg_digest((a32,), {}) != cw.arg_digest((a16,), {})
    # Python scalars digest by TYPE, not value: a traced scalar
    # changing value must never mint a fake storm.
    assert cw.arg_digest((a32, 1), {}) == cw.arg_digest((a32, 2), {})
    assert cw.arg_digest((a32, 1), {}) != cw.arg_digest((a32, 1.0), {})
    # ...while strings are always jit statics: value matters.
    assert cw.arg_digest((a32, "mean"), {}) != cw.arg_digest(
        (a32, "sum"), {}
    )
    # Cross-process stability (the head folds digests from many
    # ranks): the short key is content-derived, not hash()-salted.
    key = cw.digest_key(cw.arg_digest((a32,), {}))
    assert key == cw.digest_key(cw.arg_digest((b32,), {}))
    assert len(key) == 12


def test_shape_delta_names_drifting_dimension():
    import numpy as np

    from ray_tpu._private import compile_watch as cw

    prev = cw.digest_leaves(
        cw.arg_digest((np.zeros((8, 128), np.int32),), {})
    )
    new = cw.digest_leaves(
        cw.arg_digest((np.zeros((8, 131), np.int32),), {})
    )
    delta = cw.shape_delta(prev, new)
    assert "dim 1" in delta and "i32[8,128]" in delta
    dtype_new = cw.digest_leaves(
        cw.arg_digest((np.zeros((8, 128), np.float32),), {})
    )
    assert "dtype" in cw.shape_delta(prev, dtype_new)
    assert "arity" in cw.shape_delta(prev, prev + new)


def test_storm_detector_thresholds():
    from ray_tpu._private import compile_watch as cw

    programs: dict = {}
    for i in range(6):
        cw.fold_record(
            programs,
            "bucketed.prefill",
            5.0,
            {"digest": f"bucket{i}", "leaves": (("int32", (8, 2 ** i)),)},
        )
    # 6 distinct digests: a legitimate bucket family, below the
    # default threshold of 8 — no storm.
    assert cw.detect_storms(programs, 8) == []
    for i in range(6, 12):
        cw.fold_record(
            programs,
            "bucketed.prefill",
            5.0,
            {"digest": f"bucket{i}", "leaves": (("int32", (8, 2 ** i)),)},
        )
    storms = cw.detect_storms(programs, 8)
    assert len(storms) == 1
    assert storms[0]["program"] == "bucketed.prefill"
    assert storms[0]["distinct_shapes"] == 12
    assert "bucketed.prefill" in storms[0]["detail"]


def test_storm_window_ages_out_old_digests():
    """Distinct shapes accumulated over a cluster's LIFETIME are not
    a storm: digests older than the window don't count, so a healthy
    long-lived cluster (warmup buckets + redeploys + successive
    jobs) goes back to exit 0 once nothing is actively drifting."""
    import time as _time

    from ray_tpu._private import compile_watch as cw

    programs: dict = {}
    stale = _time.time() - 2 * cw.STORM_WINDOW_S
    for i in range(20):
        cw.fold_record(
            programs,
            "longlived.step",
            5.0,
            {"digest": f"old{i}", "time": stale + i},
        )
    assert cw.detect_storms(programs, 8) == []
    # The same count of RECENT digests is a storm.
    for i in range(8):
        cw.fold_record(
            programs, "longlived.step", 5.0, {"digest": f"new{i}"}
        )
    storms = cw.detect_storms(programs, 8)
    assert len(storms) == 1
    assert storms[0]["distinct_shapes"] == 8


def test_digest_ring_is_bounded():
    from ray_tpu._private import compile_watch as cw

    programs: dict = {}
    for i in range(4 * cw.DIGEST_RING):
        cw.fold_record(
            programs, "p", 1.0, {"digest": f"d{i}"}
        )
    row = programs["p"]
    assert row["compiles"] == 4 * cw.DIGEST_RING
    assert len(row["digests"]) == cw.DIGEST_RING


# ---------------------------------------------------------------------------
# instrumented programs against a live session
# ---------------------------------------------------------------------------


def _drifting_loop(n: int = 12):
    """A deliberately shape-unstable jitted loop: one dimension grows
    every iteration — the classic silent recompile storm."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu._private import compile_watch as cw

    fn = cw.instrument(
        "test.drifting_step", jax.jit(lambda x: (x * 2 + 1).sum())  # rt: noqa[RT301] — fixture exists to provoke recompiles on purpose
    )
    for i in range(2, n + 2):
        fn(jnp.asarray(np.zeros((4, i), np.float32)))
    return fn


def test_shape_unstable_loop_convicted_by_doctor(rt_session):
    rt = rt_session
    from ray_tpu.util import metrics

    _drifting_loop()
    metrics.flush()
    verdict = rt.diagnose(capture_stacks=False)
    assert verdict["healthy"] is False
    storms = [
        p
        for p in verdict["problems"]
        if p["kind"] == "recompile_storm"
    ]
    assert len(storms) == 1
    assert storms[0]["program"] == "test.drifting_step"
    assert storms[0]["compiles"] == 12
    # The runbook half: the verdict names WHAT drifted, down to the
    # dimension.
    assert "dim 1" in storms[0]["delta"]
    comp = verdict["compile"]
    assert comp["programs"]["test.drifting_step"]["distinct_shapes"] == 12
    # The same table is served standalone for the dashboard tab.
    from ray_tpu.util.state import compile_summary

    summary = compile_summary()
    assert summary["storms"][0]["program"] == "test.drifting_step"


def test_shape_stable_loop_stays_clean(rt_session):
    rt = rt_session
    import jax
    import jax.numpy as jnp

    from ray_tpu._private import compile_watch as cw
    from ray_tpu.util import metrics

    fn = cw.instrument(
        "test.stable_step", jax.jit(lambda x: (x * 2).sum())
    )
    x = jnp.zeros((4, 8), jnp.float32)
    for _ in range(20):
        fn(x)
    metrics.flush()
    verdict = rt.diagnose(capture_stacks=False)
    assert [
        p
        for p in verdict["problems"]
        if p["kind"] == "recompile_storm"
    ] == []
    row = verdict["compile"]["programs"]["test.stable_step"]
    assert row["compiles"] == 1
    assert row["distinct_shapes"] == 1
    assert fn.stats() == {"compiles": 1, "distinct_shapes": 1}


def test_doctor_cli_names_program_and_shape_delta(rt_session):
    """The operator surface: `ray_tpu doctor --json` (a separate
    process) exits 1 on a recompile storm and its JSON names the
    program and the drifting dimension."""
    from ray_tpu.util import metrics

    _drifting_loop()
    metrics.flush()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    import ray_tpu as rt

    address = rt.api._session.address
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "ray_tpu",
            "doctor",
            "--json",
            "--address",
            address,
            "--no-stacks",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    verdict = json.loads(out.stdout)
    storms = [
        p
        for p in verdict["problems"]
        if p["kind"] == "recompile_storm"
    ]
    assert storms and storms[0]["program"] == "test.drifting_step"
    assert "dim 1" in storms[0]["delta"]


def test_compile_ms_bills_only_the_compiling_step(rt_session):
    """compile_ms is a first-class stall phase: present (and large)
    on the step whose call compiled, ABSENT on the steady-state steps
    after it — cold compiles stop polluting steady-state goodput."""
    import jax
    import jax.numpy as jnp

    from ray_tpu._private import compile_watch as cw
    from ray_tpu._private.step_telemetry import take_phases
    from ray_tpu.train import telemetry

    take_phases()  # baseline drain, hand-rolled-loop contract
    fn = cw.instrument(
        "test.billed_step", jax.jit(lambda x: (x @ x.T).sum())
    )
    x = jnp.zeros((16, 16), jnp.float32)
    for step in (1, 2, 3):
        t0 = time.monotonic()
        fn(x)
        telemetry.report_step(
            step, rank=0, wall_ms=(time.monotonic() - t0) * 1e3
        )
    records = {r["step"]: r for r in telemetry.step_records()}
    assert records[1].get("compile_ms", 0.0) > 0.0
    assert "compile_ms" not in records[2]
    assert "compile_ms" not in records[3]
    # Goodput classifies compile as stall, not compute: the compiling
    # step's residual step_ms must not contain the compile seconds.
    assert records[1]["step_ms"] <= records[1]["wall_ms"] - records[
        1
    ]["compile_ms"] + 1.0


def test_hbm_fields_absent_on_cpu(rt_session):
    """On CPU backends device.memory_stats() is unavailable: the
    step record carries NO hbm_* fields (absent, never fake zeros)
    and the verdict reports no HBM pressure."""
    import jax  # noqa: F401 — ensure jax is loaded, the probed path

    from ray_tpu._private.compile_watch import device_memory
    from ray_tpu.train import telemetry

    assert device_memory() is None
    rt = rt_session
    telemetry.report_step(1, rank=0, wall_ms=25.0, step_ms=20.0)
    records = telemetry.step_records()
    assert records
    for rec in records:
        for key in rec:
            assert not key.startswith("hbm_"), rec
    verdict = rt.diagnose(capture_stacks=False)
    assert verdict["compile"]["hbm_pressure"] == []


def test_hbm_pressure_verdict_names_rank(rt_session):
    """A step record reporting >=90% of HBM capacity flips the
    doctor to hbm_pressure naming the rank (fed through the same
    step-record path a TPU rank would use)."""
    rt = rt_session
    from ray_tpu.train import telemetry

    gib = 2 ** 30
    telemetry.report_step(
        1,
        rank=3,
        wall_ms=100.0,
        step_ms=90.0,
        extra={
            "hbm_bytes_in_use": 15 * gib,
            "hbm_peak_bytes": 15 * gib,
            "hbm_bytes_limit": 16 * gib,
        },
    )
    verdict = rt.diagnose(capture_stacks=False)
    pressure = [
        p for p in verdict["problems"] if p["kind"] == "hbm_pressure"
    ]
    assert len(pressure) == 1
    assert pressure[0]["rank"] == 3
    assert pressure[0]["fraction"] == pytest.approx(15 / 16, abs=1e-3)
    assert "rank 3" in pressure[0]["detail"]


def test_unregistered_compiles_never_fake_a_storm(rt_session):
    """Compiles outside any instrumented program are still counted
    (program "(unregistered)") but carry no digest — so they can
    never cross the distinct-shapes storm threshold."""
    rt = rt_session
    import jax.numpy as jnp

    from ray_tpu.util import metrics

    # Eager ops with drifting shapes compile un-instrumented.
    for i in range(2, 12):
        _ = jnp.ones((i,), jnp.float32) * 2
    metrics.flush()
    verdict = rt.diagnose(capture_stacks=False)
    storms = [
        p
        for p in verdict["problems"]
        if p["kind"] == "recompile_storm"
    ]
    assert storms == []
    row = verdict["compile"]["programs"].get("(unregistered)")
    if row is not None:  # jax may cache some of these
        assert row["distinct_shapes"] == 0


def test_metrics_exposition_program_label_only(rt_session):
    """The RT010 cardinality contract by construction: the exported
    compile series carry the program NAME as their only label — no
    digest/shape labels ever reach the exposition."""
    import jax
    import jax.numpy as jnp

    from ray_tpu._private import compile_watch as cw
    from ray_tpu.util import metrics
    from ray_tpu.util.prometheus import render_prometheus

    fn = cw.instrument(
        "test.labels", jax.jit(lambda x: x + 1)
    )
    fn(jnp.zeros((4,), jnp.float32))
    metrics.flush()
    text = render_prometheus(metrics.metrics_summary())
    lines = [
        line
        for line in text.splitlines()
        if line.startswith("rt_jax_") and "{" in line
    ]
    assert any(
        'rt_jax_compiles_total{program="test.labels"}' in line
        for line in lines
    )
    for line in lines:
        labels = line[line.index("{") + 1 : line.index("}")]
        keys = {
            part.split("=", 1)[0] for part in labels.split(",")
        }
        assert keys <= {"program", "le"}, line
    # HELP lines ride from metric_defs.PIPE_METRICS.
    assert "# HELP rt_jax_compiles_total" in text


def test_config_kill_switch_and_threshold():
    """compile_watch honors the cluster config: disabled -> the hot
    path is a passthrough recording nothing; storm threshold follows
    compile_storm_threshold."""
    from ray_tpu._private import compile_watch as cw
    from ray_tpu._private.config import Config

    try:
        cw.configure(Config(compile_watch_enabled=False))
        assert not cw.enabled()
        fn = cw.instrument("test.disabled", lambda x: x)
        assert fn(41) == 41
        assert cw.snapshot() == {}
        cw.configure(
            Config(
                compile_watch_enabled=True,
                compile_storm_threshold=3,
            )
        )
        assert cw.enabled() and cw.storm_threshold() == 3
    finally:
        cw.configure(Config())


def test_hot_path_overhead_under_one_percent_of_smoke_step():
    """The hard bar from ISSUE 15: the per-call hot-path cost of an
    instrumented program (digest + seen-set lookup) on a realistic
    train-step argument tree must stay under 1% of the --smoke train
    step time. Measured against a conservative floor (20 ms) ~40x
    below the observed smoke step median (~860 ms for the tiny-llama
    CPU step this box runs), so the test neither inherits the step's
    run-to-run noise nor flakes when CI runs the suite under load —
    while still holding the watcher to <0.2 ms/call (typical: ~40
    µs)."""
    import jax  # noqa: F401 — the digest fast path needs jax loaded
    import numpy as np

    from ray_tpu._private import compile_watch as cw

    params = {
        f"layer_{i}": {
            "attn": {
                k: np.zeros((4, 4), np.float32)
                for k in ("wq", "wk", "wv", "wo")
            },
            "mlp": {
                "w1": np.zeros((4, 8), np.float32),
                "w2": np.zeros((8, 4), np.float32),
            },
        }
        for i in range(16)
    }
    batch = np.zeros((8, 128), np.int32)
    fn = cw.instrument("test.overhead", lambda *a: None)
    fn(params, batch, batch)  # the one recorded compile
    n = 2000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            fn(params, batch, batch)
        best = min(best, (time.perf_counter() - t0) / n)
    overhead_ms = best * 1e3
    smoke_step_floor_ms = 20.0
    assert overhead_ms < 0.01 * smoke_step_floor_ms, (
        f"compile-watch hot path costs {overhead_ms:.4f} ms/call — "
        f"over 1% of a {smoke_step_floor_ms} ms smoke step"
    )
    # The hot path recorded nothing (the seed call records at most
    # one compile — zero when monitoring proved no XLA work fired).
    assert fn.stats()["compiles"] <= 1


# ---------------------------------------------------------------------------
# coordinated gang profiling
# ---------------------------------------------------------------------------


def test_profile_gang_requires_step_reporting_gang(rt_session):
    rt = rt_session
    with pytest.raises(Exception, match="step-reporting"):
        rt.profile_gang(duration_s=0.2)


@pytest.mark.slow
def test_gang_profile_two_nodes_one_merged_trace(tmp_path):
    """E2E (slow): a 2-rank gang across 2 nodes reports step
    telemetry, then `rt.profile_gang` captures one synchronized
    window; the merged artifact must parse as chrome-trace JSON with
    both ranks' sampler slices AND step phases on one epoch-us
    clock."""
    from ray_tpu.cluster_utils import Cluster

    import ray_tpu as rt

    c = Cluster(initialize_head=True, head_resources={"CPU": 3.0})
    c.add_node(num_cpus=3, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:

        @rt.remote
        class GangMember:
            def __init__(self, rank):
                self.rank = rank

            def report(self):
                from ray_tpu.train import telemetry
                from ray_tpu.util import metrics

                for step in range(1, 4):
                    telemetry.report_step(
                        step,
                        rank=self.rank,
                        wall_ms=20.0,
                        step_ms=15.0,
                    )
                metrics.flush()
                return self.rank

            def spin(self, duration_s):
                # Busy work for the sampler to see during the window.
                t0 = time.monotonic()
                x = 0
                while time.monotonic() - t0 < duration_s:
                    x += sum(range(200))
                return x

        ranks = [
            GangMember.remote(0),
            GangMember.options(
                resources={"remote_node": 1.0}
            ).remote(1),
        ]
        assert rt.get(
            [m.report.remote() for m in ranks], timeout=120
        ) == [0, 1]

        spins = [m.spin.remote(4.0) for m in ranks]
        out_path = tmp_path / "gang_trace.json"
        reply = rt.profile_gang(
            duration_s=1.0, hz=200.0, path=str(out_path)
        )
        rt.get(spins, timeout=120)

        assert reply["errors"] == {}
        assert sorted(r["rank"] for r in reply["ranks"]) == [0, 1]
        assert all(r["samples"] > 0 for r in reply["ranks"])
        # The artifact is chrome-trace JSON, both ranks' sampler
        # slices re-homed under rank-labeled process rows.
        trace = json.loads(out_path.read_text())
        assert isinstance(trace, list) and trace
        sample_pids = {
            e["pid"] for e in trace if e.get("cat") == "sample"
        }
        assert {"rank 0", "rank 1"} <= sample_pids
        # Step phases of the same job ride the same artifact...
        step_rows = {
            e["tid"] for e in trace if e.get("cat") == "step"
        }
        assert {"rank 0", "rank 1"} <= step_rows
        # ...and every slice sits on ONE shared epoch-us clock: all
        # sampler timestamps fall inside the synchronized window.
        window = reply["window"]
        lo = (window["start"] - 1.0) * 1e6
        hi = (
            window["start"] + window["duration_s"] + 30.0
        ) * 1e6
        for e in trace:
            if e.get("cat") == "sample":
                assert lo <= e["ts"] <= hi, e
    finally:
        rt.shutdown()
        c.shutdown()


def test_doctor_stack_capture_rides_gang_relay(rt_session):
    """Satellite: the doctor's hung-task stack capture was rewired
    onto the SAME _profile_target relay the gang profiler uses (one
    start/stop/collect implementation) — a hung task's verdict still
    carries its stack."""
    rt = rt_session

    @rt.remote
    def hang_for_profile():
        time.sleep(300)

    ref = hang_for_profile.remote()
    try:
        deadline = time.time() + 60
        hung = []
        while time.time() < deadline and not hung:
            verdict = rt.diagnose(hung_task_s=0.5)
            hung = [
                p
                for p in verdict["problems"]
                if p["kind"] == "hung_task"
            ]
            if not hung:
                time.sleep(0.3)
        assert hung, "hung task never detected"
        assert "hang_for_profile" in hung[0].get("stack", ""), hung[0]
    finally:
        rt.cancel(ref, force=True)
