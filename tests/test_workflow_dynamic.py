"""Dynamic workflow tests: continuations + virtual actors.

Reference test model: python/ray/workflow/tests/test_recovery.py
(continuation recursion is durable across crashes) and the virtual
actor semantics (state persisted per call, reattach by id).
"""

import os

import pytest


def test_continuation_recursion_durable(rt_session, tmp_path):
    """A recursive factorial via continuations: every level is a
    durable step; the final value is the full product."""
    rt = rt_session
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    @rt.remote
    def fact(pair):
        from ray_tpu import workflow as wf

        n, acc = pair
        if n <= 1:
            return acc
        with InputNode() as inp:
            sub = fact.bind(inp)
        return wf.continuation(sub, (n - 1, acc * n))

    with InputNode() as inp:
        dag = fact.bind(inp)
    result = workflow.run(
        dag,
        workflow_id="wf-fact",
        input_value=(5, 1),
        storage=str(tmp_path),
    )
    assert result == 120
    # Each recursion level left durable step files, namespaced under
    # the parent step (001-fact, 001-fact.001-fact, ...).
    files = sorted(os.listdir(tmp_path / "wf-fact"))
    nested = [f for f in files if f.count("001-fact") >= 2]
    assert nested, files


def test_continuation_resume_skips_committed_levels(
    rt_session, tmp_path
):
    """Crash mid-continuation: resume re-enters the persisted sub-DAG
    without re-running the generating step."""
    rt = rt_session
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    marker = str(tmp_path / "runs")
    flag = str(tmp_path / "fail.flag")
    open(flag, "w").close()

    @rt.remote
    def outer(x):
        from ray_tpu import workflow as wf

        with open(marker, "a") as f:
            f.write("outer\n")
        with InputNode() as inp:
            sub = inner.bind(inp)
        return wf.continuation(sub, x + 1)

    @rt.remote
    def inner(y):
        if os.path.exists(flag):
            raise RuntimeError("injected crash")
        with open(marker, "a") as f:
            f.write("inner\n")
        return y * 10

    with InputNode() as inp:
        dag = outer.bind(inp)

    with pytest.raises(Exception):
        workflow.run(
            dag,
            workflow_id="wf-cont",
            input_value=3,
            storage=str(tmp_path),
        )
    os.remove(flag)
    assert (
        workflow.resume("wf-cont", storage=str(tmp_path)) == 40
    )
    with open(marker) as f:
        runs = f.read().split()
    # outer committed once (its continuation was persisted before the
    # crash); inner ran once after the flag cleared.
    assert runs == ["outer", "inner"]


@pytest.mark.timeout(300)
def test_continuation_depth_beyond_python_recursion_limit(
    rt_session, tmp_path
):
    """350 durable continuation levels: a recursive implementation
    dies on the interpreter's frame limit around depth ~300 (and
    again on every resume); the trampoline walks it flat. Deep
    prefixes also exceed filename limits and must digest-collapse."""
    rt = rt_session
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    @rt.remote
    def countdown(pair):
        from ray_tpu import workflow as wf

        n, acc = pair
        if n == 0:
            return acc
        with InputNode() as inp:
            sub = countdown.bind(inp)
        return wf.continuation(sub, (n - 1, acc + n))

    with InputNode() as inp:
        dag = countdown.bind(inp)
    depth = 350
    result = workflow.run(
        dag,
        workflow_id="wf-deep",
        input_value=(depth, 0),
        storage=str(tmp_path),
    )
    assert result == depth * (depth + 1) // 2
    # Long step ids collapsed to digest names, none past the
    # filesystem's 255-byte component limit.
    names = os.listdir(tmp_path / "wf-deep")
    assert max(len(n) for n in names) < 200
    assert len(names) > 2 * depth  # every level left durable files


def test_virtual_actor_state_persists_and_reattaches(
    rt_session, tmp_path
):
    rt = rt_session
    from ray_tpu import workflow

    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.value = start

        def add(self, n):
            self.value += n
            return self.value

        @workflow.virtual_actor_readonly
        def get(self):
            return self.value

    counter = Counter.get_or_create(
        "c1", 100, storage=str(tmp_path)
    )
    assert counter.add.run(5) == 105
    assert counter.add.run(7) == 112
    assert counter.get.run() == 112

    # Reattach from a fresh handle (same process, state from disk).
    again = workflow.get_actor("c1", storage=str(tmp_path))
    assert again.get.run() == 112
    assert again.add.run(1) == 113
    log = again.call_log()
    assert [e["method"] for e in log] == ["add", "add", "add"]
    assert [e["result"] for e in log] == [105, 112, 113]


def test_virtual_actor_readonly_commits_nothing(
    rt_session, tmp_path
):
    rt = rt_session
    from ray_tpu import workflow

    @workflow.virtual_actor
    class Probe:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        @workflow.virtual_actor_readonly
        def peek(self):
            return self.n

    probe = Probe.get_or_create("p1", storage=str(tmp_path))
    probe.bump.run()
    files_before = sorted(os.listdir(tmp_path / "va-p1"))
    for _ in range(3):
        assert probe.peek.run() == 1
    assert sorted(os.listdir(tmp_path / "va-p1")) == files_before
