"""CI gate for the serving bench: `servebench.py --smoke` must run
the FULL engine path — proxy -> router -> replica -> continuous-
batching engine, plus the engine-off baseline — on CPU in about a
minute and emit one well-formed JSON line (same pattern as
test_bench_smoke.py: a broken bench is caught by the suite, not at
measurement time)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# slow: ~90s of serving + jit compiles on a loaded 1-core CI box.
@pytest.mark.slow
@pytest.mark.timeout(560)
def test_servebench_smoke_emits_composite_json(tmp_path):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    out_path = str(tmp_path / "SERVEBENCH.json")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "servebench.py"),
            "--smoke",
            "--out",
            out_path,
        ],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [
        ln for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ][-1]
    out = json.loads(line)
    with open(out_path) as f:
        assert json.load(f) == out  # file matches the stdout line

    assert out["smoke"] is True
    assert out["metric"] == "servebench_tokens_per_s"

    # >= 2 offered-load points, each with the committed percentiles.
    assert len(out["points"]) >= 2
    for point in out["points"]:
        assert point["completed"] > 0
        assert point["tokens_per_s"] > 0
        for stat in ("p50", "p99"):
            assert point["ttft_ms"][stat] > 0
            assert point["per_token_ms"][stat] > 0

    # The top point runs the multi-family mix and the engines served
    # it CONCURRENTLY (occupancy sampled live from /api/serve).
    top = out["points"][-1]
    assert sorted(top["mix"]) == ["tiny-a", "tiny-b"]
    assert top["engine"]["max_slots_used"] >= 2
    assert top["engine"]["max_concurrent_families"] == 2

    # Engine series visible on Prometheus + /api/serve.
    assert out["metrics_visible"]["prometheus_engine_series"] is True
    assert out["metrics_visible"]["api_serve_engine"] is True

    # The serialize-per-request baseline ran at the same loads and
    # continuous batching won on tokens/s at the top load.
    assert len(out["baseline"]) == len(out["points"])
    cmp = out["comparison"]
    assert cmp["engine_tokens_per_s"] > cmp["baseline_tokens_per_s"]
    assert cmp["speedup"] > 1.0

    # Paged-KV + prefix-cache visibility (ISSUE 11): the shared
    # system-prefix workload must actually HIT the prefix cache, and
    # the new series must render on the Prometheus exposition.
    assert out["prefix"]["hits"] > 0
    assert out["prefix"]["tokens_saved"] > 0
    assert out["metrics_visible"]["prometheus_prefix_series"] is True


# slow: ~3 min — the multi-replica pass redeploys at 2 replicas and
# runs the scale loads on top of the single-replica points.
@pytest.mark.slow
@pytest.mark.timeout(560)
def test_servebench_smoke_multi_replica(tmp_path):
    """ISSUE 11 CI satellite: >=2 replicas on CPU through the full
    proxy -> least-outstanding-tokens router -> replica -> paged
    engine path; prefix-hit counter > 0 and the new Prometheus series
    parse (text-format sanity via the repo's own renderer checks)."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    out_path = str(tmp_path / "SERVEBENCH.json")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "servebench.py"),
            "--smoke",
            "--replicas", "2",
            "--no-baseline",
            "--out", out_path,
        ],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [
        ln for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ][-1]
    out = json.loads(line)

    multi = out["multi_replica"]
    assert multi["replicas"] == 2
    assert len(multi["points"]) >= 2
    for point in multi["points"]:
        assert point["completed"] > 0
        assert point["tokens_per_s"] > 0
        assert "shed" in point  # sheds counted per point
    assert multi["scaling"]["multi_replica_peak_rps"] > 0

    # Prefix caching engaged across the run and is exposition-visible.
    assert out["prefix"]["hits"] > 0
    assert out["metrics_visible"]["prometheus_prefix_series"] is True
    assert out["metrics_visible"]["prometheus_engine_series"] is True
