"""End-to-end LLM serving: the Llama decode path behind a Serve
deployment with request batching — the framework's pieces composed the
way a user would (reference story: vLLM-on-Ray; here the in-tree
decoder serves)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_llm_deployment_with_batching(rt_session):
    rt = rt_session
    import ray_tpu.serve as serve

    @serve.deployment
    class LlamaService:
        def __init__(self):
            from ray_tpu.models.llama import LlamaConfig, init_params

            self.cfg = LlamaConfig(
                vocab_size=128,
                dim=64,
                n_layers=2,
                n_heads=4,
                n_kv_heads=4,
                intermediate=128,
                max_seq_len=64,
                dtype=jnp.float32,
                attention="reference",
            )
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.1)
        def complete(self, prompts):
            """prompts: list of token-id lists (equal length); one
            jitted generate serves the whole batch."""
            from ray_tpu.models.generate import generate

            batch = np.asarray(prompts, np.int32)
            lengths = jnp.full((len(prompts),), batch.shape[1], jnp.int32)
            out, out_lengths = generate(
                self.params,
                jnp.asarray(batch),
                lengths,
                self.cfg,
                max_new_tokens=6,
                temperature=0.0,
            )
            return [
                row[:n].tolist()
                for row, n in zip(
                    np.asarray(out), np.asarray(out_lengths)
                )
            ]

    try:
        handle = serve.run(
            LlamaService.bind(), name="llm", route_prefix=None
        )
        prompts = [[1 + i, 7, 12, 5] for i in range(6)]
        responses = [handle.complete.remote(p) for p in prompts]
        results = [r.result(timeout=120) for r in responses]
        assert len(results) == 6
        for tokens in results:
            assert len(tokens) == 6
            assert all(0 <= t < 128 for t in tokens)
        # Determinism: same prompt, same greedy completion.
        again = handle.complete.remote(prompts[0]).result(timeout=120)
        assert again == results[0]
    finally:
        serve.shutdown()


def test_llm_token_streaming(rt_session):
    """Token streaming: an engine actor decodes with generate_stream
    and yields each step through a streaming generator — the consumer
    receives tokens while decoding is still running (reference story:
    streaming chat completions; transport:
    num_returns='streaming' + models/generate.generate_stream)."""
    rt = rt_session

    @rt.remote
    class Engine:
        def __init__(self):
            from ray_tpu.models.llama import LlamaConfig, init_params

            self.cfg = LlamaConfig(
                vocab_size=128, dim=64, n_layers=2, n_heads=4,
                n_kv_heads=4, intermediate=128, max_seq_len=64,
                dtype=jnp.float32, attention="reference",
            )
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        def stream(self, prompt, max_new_tokens):
            from ray_tpu.models.generate import generate_stream

            batch = jnp.asarray([prompt], jnp.int32)
            lengths = jnp.asarray([len(prompt)], jnp.int32)
            for step_tokens in generate_stream(
                self.params, batch, lengths, self.cfg,
                max_new_tokens=max_new_tokens, temperature=0.0,
            ):
                yield int(step_tokens[0])

    engine = Engine.remote()
    gen = engine.stream.options(num_returns="streaming").remote(
        [1, 7, 12, 5], 6
    )
    tokens = [rt.get(r, timeout=60) for r in gen]
    assert len(tokens) == 6
    assert all(0 <= t < 128 for t in tokens)

    # Greedy decode must match the batch (scan) path token-for-token.
    from ray_tpu.models.generate import generate
    from ray_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
        intermediate=128, max_seq_len=64, dtype=jnp.float32,
        attention="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    out, _ = generate(
        params,
        jnp.asarray([[1, 7, 12, 5]], jnp.int32),
        jnp.asarray([4], jnp.int32),
        cfg,
        max_new_tokens=6,
        temperature=0.0,
    )
    assert tokens == np.asarray(out)[0].tolist()


def test_serve_converted_hf_checkpoint(rt_session, tmp_path):
    """The full user story: an HF Llama checkpoint converts, deploys
    behind Serve, and the served greedy tokens are IDENTICAL to
    transformers.generate on the same weights."""
    rt = rt_session
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    import ray_tpu.serve as serve

    torch.manual_seed(9)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        tie_word_embeddings=False, attn_implementation="eager",
    ))
    hf.eval()
    ckpt = str(tmp_path / "tiny_llama")
    hf.save_pretrained(ckpt)

    prompt = np.random.default_rng(9).integers(
        1, 128, (1, 10), dtype=np.int64
    )
    with torch.no_grad():
        expected = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=6,
            do_sample=False, pad_token_id=0, eos_token_id=None,
        )[:, prompt.shape[1]:].numpy().tolist()

    @serve.deployment
    class Checkpoint:
        def __init__(self, path):
            from ray_tpu.models.hf_convert import load_hf_llama

            self.params, self.cfg = load_hf_llama(path)

        def complete(self, tokens):
            from ray_tpu.models.generate import generate

            batch = np.asarray([tokens], np.int32)
            out, _ = generate(
                self.params, jnp.asarray(batch),
                jnp.full((1,), batch.shape[1], jnp.int32),
                self.cfg, max_new_tokens=6, temperature=0.0,
            )
            return np.asarray(out)[0].tolist()

    try:
        handle = serve.run(
            Checkpoint.bind(ckpt), name="hf-llm", route_prefix=None
        )
        served = handle.complete.remote(
            prompt[0].tolist()
        ).result(timeout=120)
        assert [served] == expected
    finally:
        serve.shutdown()
