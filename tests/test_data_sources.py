"""Data source/interop tests: tfrecords, numpy files, pandas/arrow/
torch converters, torch batch iteration.

Reference test model: python/ray/data/tests/ per-datasource round-trip
tests (write -> read -> compare).
"""

import numpy as np
import pytest


def test_tfrecords_round_trip(rt_session, tmp_path):
    from ray_tpu import data

    ds = data.from_items(
        [
            {"idx": i, "score": float(i) / 2, "tag": f"row-{i}"}
            for i in range(10)
        ]
    )
    ds.write_tfrecords(str(tmp_path / "tfr"))
    back = data.read_tfrecords(str(tmp_path / "tfr"))
    rows = sorted(back.take_all(), key=lambda r: r["idx"])
    assert len(rows) == 10
    assert rows[3]["idx"] == 3
    assert abs(rows[3]["score"] - 1.5) < 1e-6
    assert rows[3]["tag"] == "row-3"


def test_tfrecords_array_columns_round_trip(rt_session, tmp_path):
    """Array columns (the TPU input-pipeline case) flatten into
    feature lists and read back (shape restored by consumer)."""
    from ray_tpu import data

    ds = data.from_items(
        [
            {
                "vec": np.arange(4, dtype=np.float32) + i,
                "mask": np.array([True, False]),
                "idx": np.int64(i),
            }
            for i in range(3)
        ]
    )
    ds.write_tfrecords(str(tmp_path / "arr"))
    rows = sorted(
        data.read_tfrecords(str(tmp_path / "arr")).take_all(),
        key=lambda r: r["idx"],
    )
    assert rows[1]["vec"] == [1.0, 2.0, 3.0, 4.0]
    assert rows[1]["mask"] == [1, 0]
    assert rows[2]["idx"] == 2


def test_tfrecords_corruption_detected(rt_session, tmp_path):
    from ray_tpu import data
    from ray_tpu.data.tfrecords import encode_example, write_records

    path = tmp_path / "bad.tfrecord"
    write_records(
        str(path), [encode_example({"a": 1}), encode_example({"a": 2})]
    )
    raw = bytearray(path.read_bytes())
    raw[-6] ^= 0xFF  # flip a payload byte of the last record
    path.write_bytes(bytes(raw))
    with pytest.raises(Exception, match="crc|corrupt"):
        data.read_tfrecords(str(path)).take_all()


def test_read_numpy_npy_and_npz(rt_session, tmp_path):
    from ray_tpu import data

    np.save(tmp_path / "a.npy", np.arange(12).reshape(6, 2))
    ds = data.read_numpy(str(tmp_path / "a.npy"))
    rows = ds.take_all()
    assert len(rows) == 6
    assert rows[2]["data"].tolist() == [4, 5]

    np.savez(
        tmp_path / "b.npz",
        x=np.arange(4),
        y=np.arange(4) * 10.0,
    )
    rows = data.read_numpy(str(tmp_path / "b.npz")).take_all()
    assert len(rows) == 4
    assert rows[1]["x"] == 1 and rows[1]["y"] == 10.0


def test_write_numpy(rt_session, tmp_path):
    from ray_tpu import data

    ds = data.from_items([{"data": [i, i + 1]} for i in range(5)])
    ds.write_numpy(str(tmp_path / "out"), column="data")
    back = data.read_numpy(
        str(tmp_path / "out") + "/*.npy"
    ).take_all()
    assert sorted(r["data"].tolist() for r in back) == [
        [i, i + 1] for i in range(5)
    ]


def test_pandas_round_trip(rt_session):
    import pandas as pd

    from ray_tpu import data

    df = pd.DataFrame(
        {"a": [1, 2, 3], "b": ["x", "y", "z"]}
    )
    ds = data.from_pandas(df)
    assert ds.count() == 3
    out = ds.map(lambda r: {**r, "a": r["a"] * 2}).to_pandas()
    assert out.sort_values("a")["a"].tolist() == [2, 4, 6]
    assert set(out.columns) == {"a", "b"}


def test_arrow_round_trip(rt_session):
    import pyarrow as pa

    from ray_tpu import data

    table = pa.table({"k": [1, 2], "v": [0.5, 1.5]})
    ds = data.from_arrow(table)
    back = ds.to_arrow()
    assert back.num_rows == 2
    assert back.column("v").to_pylist() == [0.5, 1.5]


def test_from_torch_and_iter_torch_batches(rt_session):
    import torch
    from torch.utils.data import Dataset as TorchDataset

    from ray_tpu import data

    class Squares(TorchDataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return i * i

    ds = data.from_torch(Squares())
    assert sorted(r["item"] for r in ds.take_all()) == [
        i * i for i in range(8)
    ]

    ds2 = data.from_items([{"x": i, "y": 2 * i} for i in range(10)])
    batches = list(
        ds2.iter_torch_batches(batch_size=4, dtypes=torch.float32)
    )
    assert [len(b["x"]) for b in batches] == [4, 4, 2]
    assert batches[0]["x"].dtype == torch.float32
    total = torch.cat([b["y"] for b in batches]).sum().item()
    assert total == sum(2 * i for i in range(10))


def test_from_huggingface_shape(rt_session):
    """Any __len__/__getitem__->dict source works (the HF map-style
    surface) without the datasets package installed."""

    from ray_tpu import data

    class FakeHF:
        def __len__(self):
            return 5

        def __getitem__(self, i):
            return {"text": f"doc {i}", "label": i % 2}

    rows = data.from_huggingface(FakeHF()).take_all()
    assert len(rows) == 5
    assert {r["label"] for r in rows} == {0, 1}
