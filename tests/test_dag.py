"""DAG tests (reference test model: python/ray/dag/tests/ —
interpreted bind/execute graphs and compiled actor pipelines over
channels)."""

import time

import pytest


def test_interpreted_task_dag(rt_session):
    rt = rt_session

    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def add(a, b):
        return a + b

    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), double.bind(inp))
    assert rt.get(dag.execute(3), timeout=20) == 12
    assert rt.get(dag.execute(5), timeout=20) == 20


def test_interpreted_actor_dag(rt_session):
    rt = rt_session

    @rt.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    acc = Acc.remote()
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = acc.add.bind(inp)
    assert rt.get(dag.execute(2), timeout=20) == 2
    assert rt.get(dag.execute(3), timeout=20) == 5


def test_shm_channel_roundtrip():
    from ray_tpu.dag.channels import ShmChannel

    chan = ShmChannel(1 << 16)
    try:
        chan.put(("v", [1, 2, 3]))
        chan.put(("v", "x" * 30000))  # forces wraparound next
        assert chan.get(timeout=1) == ("v", [1, 2, 3])
        chan.put(("v", "y" * 30000))
        assert chan.get(timeout=1)[1] == "x" * 30000
        assert chan.get(timeout=1)[1] == "y" * 30000
        with pytest.raises(ValueError):
            chan.put_bytes(b"z" * (1 << 17))
    finally:
        chan.close()
        chan.unlink()


def test_compiled_two_stage_pipeline(rt_session):
    rt = rt_session

    @rt.remote
    class Stage:
        def __init__(self, scale):
            self.scale = scale

        def apply(self, x):
            return x * self.scale

    a = Stage.remote(2)
    b = Stage.remote(10)
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = b.apply.bind(a.apply.bind(inp))
    compiled = dag.experimental_compile()
    try:
        # Pipelined executes: submit all, then collect.
        refs = [compiled.execute(i) for i in range(10)]
        assert [r.get(timeout=30) for r in refs] == [
            i * 20 for i in range(10)
        ]
    finally:
        compiled.teardown()
    # The actors are usable again after teardown.
    assert rt.get(a.apply.remote(7), timeout=20) == 14


def test_compiled_multi_output(rt_session):
    rt = rt_session

    @rt.remote
    class Worker:
        def __init__(self, k):
            self.k = k

        def mul(self, x):
            return x * self.k

    w1, w2 = Worker.remote(3), Worker.remote(5)
    from ray_tpu.dag import InputNode, MultiOutputNode, experimental_compile

    with InputNode() as inp:
        dag = MultiOutputNode([w1.mul.bind(inp), w2.mul.bind(inp)])
    compiled = experimental_compile(dag)
    try:
        assert compiled.execute(2).get(timeout=30) == [6, 10]
        assert compiled.execute(4).get(timeout=30) == [12, 20]
    finally:
        compiled.teardown()


def test_compiled_error_propagates(rt_session):
    rt = rt_session

    @rt.remote
    class Flaky:
        def run(self, x):
            if x == 13:
                raise ValueError("unlucky")
            return x + 1

    @rt.remote
    class Downstream:
        def run(self, x):
            return x * 2

    f, d = Flaky.remote(), Downstream.remote()
    from ray_tpu.dag import InputNode, experimental_compile

    with InputNode() as inp:
        dag = d.run.bind(f.run.bind(inp))
    compiled = experimental_compile(dag)
    try:
        assert compiled.execute(1).get(timeout=30) == 4
        with pytest.raises(ValueError, match="unlucky"):
            compiled.execute(13).get(timeout=30)
        # The pipeline keeps working after an error.
        assert compiled.execute(2).get(timeout=30) == 6
    finally:
        compiled.teardown()


def test_compiled_throughput_beats_rpc(rt_session):
    """The point of compiling: channel hops are much cheaper than
    scheduler round-trips (reference: aDAG motivation)."""
    rt = rt_session

    @rt.remote
    class Echo:
        def hit(self, x):
            return x

    e = Echo.remote()
    rt.get(e.hit.remote(0), timeout=20)  # warm the worker
    n = 200

    def time_rpc():
        start = time.perf_counter()
        for i in range(n):
            rt.get(e.hit.remote(i), timeout=20)
        return time.perf_counter() - start

    # Two measurements, best-of, to shrug off CI timing noise.
    rpc_time = min(time_rpc(), time_rpc())

    from ray_tpu.dag import InputNode, experimental_compile

    with InputNode() as inp:
        dag = e.hit.bind(inp)
    compiled = experimental_compile(dag)
    try:

        def time_compiled():
            start = time.perf_counter()
            for i in range(n):
                compiled.execute(i).get(timeout=30)
            return time.perf_counter() - start

        compiled.execute(0).get(timeout=30)  # warm the loop
        compiled_time = min(time_compiled(), time_compiled())
    finally:
        compiled.teardown()
    # Generous margin: this is a correctness guard that the compiled
    # path isn't catastrophically slower than RPC, not a benchmark —
    # zero-margin timing assertions flake on loaded CI hosts.
    assert compiled_time < 2.0 * rpc_time


def test_compiled_cross_node_pipeline():
    """A compiled pipeline whose stages live on DIFFERENT nodes: the
    stage-to-stage edges must ride TCP channels (KV rendezvous), not
    same-host shm rings (reference: node_manager.proto:467-469 pushes
    mutable objects to the reader's node)."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.dag.tcp_channel import TcpChannel
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    try:
        cluster.add_node(num_cpus=2)
        rt.init(address=cluster.address)
        cluster.wait_for_nodes(2)
        nodes = sorted(n["node_id"] for n in rt.nodes())

        @rt.remote
        class Stage:
            def __init__(self, scale):
                self.scale = scale

            def apply(self, x):
                return x * self.scale

        a = Stage.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nodes[0]
            )
        ).remote(3)
        b = Stage.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nodes[1]
            )
        ).remote(7)
        with InputNode() as inp:
            dag = b.apply.bind(a.apply.bind(inp))
        compiled = dag.experimental_compile()
        try:
            # The a->b edge crosses nodes; at least one channel must be
            # a TcpChannel (driver-adjacent edges depend on which node
            # hosts the driver).
            assert any(
                isinstance(c, TcpChannel) for c in compiled._all_channels
            )
            refs = [compiled.execute(i) for i in range(6)]
            assert [r.get(timeout=60) for r in refs] == [
                i * 21 for i in range(6)
            ]
        finally:
            compiled.teardown()
        # Actors return to normal RPC service afterwards.
        assert rt.get(a.apply.remote(5), timeout=20) == 15
    finally:
        try:
            rt.shutdown()
        finally:
            cluster.shutdown()


def test_compiled_cross_node_teardown_without_get():
    """teardown() before any ref.get() must not wedge a cross-node
    stage: the stage's unbounded result put() can only complete if the
    driver's reader address was published at compile time (the driver
    binds eagerly; TCP's listen backlog absorbs the record)."""
    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    try:
        cluster.add_node(num_cpus=2)
        rt.init(address=cluster.address)
        cluster.wait_for_nodes(2)
        nodes = sorted(n["node_id"] for n in rt.nodes())

        @rt.remote
        class Stage:
            def apply(self, x):
                return x + 1

        a = Stage.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nodes[0]
            )
        ).remote()
        b = Stage.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nodes[1]
            )
        ).remote()
        with InputNode() as inp:
            dag = b.apply.bind(a.apply.bind(inp))
        compiled = dag.experimental_compile()
        compiled.execute(1)  # never read
        compiled.teardown()
        # The deadlock symptom was actors never returning to RPC
        # service (exec loop stuck in rendezvous-poll forever).
        assert rt.get(a.apply.remote(5), timeout=20) == 6
        assert rt.get(b.apply.remote(5), timeout=20) == 6
    finally:
        try:
            rt.shutdown()
        finally:
            cluster.shutdown()


def test_input_attribute_nodes(rt_session):
    """`inp["x"]` / `inp[0]` projections of the runtime input
    (reference: InputAttributeNode) work in BOTH execution modes:
    interpreted task DAGs and compiled actor pipelines (the driver
    writes each input channel its projected field)."""
    rt = rt_session
    from ray_tpu.dag import InputNode

    # Interpreted: two tasks each consume a different field.
    @rt.remote
    def double(x):
        return x * 2

    @rt.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(double.bind(inp["a"]), inp["b"])
    assert rt.get(dag.execute({"a": 3, "b": 10}), timeout=30) == 16

    # Compiled: projections feed different actor stages.
    @rt.remote
    class Scale:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x * self.k

    @rt.remote
    class Sum:
        def add(self, a, b):
            return a + b

    s = Scale.remote(10)
    t = Sum.remote()
    with InputNode() as inp:
        cdag = t.add.bind(s.apply.bind(inp[0]), inp[1])
    compiled = cdag.experimental_compile()
    try:
        assert compiled.execute((2, 5)).get(timeout=30) == 25
        assert compiled.execute((3, 1)).get(timeout=30) == 31
        # A missing key fails the execute up front, not mid-pipeline.
        import pytest as _pytest

        with _pytest.raises(IndexError):
            compiled.execute((7,))
    finally:
        compiled.teardown()
