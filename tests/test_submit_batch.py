"""Batched + pipelined task submission (control-plane raw speed PR).

Covers the three semantic guarantees the batch plane must keep
invisible to callers:

* ordering preserved per driver (FIFO through the coalescing queue),
* per-spec error isolation inside a failed batch (one bad task fails
  alone; the batch envelope is transport, not semantics),
* exactly-once under `RT_testing_rpc_failure` chaos injection (a
  dropped batch frame retries without re-executing anything), plus
  head-side task_id dedup for retried `submit_tasks` frames.

Also: the flat spec codec round trip, the daemon-path submit pipeline
(`use_direct_calls=False`), the batched worker arg-fetch, and the
`task_submit_batching=False` kill switch.
"""

import os
import time

import pytest

from ray_tpu._private import wire

# ---------------------------------------------------------------------------
# flat spec codec (no cluster)
# ---------------------------------------------------------------------------


def _spec(**over):
    spec = {
        "task_id": b"T" * 16,
        "job_id": b"J" * 4,
        "kind": "normal",
        "name": "nop",
        "function_key": "fn:abc123",
        "args": [("inline", b"x" * 40), ("ref", b"R" * 20)],
        "returns": [b"R" * 20],
        "resources": {"CPU": 1.0},
        "max_retries": 0,
    }
    spec.update(over)
    return spec


def test_codec_hot_roundtrip():
    spec = _spec()
    assert wire.decode_spec(wire.encode_spec(spec)) == spec


def test_codec_cold_fields_and_edge_values():
    spec = _spec(
        kind="actor_creation",
        max_retries=-1,  # infinite-retry sentinel must survive
        ns_ctx="myns",
        scheduling_strategy={"type": "SPREAD"},
        handle_meta=None,
        release_creation_resources=True,
        max_concurrency=4,
        concurrency_groups={"io": 2},
        _retries_left=2,
    )
    assert wire.decode_spec(wire.encode_spec(spec)) == spec


def test_codec_empty_args_returns_resources():
    spec = _spec(args=[], returns=[], resources={}, name="")
    assert wire.decode_spec(wire.encode_spec(spec)) == spec


def test_codec_batch_roundtrip_and_split():
    specs = [_spec(task_id=bytes([i]) * 16) for i in range(7)]
    frame = wire.encode_spec_batch(wire.encode_spec(s) for s in specs)
    assert wire.decode_spec_batch(frame) == specs
    blobs = wire.split_spec_batch(frame)
    assert len(blobs) == 7
    assert wire.decode_spec(blobs[3]) == specs[3]


def test_codec_rejects_garbage():
    with pytest.raises(wire.SpecCodecError):
        wire.decode_spec(b"\x00" * 40)  # wrong magic
    with pytest.raises(wire.SpecCodecError):
        wire.decode_spec(b"")
    with pytest.raises(wire.SpecCodecError):
        wire.split_spec_batch(b"\xff\xff\xff\xff trailing")


def test_codec_field_table_is_append_only_prefix():
    """The field-id table is wire format: the hot fields must keep
    their positions (ids are indexes into SPEC_FIELDS)."""
    assert wire.SPEC_FIELDS[:9] == [
        "task_id", "job_id", "kind", "name", "function_key", "args",
        "returns", "resources", "max_retries",
    ]


# ---------------------------------------------------------------------------
# batch semantics on a live session (direct transport, batching on)
# ---------------------------------------------------------------------------


def test_flood_coalesces_into_batches(rt_session):
    """A tight submit loop must actually ride multi-spec frames (the
    hysteresis engages), and every result must come back correct."""
    rt = rt_session
    from ray_tpu._private.worker import global_worker

    @rt.remote
    def echo(i):
        return i

    assert rt.get(echo.remote(-1), timeout=60) == -1
    import ray_tpu._private.direct as direct

    sizes = []
    orig = direct.DirectTaskManager._send_batch

    def spy(self, key, ks, lease, batch):
        sizes.append(len(batch))
        return orig(self, key, ks, lease, batch)

    direct.DirectTaskManager._send_batch = spy
    try:
        refs = [echo.remote(i) for i in range(1500)]
        got = rt.get(refs, timeout=120)
    finally:
        direct.DirectTaskManager._send_batch = orig
    assert got == list(range(1500))
    assert max(sizes) > 10, f"no multi-spec frames formed: {sizes[:20]}"
    # far fewer frames than tasks — the wire round trip is amortized
    assert sum(sizes) >= 1500 and len(sizes) < 1500 / 2
    assert global_worker()._direct is not None


def test_submission_order_preserved_single_worker():
    """FIFO per driver: with one worker, execution order must equal
    submission order even when specs flow through queue + batches."""
    import ray_tpu as rt

    rt.init(num_cpus=1)
    try:
        @rt.remote
        def stamp(i):
            global _exec_seq  # worker-process-global execution counter
            try:
                _exec_seq += 1
            except NameError:
                _exec_seq = 0
            return (i, _exec_seq)

        warm = rt.get(stamp.remote(-1), timeout=60)
        refs = [stamp.remote(i) for i in range(400)]
        got = rt.get(refs, timeout=120)
        order = [seq for _i, seq in got]
        assert order == sorted(order), "batching reordered execution"
        assert [i for i, _seq in got] == list(range(400))
        assert warm[0] == -1
    finally:
        rt.shutdown()


def test_per_spec_error_isolation_in_batches(rt_session):
    rt = rt_session

    @rt.remote
    def ok(i):
        return i

    @rt.remote
    def boom(i):
        raise ValueError(f"boom-{i}")

    rt.get(ok.remote(0), timeout=60)
    refs = [
        boom.remote(i) if i % 7 == 0 else ok.remote(i)
        for i in range(200)
    ]
    failures = 0
    for i, ref in enumerate(refs):
        if i % 7 == 0:
            with pytest.raises(ValueError, match=f"boom-{i}"):
                rt.get(ref, timeout=60)
            failures += 1
        else:
            assert rt.get(ref, timeout=60) == i
    assert failures == len(range(0, 200, 7))


def test_exactly_once_under_execute_tasks_chaos(tmp_path):
    """Chaos-drop the first execute_tasks frames: the batch retries on
    a fresh lease and every task still executes EXACTLY once (the drop
    happens before any bytes hit the wire)."""
    import ray_tpu as rt
    from ray_tpu._private.rpc import configure_chaos

    rt.init(num_cpus=2)
    try:
        marker_dir = str(tmp_path)

        @rt.remote
        def touch(i):
            # O_APPEND on distinct files: double execution would
            # leave a second line behind.
            with open(os.path.join(marker_dir, f"{i}.txt"), "a") as f:
                f.write("x\n")
            return i

        assert rt.get(touch.remote(999), timeout=60) == 999
        configure_chaos("execute_tasks=2")
        try:
            refs = [touch.remote(i) for i in range(60)]
            got = rt.get(refs, timeout=120)
        finally:
            configure_chaos("")
        assert got == list(range(60))
        for i in range(60):
            with open(os.path.join(marker_dir, f"{i}.txt")) as f:
                lines = f.readlines()
            assert len(lines) == 1, f"task {i} executed {len(lines)}x"
    finally:
        rt.shutdown()


def test_head_dedups_retried_submit_tasks_batches(rt_session):
    """submit_tasks ingestion is idempotent by task_id: re-sending the
    same batch (a driver-side transport retry) must not double-ingest
    or double-execute."""
    rt = rt_session
    from ray_tpu._private.worker import global_worker

    w = global_worker()

    def counter_fn():
        return "ran"

    func_key = w.functions.export(counter_fn)
    task_id = os.urandom(16)
    ret = task_id + (1).to_bytes(4, "big")
    spec = {
        "task_id": task_id,
        "job_id": w.job_id.binary(),
        "kind": "normal",
        "name": "dedup_probe",
        "function_key": func_key,
        "args": [],
        "returns": [ret],
        "resources": {"CPU": 1.0},
        "max_retries": 0,
    }
    payload = wire.encode_spec_batch([wire.encode_spec(spec)])
    r1 = w.call("submit_tasks", specs=payload, count=1)
    r2 = w.call("submit_tasks", specs=payload, count=1)  # "retry"
    assert r1["accepted"] == 1
    assert r2["accepted"] == 0
    reply = w.call("get_object", oid=ret, timeout=60.0)
    assert reply.get("inline") is not None
    assert w.serialization.deserialize(reply["inline"]) == "ran"


def test_submit_tasks_per_spec_decode_errors(rt_session):
    """One malformed blob inside a batch fails alone: the other spec
    is ingested and runs."""
    rt = rt_session
    from ray_tpu._private.worker import global_worker

    w = global_worker()

    def fine():
        return 7

    func_key = w.functions.export(fine)
    task_id = os.urandom(16)
    ret = task_id + (1).to_bytes(4, "big")
    good = wire.encode_spec({
        "task_id": task_id,
        "job_id": w.job_id.binary(),
        "kind": "normal",
        "name": "fine",
        "function_key": func_key,
        "args": [],
        "returns": [ret],
        "resources": {"CPU": 1.0},
        "max_retries": 0,
    })
    bad = b"\x00garbage-not-a-spec"
    payload = wire.encode_spec_batch([bad, good])
    reply = w.call("submit_tasks", specs=payload, count=2)
    assert reply["accepted"] == 1
    assert 0 in {int(k) for k in reply["errors"]}
    got = w.call("get_object", oid=ret, timeout=60.0)
    assert w.serialization.deserialize(got["inline"]) == 7


# ---------------------------------------------------------------------------
# daemon-path pipeline (direct transport off) + kill switch
# ---------------------------------------------------------------------------


def test_daemon_path_pipeline_and_chaos_exactly_once(tmp_path):
    """use_direct_calls=False: submissions ride the SubmitPipeline's
    submit_tasks batches. With chaos dropping the first frame, the
    whole-batch retry + head dedup keep execution exactly-once."""
    import ray_tpu as rt
    from ray_tpu._private.rpc import configure_chaos

    rt.init(num_cpus=2, _system_config={"use_direct_calls": False})
    try:
        from ray_tpu._private.worker import global_worker

        marker_dir = str(tmp_path)

        @rt.remote
        def touch(i):
            with open(os.path.join(marker_dir, f"{i}.txt"), "a") as f:
                f.write("x\n")
            return i

        w = global_worker()
        assert w._direct is None
        assert w._submit_pipeline is not None
        assert rt.get(touch.remote(999), timeout=60) == 999
        configure_chaos("submit_tasks=1")
        try:
            refs = [touch.remote(i) for i in range(40)]
            got = rt.get(refs, timeout=120)
        finally:
            configure_chaos("")
        assert got == list(range(40))
        for i in range(40):
            with open(os.path.join(marker_dir, f"{i}.txt")) as f:
                assert len(f.readlines()) == 1
    finally:
        rt.shutdown()


def test_kill_switch_reverts_to_per_task_rpcs():
    """task_submit_batching=False restores the per-task wire shape on
    both paths; everything still works."""
    import ray_tpu as rt

    rt.init(num_cpus=2, _system_config={"task_submit_batching": False})
    try:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        assert w._submit_pipeline is None
        assert w._direct is not None and not w._direct._batching

        @rt.remote
        def echo(i):
            return i

        refs = [echo.remote(i) for i in range(100)]
        assert rt.get(refs, timeout=120) == list(range(100))

        @rt.remote
        def boom():
            raise RuntimeError("legacy boom")

        with pytest.raises(RuntimeError, match="legacy boom"):
            rt.get(boom.remote(), timeout=60)
    finally:
        rt.shutdown()


# ---------------------------------------------------------------------------
# batched arg fetch (args_10k satellite) + get_objects
# ---------------------------------------------------------------------------


def test_many_ref_args_resolve_batched(rt_session):
    rt = rt_session

    @rt.remote
    def many_args(*args):
        return sum(args)

    refs = [rt.put(i) for i in range(1000)]
    t0 = time.perf_counter()
    assert rt.get(many_args.remote(*refs), timeout=120) == sum(range(1000))
    elapsed = time.perf_counter() - t0
    # per-arg round trips made this ~150 ms/1k args; the batched
    # get_objects fetch should be far under the old regime even on a
    # loaded box. Generous bound: this is a smoke guard, not a bench.
    assert elapsed < 30.0


def test_duplicate_ref_args_stay_independent(rt_session):
    """The batched arg fetch dedups the RPC per unique oid but must
    deserialize once per arg position: mutating one arg in place must
    not be visible through a duplicate of the same ref."""
    rt = rt_session

    @rt.remote
    def mutate(a, b, c):
        a.append(99)
        return len(a), len(b)

    r = rt.put([1, 2])
    r2 = rt.put([3])
    assert tuple(rt.get(mutate.remote(r, r, r2), timeout=60)) == (3, 2)


def test_get_objects_batch_handler(rt_session):
    rt = rt_session
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    known = rt.put("hello")
    w.ensure_globally_visible(known.id())
    missing = os.urandom(20)
    reply = w.call(
        "get_objects", oids=[known.binary(), missing]
    )
    results = reply["results"]
    assert len(results) == 2
    assert w.serialization.deserialize(results[0]["inline"]) == "hello"
    assert results[1] == {"pending": True}
