"""Autoscaler tests (reference test model: autoscaler tests with
FakeMultiNodeProvider + AutoscalingCluster — scale up on demand, honor
min/max, scale down when idle)."""

import time

import pytest


@pytest.fixture
def scaling_cluster():
    import ray_tpu as rt
    from ray_tpu.autoscaler import AutoscalingCluster

    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_types={
            "cpu-worker": {
                "resources": {"CPU": 2.0, "memory": float(2**30)},
                "min_workers": 0,
                "max_workers": 3,
            },
        },
        idle_timeout_s=2.0,
    )
    cluster.start()
    rt.init(address=cluster.address)
    yield rt, cluster
    rt.shutdown()
    cluster.shutdown()


def test_scales_up_for_infeasible_task_then_down(scaling_cluster):
    rt, cluster = scaling_cluster
    assert cluster.num_workers() == 0

    # Needs 2 CPUs; the 1-CPU head can't run it.
    @rt.remote(num_cpus=2)
    def heavy():
        return "ran"

    ref = heavy.remote()
    assert rt.get(ref, timeout=60) == "ran"
    assert cluster.num_workers() >= 1

    # Idle workers terminate after idle_timeout (min_workers=0).
    deadline = time.time() + 30
    while time.time() < deadline and cluster.num_workers() > 0:
        time.sleep(0.3)
    assert cluster.num_workers() == 0


def test_scales_up_for_placement_group(scaling_cluster):
    rt, cluster = scaling_cluster
    from ray_tpu.util import placement_group

    pg = placement_group(
        [{"CPU": 2.0}, {"CPU": 2.0}], strategy="STRICT_SPREAD"
    )
    # Generous: worker spawn + 2PC on a 1-core box mid-suite can
    # take minutes under load (flaked at 60s in a full-suite run).
    assert pg.wait(150)
    assert cluster.num_workers() >= 2


def test_respects_max_workers(scaling_cluster):
    rt, cluster = scaling_cluster

    @rt.remote(num_cpus=2)
    def hold():
        import time as _t

        _t.sleep(3)
        return 1

    refs = [hold.remote() for _ in range(10)]
    deadline = time.time() + 20
    peak = 0
    while time.time() < deadline:
        peak = max(peak, cluster.num_workers())
        time.sleep(0.2)
        if peak >= 3:
            break
    assert peak <= 3
    rt.get(refs, timeout=120)


def test_gcp_tpu_client_against_fake_service():
    """GcpTpuClient speaks the TPU v2 REST surface (reference:
    gcp/node.py:629 GCPTPU): create returns a long-running operation,
    polling completes it, the node lists READY with one
    networkEndpoint per slice host, delete removes it."""
    from ray_tpu.autoscaler.gcp import FakeGcpTpuService, GcpTpuClient
    from ray_tpu.autoscaler.gcp.api import GcpApiError

    service = FakeGcpTpuService(ready_delay_s=0.01)
    client = GcpTpuClient(
        "proj", "fake-zone-a", transport=service, poll_interval_s=0.01
    )
    op = client.create_node(
        "my-slice-tpu",
        {
            "acceleratorType": "v5litepod-16",
            "runtimeVersion": "tpu-ubuntu2204-base",
            "labels": {"rt-cluster-name": "c"},
            "metadata": {"rt-slice-hosts": "4"},
        },
    )
    assert not op.get("done")
    done = client.wait_for_operation(op, timeout_s=10)
    assert done["done"] and "error" not in done

    nodes = client.list_nodes()
    assert len(nodes) == 1
    node = nodes[0]
    assert node["state"] == "READY"
    assert len(node["networkEndpoints"]) == 4  # one per slice host
    assert client.get_node(node["name"])["state"] == "READY"

    client.delete_node(node["name"])
    assert client.list_nodes() == []
    with pytest.raises(GcpApiError):
        client.get_node(node["name"])


def test_slice_pg_scales_up_one_tpu_node_then_down():
    """The slice-granular TPU scale-up path end-to-end (reference:
    gcp/node_provider.py + node.py GCPNodeType.TPU): one pending
    slice_placement_group drives ONE tpu-v5e-16 node request through
    the fake TPU API; its 4 host daemons join with pod-head + pod-name
    resources; the gang schedules; after release the whole slice (and
    only the slice, never a partial host set) scales down on idle."""
    import ray_tpu as rt
    from ray_tpu.autoscaler import TpuAutoscalingCluster
    from ray_tpu.util.accelerators.tpu import slice_placement_group
    from ray_tpu.util.placement_group import remove_placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    cluster = TpuAutoscalingCluster(
        head_resources={"CPU": 1.0},
        tpu_node_types={
            "tpu-v5e-16": {
                "pod_type": "v5e-16",
                "accelerator_type": "v5litepod-16",
                "max_workers": 2,
                "host_cpus": 2.0,
            },
        },
        idle_timeout_s=2.0,
    )
    cluster.start()
    try:
        rt.init(address=cluster.address)
        assert cluster.num_slices() == 0

        pg = slice_placement_group("v5e-16")
        assert pg.wait(180), "slice gang never scheduled"

        # Slice granularity: the 4-bundle STRICT_SPREAD gang launched
        # exactly ONE provider node (not 4), with 4 host daemons.
        assert cluster.num_slices() == 1
        # Filter by label, not by the TPU resource: a committed bundle
        # rewrites the host's TPU into PG-group-scoped keys.
        tpu_hosts = [
            n
            for n in rt.nodes()
            if n.get("alive")
            and n["labels"].get("rt.io/tpu-pod-type") == "v5e-16"
        ]
        assert len(tpu_hosts) == 4
        # Host 0 carries the slice-head marker; every host carries the
        # pod-name resource (accelerators/tpu.py, reference tpu.py:334).
        heads = [
            n
            for n in tpu_hosts
            if "TPU-v5e-16-head" in n["resources"]
        ]
        assert len(heads) == 1
        provider_nodes = {
            n["labels"].get("rt.io/provider-node") for n in tpu_hosts
        }
        assert len(provider_nodes) == 1
        pod_name = provider_nodes.pop()
        assert all(
            n["resources"].get(pod_name) == 1.0 for n in tpu_hosts
        )

        # The gang is actually usable: one task per bundle, spread
        # across distinct hosts.
        # num_cpus=0: the bundle holds only the host's chip set, so
        # the gang task must not ask the bundle for CPU too.
        @rt.remote(num_tpus=4, num_cpus=0)
        def host_id():
            return rt.get_runtime_context().get_node_id()

        refs = [
            host_id.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=i
                )
            ).remote()
            for i in range(4)
        ]
        assert len(set(rt.get(refs, timeout=60))) == 4

        # Release the gang: the slice idles out and terminates as one
        # unit through the fake TPU API delete.
        remove_placement_group(pg)
        deadline = time.time() + 45
        while time.time() < deadline and cluster.num_slices() > 0:
            time.sleep(0.3)
        assert cluster.num_slices() == 0
        rt.shutdown()
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def test_min_workers_floor():
    import ray_tpu as rt
    from ray_tpu.autoscaler import AutoscalingCluster

    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_types={
            "base": {
                "resources": {"CPU": 1.0, "memory": float(2**30)},
                "min_workers": 2,
                "max_workers": 4,
            },
        },
    )
    cluster.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline and cluster.num_workers() < 2:
            time.sleep(0.2)
        assert cluster.num_workers() >= 2
        rt.init(address=cluster.address)
        rt.shutdown()
    finally:
        cluster.shutdown()


def test_request_resources_scales_up_holds_then_releases(scaling_cluster):
    """Programmatic capacity target (reference: autoscaler sdk
    request_resources): scaling happens WITHOUT any queued work, the
    satisfying nodes are held against idle scale-down while the
    target stands, and clearing the target releases them."""
    rt, cluster = scaling_cluster
    from ray_tpu.autoscaler import request_resources

    assert cluster.num_workers() == 0
    # 4 one-CPU bundles: head holds 1, so at least 2 x 2-CPU workers
    # must come up — with zero tasks submitted.
    count = request_resources(num_cpus=4)
    assert count == 4
    deadline = time.time() + 60
    while time.time() < deadline and cluster.num_workers() < 2:
        time.sleep(0.3)
    assert cluster.num_workers() >= 2

    # Held: idle_timeout_s=2.0 must NOT scale these down while the
    # target stands.
    time.sleep(5.0)
    assert cluster.num_workers() >= 2

    # Clearing the target releases the nodes.
    assert request_resources(bundles=[]) == 0
    deadline = time.time() + 30
    while time.time() < deadline and cluster.num_workers() > 0:
        time.sleep(0.3)
    assert cluster.num_workers() == 0


# ---------------------------------------------------------------------------
# regression: partially-joined slices still count as launching capacity
# ---------------------------------------------------------------------------


class _FakeSliceProvider:
    """One 4-host slice node type; records create_node calls."""

    head_address = "unused"

    def __init__(self, nodes=()):
        self.nodes = list(nodes)
        self.created = []

    def non_terminated_nodes(self):
        return list(self.nodes)

    def node_type(self, p):
        return "tpu-slice"

    def cluster_node_id(self, p):
        return None

    def create_node(self, node_type, resources, labels):
        name = f"slice-{len(self.created)}"
        self.created.append(name)
        self.nodes.append(name)
        return name

    def terminate_node(self, p):
        self.nodes.remove(p)


def _slice_autoscaler(provider):
    from ray_tpu.autoscaler.autoscaler import (
        NodeTypeConfig,
        StandardAutoscaler,
    )

    return StandardAutoscaler(
        provider,
        {
            "tpu-slice": NodeTypeConfig(
                resources={"CPU": 2.0, "TPU": 4.0},
                max_workers=4,
                slice_hosts=4,
            )
        },
        idle_timeout_s=999.0,
    )


def _gang_load(joined_hosts):
    """A pending 4-bundle STRICT_SPREAD gang + `joined_hosts` daemons
    of provider node slice-0 already registered (mid-boot)."""
    nodes = [
        {
            "node_id": "head",
            "available": {"CPU": 1.0},
            "total": {"CPU": 1.0},
            "queued": 0,
            "labels": {},
        }
    ]
    for i in range(joined_hosts):
        nodes.append(
            {
                "node_id": f"d{i}",
                "available": {"CPU": 2.0, "TPU": 4.0},
                "total": {"CPU": 2.0, "TPU": 4.0},
                "queued": 0,
                "labels": {"rt.io/provider-node": "slice-0"},
            }
        )
    return {
        "infeasible": [],
        "pending_placement_groups": [
            {
                "strategy": "STRICT_SPREAD",
                "bundles": [{"TPU": 4.0}] * 4,
            }
        ],
        "nodes": nodes,
        "resource_requests": [],
    }


@pytest.mark.parametrize("joined", [0, 1, 2, 3])
def test_partially_joined_slice_is_not_relaunched(joined):
    """The double-launch bug: while a 4-host slice boots, each
    reconcile tick sees SOME daemons joined and — if the remaining
    hosts aren't counted as launching capacity — launches another
    whole slice for the gang's unplaced remainder. Any join state of
    an already-launched slice must satisfy the gang with zero new
    nodes."""
    provider = _FakeSliceProvider(nodes=["slice-0"])
    autoscaler = _slice_autoscaler(provider)
    autoscaler._load = lambda: _gang_load(joined)
    result = autoscaler.update()
    assert result["launched"] == [], (
        f"joined={joined}: relaunched a booting slice"
    )
    assert provider.created == []


def test_unlaunched_gang_still_launches_exactly_one_slice():
    """Sanity: with NO provider node yet, the same gang launches one
    slice (not four single hosts)."""
    provider = _FakeSliceProvider()
    autoscaler = _slice_autoscaler(provider)
    autoscaler._load = lambda: _gang_load(0)
    load = autoscaler._load()
    load["nodes"] = load["nodes"][:1]  # head only
    autoscaler._load = lambda: load
    result = autoscaler.update()
    assert len(result["launched"]) == 1
    assert provider.created == ["slice-0"]


def test_dead_slice_host_stops_masking_demand_after_launch_timeout():
    """A slice past its launch timeout with a missing host must NOT
    keep contributing phantom 'launching' capacity: the gang would
    wedge forever waiting on a dead host. Past the timeout the
    remainder launches a replacement slice."""
    provider = _FakeSliceProvider(nodes=["slice-0"])
    autoscaler = _slice_autoscaler(provider)
    autoscaler.launch_timeout_s = 60.0
    autoscaler._load = lambda: _gang_load(3)  # 3 of 4 hosts, 1 dead
    # Simulate the slice having been seen long before the timeout.
    autoscaler._first_seen["slice-0"] = time.time() - 999.0
    result = autoscaler.update()
    assert len(result["launched"]) == 1, "gang wedged on a dead host"
    # Within the timeout the same state launches nothing (booting).
    provider2 = _FakeSliceProvider(nodes=["slice-0"])
    autoscaler2 = _slice_autoscaler(provider2)
    autoscaler2.launch_timeout_s = 60.0
    autoscaler2._load = lambda: _gang_load(3)
    assert autoscaler2.update()["launched"] == []
