"""Autoscaler tests (reference test model: autoscaler tests with
FakeMultiNodeProvider + AutoscalingCluster — scale up on demand, honor
min/max, scale down when idle)."""

import time

import pytest


@pytest.fixture
def scaling_cluster():
    import ray_tpu as rt
    from ray_tpu.autoscaler import AutoscalingCluster

    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_types={
            "cpu-worker": {
                "resources": {"CPU": 2.0, "memory": float(2**30)},
                "min_workers": 0,
                "max_workers": 3,
            },
        },
        idle_timeout_s=2.0,
    )
    cluster.start()
    rt.init(address=cluster.address)
    yield rt, cluster
    rt.shutdown()
    cluster.shutdown()


def test_scales_up_for_infeasible_task_then_down(scaling_cluster):
    rt, cluster = scaling_cluster
    assert cluster.num_workers() == 0

    # Needs 2 CPUs; the 1-CPU head can't run it.
    @rt.remote(num_cpus=2)
    def heavy():
        return "ran"

    ref = heavy.remote()
    assert rt.get(ref, timeout=60) == "ran"
    assert cluster.num_workers() >= 1

    # Idle workers terminate after idle_timeout (min_workers=0).
    deadline = time.time() + 30
    while time.time() < deadline and cluster.num_workers() > 0:
        time.sleep(0.3)
    assert cluster.num_workers() == 0


def test_scales_up_for_placement_group(scaling_cluster):
    rt, cluster = scaling_cluster
    from ray_tpu.util import placement_group

    pg = placement_group(
        [{"CPU": 2.0}, {"CPU": 2.0}], strategy="STRICT_SPREAD"
    )
    assert pg.wait(60)
    assert cluster.num_workers() >= 2


def test_respects_max_workers(scaling_cluster):
    rt, cluster = scaling_cluster

    @rt.remote(num_cpus=2)
    def hold():
        import time as _t

        _t.sleep(3)
        return 1

    refs = [hold.remote() for _ in range(10)]
    deadline = time.time() + 20
    peak = 0
    while time.time() < deadline:
        peak = max(peak, cluster.num_workers())
        time.sleep(0.2)
        if peak >= 3:
            break
    assert peak <= 3
    rt.get(refs, timeout=120)


def test_min_workers_floor():
    import ray_tpu as rt
    from ray_tpu.autoscaler import AutoscalingCluster

    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_types={
            "base": {
                "resources": {"CPU": 1.0, "memory": float(2**30)},
                "min_workers": 2,
                "max_workers": 4,
            },
        },
    )
    cluster.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline and cluster.num_workers() < 2:
            time.sleep(0.2)
        assert cluster.num_workers() >= 2
        rt.init(address=cluster.address)
        rt.shutdown()
    finally:
        cluster.shutdown()
