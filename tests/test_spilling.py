"""Object spilling: overflow to disk under store pressure, restore on
get (reference behavior: src/ray/raylet/local_object_manager.h:110
SpillObjectsOfSize + AsyncRestoreSpilledObject, storage layout
python/ray/_private/external_storage.py:72)."""

import numpy as np
import pytest

import ray_tpu as rt

MB = 1024 * 1024


@pytest.fixture(params=["native", "py"])
def small_store(request):
    rt.init(
        num_cpus=2,
        _system_config={
            "object_store_memory": 24 * MB,
            "object_spilling_threshold": 0.8,
            # Scan fast so pressure-driven spilling kicks in within the
            # test's patience.
            "object_eviction_check_interval_s": 0.1,
            "use_native_object_store": request.param == "native",
        },
    )
    yield
    rt.shutdown()


def test_put_twice_store_capacity_and_read_back(small_store):
    """2x the store's capacity lives behind refs at once; every byte
    reads back intact (r2 verdict missing #6 'done =' criterion)."""
    chunks = []
    refs = []
    for i in range(12):  # 12 x 4MB = 48MB through a 24MB store
        arr = np.full(MB, i, dtype=np.uint32)  # 4MB each
        chunks.append(arr)
        refs.append(rt.put(arr))
    for i, ref in enumerate(refs):
        got = rt.get(ref, timeout=60)
        assert np.array_equal(got, chunks[i]), f"object {i} corrupted"


def test_spill_files_created_then_cleaned(small_store):
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    daemon = rt.api._session.daemon
    refs = [rt.put(np.full(MB, i, dtype=np.uint32)) for i in range(12)]
    assert daemon.spill is not None
    assert daemon.spill.stats()["spilled_objects"] > 0, (
        "store pressure at 2x capacity must have spilled something"
    )
    # Dropping the refs deletes spilled copies along with shm copies.
    del refs
    worker.flush_pending_dels()
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        if daemon.spill.stats()["spilled_objects"] == 0:
            break
        time.sleep(0.1)
    assert daemon.spill.stats()["spilled_objects"] == 0


def test_task_returns_survive_pressure(small_store):
    """Task return values spilled under pressure restore transparently
    inside a later task's argument resolution."""

    @rt.remote
    def produce(i):
        return np.full(MB, i, dtype=np.uint32)

    @rt.remote
    def check(arr, i):
        return bool((arr == i).all())

    refs = [produce.remote(i) for i in range(10)]
    oks = rt.get([check.remote(r, i) for i, r in enumerate(refs)], timeout=120)
    assert all(oks)
