"""Direct task transport tests (reference behavior:
src/ray/core_worker/transport/normal_task_submitter.cc direct calls,
actor_task_submitter.h direct actor calls).

The rt_session fixture gives a fresh single-node session; direct calls
are on by default (config.use_direct_calls)."""

import os
import time

import pytest


def _direct_manager(rt):
    from ray_tpu._private.worker import global_worker

    return global_worker()._direct


def test_direct_path_engaged(rt_session):
    rt = rt_session

    @rt.remote
    def f(x):
        return x * 2

    assert rt.get(f.remote(21)) == 42
    mgr = _direct_manager(rt)
    assert mgr is not None
    # A lease was taken for the default scheduling key.
    assert any(ks.leases for ks in mgr._keys.values())


def test_direct_errors_propagate(rt_session):
    rt = rt_session

    @rt.remote
    def boom():
        raise ValueError("direct boom")

    with pytest.raises(Exception, match="direct boom"):
        rt.get(boom.remote())


def test_direct_num_returns(rt_session):
    rt = rt_session

    @rt.remote
    def pair():
        return 1, 2

    a, b = pair.options(num_returns=2).remote()
    assert rt.get(a) == 1 and rt.get(b) == 2


def test_direct_ref_arg_chain(rt_session):
    rt = rt_session

    @rt.remote
    def add1(x):
        return x + 1

    ref = add1.remote(0)
    for _ in range(20):
        ref = add1.remote(ref)
    assert rt.get(ref) == 21


def test_direct_large_result_zero_copy(rt_session):
    rt = rt_session
    import numpy as np

    @rt.remote
    def make(n):
        return np.arange(n, dtype=np.float64)

    out = rt.get(make.remote(1_000_000))  # ~8 MB -> shm path
    assert out.shape == (1_000_000,)
    assert float(out[-1]) == 999_999.0


def test_direct_nested_ref_published(rt_session):
    """A direct inline result embedded in another value must be
    resolvable by the borrowing worker (ensure_published)."""
    rt = rt_session

    @rt.remote
    def produce():
        return "payload"

    @rt.remote
    def consume(box):
        return rt.get(box["ref"])

    inner = produce.remote()
    assert rt.get(consume.remote({"ref": inner})) == "payload"


def test_direct_temp_dep_ref_pinned(rt_session):
    """`use.remote(boom.remote())`: the dep ref is a temporary the
    caller drops immediately; the submitter must pin it until the task
    completes or the daemon deletes the dep under the worker (r3
    regression: errored dep entry deleted -> worker waits forever)."""
    rt = rt_session

    @rt.remote
    def boom():
        raise KeyError("first")

    @rt.remote
    def use(x):
        return x

    with pytest.raises(Exception, match="first"):
        rt.get(use.remote(boom.remote()), timeout=30)

    @rt.remote
    def make():
        return 7

    assert rt.get(use.remote(make.remote()), timeout=30) == 7


def test_direct_wait(rt_session):
    rt = rt_session

    @rt.remote
    def quick():
        return 1

    @rt.remote
    def slow():
        time.sleep(5)
        return 2

    q, s = quick.remote(), slow.remote()
    ready, remaining = rt.wait([q, s], num_returns=1, timeout=3)
    assert ready == [q] and remaining == [s]


def test_direct_worker_crash_retries():
    import ray_tpu as rt

    rt.init(num_cpus=2)
    try:
        marker = f"/tmp/rt_crash_once_{os.getpid()}"
        if os.path.exists(marker):
            os.unlink(marker)

        @rt.remote
        def crash_once(path):
            if not os.path.exists(path):
                open(path, "w").close()
                os._exit(1)  # hard kill: connection loss, not an error
            return "survived"

        # default task_max_retries=3 -> retried on a fresh lease
        assert rt.get(crash_once.remote(marker), timeout=60) == "survived"
        os.unlink(marker)

        @rt.remote
        def crash_always():
            os._exit(1)

        with pytest.raises(Exception):
            rt.get(
                crash_always.options(max_retries=0).remote(), timeout=60
            )
    finally:
        rt.shutdown()


def test_direct_actor_roundtrip_and_latency(rt_session):
    rt = rt_session

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert rt.get(c.inc.remote()) == 1
    # ordering across many pipelined calls
    refs = [c.inc.remote() for _ in range(50)]
    assert rt.get(refs) == list(range(2, 52))


def test_direct_disabled_fallback():
    import ray_tpu as rt

    rt.init(num_cpus=2, _system_config={"use_direct_calls": False})
    try:

        @rt.remote
        def f(x):
            return x + 1

        assert rt.get(f.remote(1)) == 2
        from ray_tpu._private.worker import global_worker

        assert global_worker()._direct is None
    finally:
        rt.shutdown()


def test_lease_released_after_idle():
    import ray_tpu as rt

    rt.init(
        num_cpus=2,
        _system_config={"worker_lease_idle_timeout_s": 0.3},
    )
    try:

        @rt.remote
        def f():
            return 1

        assert rt.get(f.remote()) == 1
        mgr = _direct_manager(rt)
        deadline = time.time() + 5
        while time.time() < deadline:
            if all(not ks.leases for ks in mgr._keys.values()):
                break
            time.sleep(0.1)
        assert all(not ks.leases for ks in mgr._keys.values())
        # and the pool still works afterwards
        assert rt.get(f.remote()) == 1
    finally:
        rt.shutdown()
