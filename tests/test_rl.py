"""RL tests (reference test model: rllib smoke tests — env mechanics,
runner batch shapes, and a PPO learning regression on CartPole with a
reward threshold, rllib/tuned_examples/)."""

import numpy as np
import pytest


def test_cartpole_dynamics():
    from ray_tpu.rl import CartPoleEnv

    env = CartPoleEnv(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0.0
    steps = 0
    terminated = False
    while not terminated and steps < 600:
        obs, reward, terminated, truncated, _ = env.step(steps % 2)
        total += reward
        steps += 1
        if truncated:
            break
    # Alternating actions balance poorly: episode ends well before cap.
    assert terminated
    assert 5 <= steps < 200


def test_env_runner_batch_shapes(rt_session):
    import jax

    from ray_tpu.rl import EnvRunnerGroup
    from ray_tpu.rl.models import init_policy_params

    group = EnvRunnerGroup(
        "CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=16,
    )
    try:
        params = init_policy_params(jax.random.PRNGKey(0), 4, 2)
        group.sync_weights(params)
        batch = group.sample()
        n = 2 * 4 * 16
        assert batch["obs"].shape == (n, 4)
        assert batch["actions"].shape == (n,)
        assert batch["advantages"].shape == (n,)
        assert batch["value_targets"].shape == (n,)
        assert np.isfinite(batch["advantages"]).all()
    finally:
        group.shutdown()


@pytest.mark.slow
def test_ppo_learns_cartpole(rt_session):
    """Learning regression: PPO must clear a return threshold
    (reference: rllib tuned_examples pass/fail on reward). Defaults
    reach ~100 mean return within ~15 iterations (measured: 19 -> 133
    over 25 iters)."""
    from ray_tpu.rl import PPOConfig

    algo = PPOConfig().environment("CartPole-v1").debugging(seed=0).build()
    try:
        best = 0.0
        for _ in range(25):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 100.0:
                break
        assert best >= 100.0, f"PPO plateaued at {best}"
    finally:
        algo.stop()


def test_ppo_save_restore(rt_session, tmp_path):
    from ray_tpu.rl import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .build()
    )
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
    finally:
        algo.stop()

    algo2 = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .build()
    )
    try:
        algo2.restore(path)
        assert algo2.iteration == 1
        result = algo2.train()
        assert result["training_iteration"] == 2
    finally:
        algo2.stop()


def test_fault_tolerant_actor_manager(rt_session):
    """FaultTolerantActorManager (reference: rllib/utils/
    actor_manager.py:198): a dead actor turns into a per-actor error
    result instead of an exception, drops from the healthy set, and a
    later probe resurrects the slot from the factory."""
    import ray_tpu as rt
    from ray_tpu.rl import FaultTolerantActorManager

    @rt.remote(num_cpus=0)
    class Echo:
        def __init__(self, tag):
            self.tag = tag

        def ping(self):
            return "ok"

        def whoami(self):
            import os

            return (self.tag, os.getpid())

    manager = FaultTolerantActorManager(
        [Echo.remote(i) for i in range(3)],
        actor_factory=lambda idx: Echo.remote(idx),
    )
    try:
        results = manager.foreach_actor("whoami", timeout=60)
        assert [r.ok for r in results] == [True] * 3
        assert [r.value[0] for r in results] == [0, 1, 2]
        victim_pid = results[1].value[1]

        rt.kill(manager.actor(1))
        results = manager.foreach_actor("whoami", timeout=60)
        oks = {r.actor_id: r.ok for r in results}
        assert oks[0] and oks[2] and not oks[1]
        assert results[1].error is not None
        assert manager.num_healthy_actors() == 2

        restored = manager.probe_unhealthy_actors(timeout=60)
        assert restored == [1]
        results = manager.foreach_actor("whoami", timeout=60)
        assert [r.ok for r in results] == [True] * 3
        assert results[1].value[0] == 1
        assert results[1].value[1] != victim_pid  # a fresh actor
    finally:
        manager.shutdown()


def test_env_runner_death_mid_iteration(rt_session):
    """A runner killed between iterations must not fail training: the
    next sample() returns the surviving runners' shard, and the one
    after returns a full batch from a respawned, re-synced runner
    (VERDICT r4 task 3 done-criterion)."""
    import jax

    import ray_tpu as rt
    from ray_tpu.rl import EnvRunnerGroup
    from ray_tpu.rl.models import init_policy_params

    group = EnvRunnerGroup(
        "CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=16,
    )
    try:
        group.sync_weights(
            init_policy_params(jax.random.PRNGKey(0), 4, 2)
        )
        full = 2 * 4 * 16
        assert group.sample()["obs"].shape[0] == full

        rt.kill(group.runners[0])
        batch = group.sample()  # iteration survives at half size
        assert batch["obs"].shape[0] == full // 2
        assert group.num_healthy_runners() == 1

        batch = group.sample()  # slot respawned + weights re-synced
        assert batch["obs"].shape[0] == full
        assert group.num_healthy_runners() == 2
    finally:
        group.shutdown()


def test_learner_group_consistency(rt_session):
    """Two-learner DDP invariant (reference: learner_group.py:206):
    after an update, every learner holds bit-identical params (they
    all applied the same averaged gradients), and those params moved
    from the init."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.rl import LearnerGroup

    rng = np.random.default_rng(0)
    n = 512
    batch = {
        "obs": rng.normal(size=(n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=n).astype(np.int32),
        "logp": np.full(n, -0.69, np.float32),
        "advantages": rng.normal(size=n).astype(np.float32),
        "value_targets": rng.normal(size=n).astype(np.float32),
    }
    group = LearnerGroup(
        2, obs_size=4, num_actions=2, minibatch_size=128, num_epochs=2
    )
    try:
        before = group.get_weights()
        metrics = group.update(batch)
        assert np.isfinite(metrics["total_loss"])
        weights = [
            rt.get(lrn.get_weights.remote(), timeout=60)
            for lrn in group.learners
        ]
        flat0 = jax_flat(weights[0])
        flat1 = jax_flat(weights[1])
        for a, b in zip(flat0, flat1):
            np.testing.assert_array_equal(a, b)
        assert any(
            not np.allclose(a, b)
            for a, b in zip(jax_flat(before), flat0)
        ), "update did not move params"
    finally:
        group.shutdown()


def jax_flat(tree):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


@pytest.mark.slow
def test_two_learner_ppo_matches_single_learner(rt_session):
    """2-learner PPO reaches the same CartPole bar as the 1-learner
    regression above — same effective minibatch, averaged gradients
    (VERDICT r4 task 3 done-criterion)."""
    from ray_tpu.rl import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .learners(num_learners=2)
        .debugging(seed=0)
        .build()
    )
    try:
        best = 0.0
        for _ in range(25):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 100.0:
                break
        assert best >= 100.0, f"2-learner PPO plateaued at {best}"
    finally:
        algo.stop()


def test_dqn_mechanics():
    """DQN plumbing without the learning wait: replay ring wraps,
    one iteration fills the buffer and reports sane metrics, target
    syncs on schedule, epsilon anneals, save/restore round-trips."""
    import numpy as np

    from ray_tpu.rl import DQNConfig, ReplayBuffer

    buf = ReplayBuffer(capacity=8, obs_size=2, seed=0)
    for i in range(12):  # wraps past capacity
        buf.add_batch(
            np.full((1, 2), i, np.float32),
            np.array([i % 2]),
            np.array([1.0], np.float32),
            np.full((1, 2), i + 1, np.float32),
            np.array([False]),
        )
    assert len(buf) == 8
    sample = buf.sample(4)
    assert sample["obs"].min() >= 4  # oldest entries overwritten

    cfg = DQNConfig().environment("CartPole-v1").debugging(seed=0)
    cfg.rollout_length = 8
    cfg.learning_starts = 32
    cfg.num_updates_per_iteration = 4
    cfg.target_update_freq = 2
    algo = cfg.build()
    r1 = algo.train()
    assert r1["num_env_steps_sampled"] == 8 * cfg.num_envs
    assert r1["num_updates"] == 4  # buffer was past learning_starts
    assert np.isfinite(r1["td_loss"])
    assert algo.updates // cfg.target_update_freq >= 1
    eps1 = r1["epsilon"]
    r2 = algo.train()
    assert r2["epsilon"] < eps1  # annealing

    path = algo.save()
    algo2 = cfg.build()
    algo2.restore(path)
    assert algo2.iteration == algo.iteration
    assert algo2.env_steps == algo.env_steps


@pytest.mark.slow
def test_dqn_learns_cartpole():
    """Second algorithm learning regression (VERDICT r4 task 3):
    double-DQN clears the CartPole bar (measured: ~130 mean return by
    ~30k env steps, 6s on 8 virtual CPUs)."""
    from ray_tpu.rl import DQNConfig

    algo = DQNConfig().environment("CartPole-v1").debugging(seed=0).build()
    best = 0.0
    for _ in range(80):
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= 100.0:
            break
    assert best >= 100.0, f"DQN plateaued at {best}"
