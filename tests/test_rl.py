"""RL tests (reference test model: rllib smoke tests — env mechanics,
runner batch shapes, and a PPO learning regression on CartPole with a
reward threshold, rllib/tuned_examples/)."""

import numpy as np
import pytest


def test_cartpole_dynamics():
    from ray_tpu.rl import CartPoleEnv

    env = CartPoleEnv(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0.0
    steps = 0
    terminated = False
    while not terminated and steps < 600:
        obs, reward, terminated, truncated, _ = env.step(steps % 2)
        total += reward
        steps += 1
        if truncated:
            break
    # Alternating actions balance poorly: episode ends well before cap.
    assert terminated
    assert 5 <= steps < 200


def test_env_runner_batch_shapes(rt_session):
    import jax

    from ray_tpu.rl import EnvRunnerGroup
    from ray_tpu.rl.models import init_policy_params

    group = EnvRunnerGroup(
        "CartPole-v1",
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_length=16,
    )
    try:
        params = init_policy_params(jax.random.PRNGKey(0), 4, 2)
        group.sync_weights(params)
        batch = group.sample()
        n = 2 * 4 * 16
        assert batch["obs"].shape == (n, 4)
        assert batch["actions"].shape == (n,)
        assert batch["advantages"].shape == (n,)
        assert batch["value_targets"].shape == (n,)
        assert np.isfinite(batch["advantages"]).all()
    finally:
        group.shutdown()


@pytest.mark.slow
def test_ppo_learns_cartpole(rt_session):
    """Learning regression: PPO must clear a return threshold
    (reference: rllib tuned_examples pass/fail on reward). Defaults
    reach ~100 mean return within ~15 iterations (measured: 19 -> 133
    over 25 iters)."""
    from ray_tpu.rl import PPOConfig

    algo = PPOConfig().environment("CartPole-v1").debugging(seed=0).build()
    try:
        best = 0.0
        for _ in range(25):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 100.0:
                break
        assert best >= 100.0, f"PPO plateaued at {best}"
    finally:
        algo.stop()


def test_ppo_save_restore(rt_session, tmp_path):
    from ray_tpu.rl import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .build()
    )
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
    finally:
        algo.stop()

    algo2 = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .build()
    )
    try:
        algo2.restore(path)
        assert algo2.iteration == 1
        result = algo2.train()
        assert result["training_iteration"] == 2
    finally:
        algo2.stop()
