"""Whole-program concurrency analysis tests (`ray_tpu devtools race`,
devtools/concurrency.py rules RT201-RT206) and the runtime lock-order
witness (devtools/lock_witness.py, `RT_lock_witness_enabled`).

Every rule has a seeded-bug fixture (must fire) and a corrected twin
(must stay quiet); the repo analyzes itself clean — package AND tests
— so every thread/lock interaction either passes the rules or carries
an explicit `# rt: noqa[RT2xx]` reviewed in the diff. Also here:
regression tests for the pre-existing concurrency bugs the pass found
in this PR (daemon RPC-under-state-lock, ActorDirectRouter._client
torn swap), the witness's live A->B/B->A inversion conviction through
`rt.diagnose()`'s `verdict.locks`, and the zero-when-off /
<1%-of-a-step overhead bars.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from ray_tpu.devtools.concurrency import (
    RULES,
    main as race_main,
    race_paths,
    race_sources,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")


def fired(source: str, path: str = "mod.py"):
    return {
        f.rule
        for f in race_sources([(path, textwrap.dedent(source))])
    }


# ---------------------------------------------------------------------------
# one seeded-bug fixture + one corrected twin per rule
# ---------------------------------------------------------------------------

CASES = [
    (
        "RT201",
        """
        import threading

        class Pump:
            def __init__(self):
                self._count = 0
                self._thread = threading.Thread(
                    target=self._loop, daemon=True
                )
                self._thread.start()

            def _loop(self):
                while True:
                    self._count = self._count + 1

            def bump(self, n):
                self._count = self._count + n
        """,
        True,
    ),
    (
        "RT201",
        """
        import threading

        class Pump:
            def __init__(self):
                self._count = 0
                self._lock = threading.Lock()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True
                )
                self._thread.start()

            def _loop(self):
                while True:
                    with self._lock:
                        self._count = self._count + 1

            def bump(self, n):
                with self._lock:
                    self._count = self._count + n
        """,
        False,
    ),
    (
        "RT202",
        """
        import threading

        class Transfer:
            def __init__(self):
                self._accounts = threading.Lock()
                self._journal = threading.Lock()

            def debit(self):
                with self._accounts:
                    with self._journal:
                        pass

            def audit(self):
                with self._journal:
                    with self._accounts:
                        pass
        """,
        True,
    ),
    (
        "RT202",
        """
        import threading

        class Transfer:
            def __init__(self):
                self._accounts = threading.Lock()
                self._journal = threading.Lock()

            def debit(self):
                with self._accounts:
                    with self._journal:
                        pass

            def audit(self):
                with self._accounts:
                    with self._journal:
                        pass
        """,
        False,
    ),
    (
        "RT203",
        """
        import threading
        import time

        class Flusher:
            def __init__(self):
                self._lock = threading.Lock()
                self._batch = []

            def flush(self):
                with self._lock:
                    batch = list(self._batch)
                    time.sleep(0.5)
        """,
        True,
    ),
    (
        "RT203",
        """
        import threading
        import time

        class Flusher:
            def __init__(self):
                self._lock = threading.Lock()
                self._batch = []

            def flush(self):
                with self._lock:
                    batch = list(self._batch)
                time.sleep(0.5)
        """,
        False,
    ),
    (
        "RT204",
        """
        import threading

        class Gate:
            def __init__(self):
                self._cond = threading.Condition()
                self._open = False

            def wait_open(self):
                with self._cond:
                    if not self._open:
                        self._cond.wait()
        """,
        True,
    ),
    (
        "RT204",
        """
        import threading

        class Gate:
            def __init__(self):
                self._cond = threading.Condition()
                self._open = False

            def wait_open(self):
                with self._cond:
                    while not self._open:
                        self._cond.wait()
        """,
        False,
    ),
    (
        "RT205",
        """
        import threading

        class Counter:
            def __init__(self):
                self._n = 0

            def bump(self):
                lock = threading.Lock()
                with lock:
                    self._n = self._n + 1
        """,
        True,
    ),
    (
        "RT205",
        """
        import threading

        class Counter:
            def __init__(self):
                self._n = 0
                self._lock = threading.Lock()

            def bump(self):
                with self._lock:
                    self._n = self._n + 1
        """,
        False,
    ),
    (
        "RT206",
        """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}

            def __del__(self):
                with self._lock:
                    self._entries.clear()
        """,
        True,
    ),
    (
        "RT206",
        """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}

            def close(self):
                with self._lock:
                    self._entries.clear()
        """,
        False,
    ),
]


@pytest.mark.parametrize(
    "rule,source,expect",
    CASES,
    ids=[
        f"{rule}-{'fires' if expect else 'quiet'}"
        for rule, _, expect in CASES
    ],
)
def test_rule_fixtures(rule, source, expect):
    rules = fired(source)
    if expect:
        assert rule in rules, f"{rule} did not fire: {rules}"
    else:
        assert rule not in rules, f"{rule} fired on corrected twin"


def test_findings_name_both_sides():
    """An RT201 finding names every unguarded context/site, an RT202
    cycle names both legs file:line — the two halves an operator must
    see to fix an ordering bug."""
    rt201 = [
        f
        for f in race_sources(
            [("mod.py", textwrap.dedent(CASES[0][1]))]
        )
        if f.rule == "RT201"
    ]
    assert rt201, "seeded RT201 fixture must fire"
    msg = rt201[0].message
    assert "_count" in msg
    assert "thread:" in msg and "caller" in msg
    rt202 = [
        f
        for f in race_sources(
            [("mod.py", textwrap.dedent(CASES[2][1]))]
        )
        if f.rule == "RT202"
    ]
    assert rt202, "seeded RT202 fixture must fire"
    msg = rt202[0].message
    assert "_accounts" in msg and "_journal" in msg
    assert msg.count("mod.py:") >= 2, msg


# ---------------------------------------------------------------------------
# suppression / CLI contract (mirrors test_lint.py / test_check.py)
# ---------------------------------------------------------------------------

SEEDED = CASES[0][1]


def test_noqa_suppresses_on_the_flagged_line():
    findings = race_sources([("mod.py", textwrap.dedent(SEEDED))])
    assert findings
    lines = textwrap.dedent(SEEDED).splitlines()
    for f in findings:
        lines[f.line - 1] += f"  # rt: noqa[{f.rule}]"
    assert race_sources([("mod.py", "\n".join(lines))]) == []


def test_noqa_must_name_the_rule():
    lines = textwrap.dedent(SEEDED).splitlines()
    findings = race_sources([("mod.py", "\n".join(lines))])
    lines[findings[0].line - 1] += "  # rt: noqa[RT999]"
    still = race_sources([("mod.py", "\n".join(lines))])
    assert findings[0].rule in {f.rule for f in still}


def test_main_exit_codes_and_json(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert race_main([str(clean)]) == 0

    seeded = tmp_path / "seeded.py"
    seeded.write_text(textwrap.dedent(SEEDED))
    assert race_main([str(seeded), "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload, "JSON mode must carry the findings"
    row = payload[0]
    assert {"path", "line", "col", "rule", "message"} <= set(row)
    assert row["rule"] == "RT201"

    assert race_main([str(tmp_path / "missing.py")]) == 2


def test_list_rules(capsys):
    assert race_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out
    # RT201-RT206 + the pass's own noqa-hygiene audit rule.
    assert set(RULES) == {f"RT20{i}" for i in range(1, 7)} | {"RT290"}


def test_rules_filter(tmp_path):
    seeded = tmp_path / "seeded.py"
    seeded.write_text(textwrap.dedent(SEEDED))
    # Filtered to a rule the fixture cannot trip: clean exit.
    assert race_main([str(seeded), "--rules", "RT204"]) == 0
    assert race_main([str(seeded), "--rules", "RT201"]) == 1


def test_repo_analyzes_clean():
    """The acceptance bar: package AND tests, zero findings — every
    suppression in the tree is explicit and justified in place."""
    assert race_paths([PKG, os.path.dirname(__file__)]) == []


def test_devtools_all_includes_race(tmp_path):
    from ray_tpu.devtools import all_main

    seeded = tmp_path / "seeded.py"
    seeded.write_text(textwrap.dedent(SEEDED))
    import io

    out = io.StringIO()
    assert all_main([str(seeded), "--json"], out=out) == 1
    rules = {row["rule"] for row in json.loads(out.getvalue())}
    assert "RT201" in rules


# ---------------------------------------------------------------------------
# regression: the pre-existing bugs this pass convicted (and this PR
# fixed)
# ---------------------------------------------------------------------------


def test_router_teardown_closes_exactly_once():
    """ActorDirectRouter._client was written unguarded from the
    executor drain, the reply-reader thread, and shutdown(): two
    racing teardowns could double-close the client (or leak the one a
    concurrent _resolve published). Fixed by swapping under _cond and
    closing outside it — this pins the exactly-one-close contract."""
    from ray_tpu._private.direct import ActorDirectRouter

    router = ActorDirectRouter(core=None, actor_id=None)

    release = threading.Event()
    entered = threading.Event()

    class FakeClient:
        def __init__(self):
            self.closes = 0

        def close(self):
            self.closes += 1
            entered.set()
            # Hold the close open so the second teardown overlaps it.
            assert release.wait(10)

    client = FakeClient()
    with router._cond:
        router._client = client

    t = threading.Thread(target=router._teardown_client)
    t.start()
    assert entered.wait(10)
    # Second teardown while the first is mid-close: must see the
    # already-swapped None and return without touching the client.
    router._teardown_client()
    release.set()
    t.join(10)
    assert client.closes == 1
    with router._cond:
        assert router._client is None


def test_schedule_task_rereport_runs_outside_state_lock(rt_session):
    """_h_schedule_task held the node's state lock across a
    synchronous actor_created RPC to the head (re-report branch): a
    slow head wedged every handler and the heartbeat on that node.
    Fixed by re-reporting after the lock is dropped — this probes the
    lock is NOT held when the report fires."""
    rt = rt_session

    @rt.remote
    class Pinger:
        def ping(self):
            return 1

    actor = Pinger.remote()
    assert rt.get(actor.ping.remote(), timeout=30) == 1

    daemon = rt.api._session.daemon
    with daemon._lock:
        aid, host = next(iter(daemon.actor_hosts.items()))
        assert host.worker_conn_id is not None
        spec = dict(host.creation_spec)

    held_during_report = []

    def probe(*args, **kwargs):
        held_during_report.append(daemon._lock._is_owned())

    original = daemon._control_actor_created
    daemon._control_actor_created = probe
    try:
        # A restarted head re-dispatching a creation this node already
        # hosts: the re-report branch.
        reply = daemon._h_schedule_task(None, {"spec": spec})
    finally:
        daemon._control_actor_created = original
    assert reply == {}
    assert held_during_report == [False]


def test_fixed_hot_files_stay_clean():
    """The files whose real bugs this PR fixed must hold the race
    rules without new suppressions sneaking in silently."""
    hot = [
        os.path.join(PKG, "_private", "daemon.py"),
        os.path.join(PKG, "_private", "direct.py"),
        os.path.join(PKG, "util", "metrics.py"),
    ]
    assert race_paths(hot, rules=["RT201", "RT203"]) == []


# ---------------------------------------------------------------------------
# runtime lock witness
# ---------------------------------------------------------------------------


@pytest.fixture
def witness():
    from ray_tpu.devtools import lock_witness as lw

    lw.uninstall()
    w = lw.install(max_edges=64)
    yield lw
    lw.uninstall()


def test_make_lock_disabled_is_raw(monkeypatch):
    """Zero-cost-off is structural: with the witness off, make_lock
    returns the SAME objects threading would — no wrapper, no branch
    on the acquire path."""
    from ray_tpu.devtools import lock_witness as lw

    lw.uninstall()
    assert type(lw.make_lock("x")) is type(threading.Lock())
    assert type(lw.make_lock("x", "rlock")) is type(threading.RLock())
    assert lw.snapshot() == {"enabled": False, "pid": os.getpid()}


def test_witness_records_inversion_with_both_stacks(witness):
    lw = witness
    a = lw.make_lock("t.a")
    b = lw.make_lock("t.b")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join(10)
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join(10)

    snap = lw.snapshot()
    assert snap["enabled"] is True
    pairs = {(e["from"], e["to"]) for e in snap["edges"]}
    assert {("t.a", "t.b"), ("t.b", "t.a")} <= pairs
    assert snap["cycles"], "A->B then B->A must cycle"
    legs = snap["cycles"][0]
    assert {leg["from"] for leg in legs} == {"t.a", "t.b"}
    for leg in legs:
        assert leg["stack"].strip(), "each leg carries its stack"
    # Both acquiring functions are named in the evidence.
    stacks = "".join(leg["stack"] for leg in legs)
    assert "ab" in stacks and "ba" in stacks
    json.dumps(snap)  # wire-safe


def test_witness_rlock_reentry_is_not_an_edge(witness):
    lw = witness
    r = lw.make_lock("t.re", "rlock")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
    with r:
        with r:
            pass
    assert lw.snapshot()["edges"] == []


def test_witness_consistent_order_is_quiet(witness):
    lw = witness
    a = lw.make_lock("t.a")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
    b = lw.make_lock("t.b")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
    for _ in range(3):
        with a:
            with b:
                pass
    snap = lw.snapshot()
    assert snap["cycles"] == []
    (edge,) = snap["edges"]
    assert (edge["from"], edge["to"]) == ("t.a", "t.b")
    assert edge["count"] == 3


def test_note_blocking_records_held_lock(witness):
    lw = witness
    a = lw.make_lock("t.hold")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
    lw.note_blocking("rpc.call:outside")  # no lock held: not recorded
    with a:
        lw.note_blocking("rpc.call:inside")
    snap = lw.snapshot()
    rows = {(r["lock"], r["op"]) for r in snap["held_blocking"]}
    assert rows == {("t.hold", "rpc.call:inside")}


def test_witness_edge_cap_counts_drops():
    from ray_tpu.devtools import lock_witness as lw

    lw.uninstall()
    lw.install(max_edges=2)
    try:
        outer = lw.make_lock("cap.outer")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
        inner = [lw.make_lock(f"cap.{i}") for i in range(5)]
        with outer:
            for lock in inner:
                with lock:
                    pass
        snap = lw.snapshot()
        assert len(snap["edges"]) == 2
        assert snap["dropped_edges"] == 3
    finally:
        lw.uninstall()


def test_witness_overhead_under_one_percent_of_smoke_step():
    """The hard bar from ISSUE 16: steady-state acquire/release of an
    instrumented nested pair must cost <1% of a --smoke train step,
    measured against the same conservative 20 ms floor the
    compile-watch bar uses (~40x below the observed smoke median), so
    the test doesn't flake under CI load. Off-cost is covered by
    test_make_lock_disabled_is_raw: no wrapper exists at all."""
    from ray_tpu.devtools import lock_witness as lw

    lw.uninstall()
    lw.install(max_edges=64)
    try:
        outer = lw.make_lock("bar.outer")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
        inner = lw.make_lock("bar.inner")  # rt: noqa[RT205] — witness fixture: the per-call lock IS the subject
        with outer:
            with inner:  # seed the edge: stack capture off the clock
                pass
        n = 2000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                with outer:
                    with inner:
                        pass
            best = min(best, (time.perf_counter() - t0) / n)
    finally:
        lw.uninstall()
    overhead_ms = best * 1e3
    smoke_step_floor_ms = 20.0
    assert overhead_ms < 0.01 * smoke_step_floor_ms, (
        f"lock witness costs {overhead_ms:.4f} ms per nested "
        f"acquire/release — over 1% of a {smoke_step_floor_ms} ms "
        "smoke step"
    )


def test_witness_env_kill_switch_beats_config(monkeypatch):
    """Env contract mirrors the flight recorder: an explicit env value
    wins over the cluster flag, so one process can opt out."""
    from ray_tpu._private.config import Config
    from ray_tpu.devtools import lock_witness as lw

    lw.uninstall()
    monkeypatch.setenv("RT_lock_witness_enabled", "0")
    lw.configure(Config(lock_witness_enabled=True))
    assert lw.witness() is None
    monkeypatch.delenv("RT_lock_witness_enabled")
    lw.configure(Config(lock_witness_enabled=True))
    assert lw.witness() is not None
    lw.uninstall()


# ---------------------------------------------------------------------------
# live conviction: witness -> diagnose -> doctor exit code
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_live_inversion_convicts_doctor_two_nodes(monkeypatch):
    """End-to-end (satellite smoke): a 2-node cluster with the witness
    enabled everywhere runs real work with a CLEAN verdict.locks; a
    worker that then interleaves A->B and B->A flips `rt.diagnose()`
    to a lock_order_inversion problem naming both locks with both
    acquiring stacks, and `ray_tpu doctor --json` (operator form)
    exits 1 on it."""
    from ray_tpu.cluster_utils import Cluster

    import ray_tpu as rt

    monkeypatch.setenv("RT_lock_witness_enabled", "1")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RT_ADDRESS", None)

    c = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    c.add_node(num_cpus=2, resources={"remote_node": 4.0})
    c.wait_for_nodes(2)
    rt.init(address=c.address)
    try:

        @rt.remote
        def ordinary(x):
            return x * 2

        assert rt.get(
            [ordinary.remote(i) for i in range(8)], timeout=60
        ) == [i * 2 for i in range(8)]

        verdict = rt.diagnose(capture_stacks=False)
        locks = verdict["locks"]
        assert locks["enabled"] is True, locks
        assert locks["procs"] >= 1
        # Healthy cluster doing real 2-node work: the witness saw the
        # framework's own locks and found no cyclic order.
        assert locks["cycles"] == [], locks["cycles"]
        assert verdict["healthy"] is True, verdict["problems"]

        @rt.remote
        def provoke_inversion():
            import threading as th

            from ray_tpu.devtools import lock_witness as lw

            a = lw.make_lock("test.inv_a")
            b = lw.make_lock("test.inv_b")

            def first_ab():
                with a:
                    with b:
                        pass

            def then_ba():
                with b:
                    with a:
                        pass

            for fn in (first_ab, then_ba):
                t = th.Thread(target=fn)
                t.start()
                t.join(10)
            return lw.snapshot()["enabled"]

        assert rt.get(provoke_inversion.remote(), timeout=60) is True

        verdict = rt.diagnose(capture_stacks=False)
        inversions = [
            p
            for p in verdict["problems"]
            if p["kind"] == "lock_order_inversion"
        ]
        assert inversions, verdict["problems"]
        problem = inversions[0]
        assert set(problem["locks"]) == {"test.inv_a", "test.inv_b"}
        stacks = "".join(leg["stack"] for leg in problem["legs"])
        assert "first_ab" in stacks and "then_ba" in stacks
        assert verdict["locks"]["cycles"], verdict["locks"]
        assert verdict["healthy"] is False

        # Operator form: the doctor CLI exits 1 and carries the
        # verdict.
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu",
                "doctor",
                "--json",
                "--no-stacks",
                "--address",
                c.address,
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 1, out.stdout + out.stderr
        cli_verdict = json.loads(out.stdout)
        assert any(
            p["kind"] == "lock_order_inversion"
            for p in cli_verdict["problems"]
        ), cli_verdict["problems"]
    finally:
        rt.shutdown()
        c.shutdown()
