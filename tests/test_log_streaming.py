"""Worker log streaming to the driver (reference behavior:
python/ray/_private/log_monitor.py tails worker logs and publishes
them; the driver prints them prefixed, worker.py:1966)."""

import sys
import time

import pytest

import ray_tpu as rt


@pytest.fixture
def cluster():
    rt.init(
        num_cpus=2,
        _system_config={"log_monitor_interval_s": 0.05},
    )
    yield
    rt.shutdown()


def _wait_for(capfd, needle, timeout=15):
    deadline = time.time() + timeout
    seen = ""
    while time.time() < deadline:
        out, err = capfd.readouterr()
        seen += out + err
        if needle in seen:
            return seen
        time.sleep(0.1)
    raise AssertionError(f"{needle!r} never streamed; got: {seen[-2000:]}")


def test_remote_print_reaches_driver(cluster, capfd):
    @rt.remote
    def shout():
        print("hello-from-worker-4242")
        return 1

    assert rt.get(shout.remote()) == 1
    seen = _wait_for(capfd, "hello-from-worker-4242")
    # Prefixed with the source worker identity.
    line = next(
        l for l in seen.splitlines() if "hello-from-worker-4242" in l
    )
    assert "pid=" in line and "worker-" in line


def test_actor_stderr_reaches_driver(cluster, capfd):
    @rt.remote
    class Noisy:
        def speak(self):
            print("actor-stderr-7777", file=sys.stderr)
            return "ok"

    a = Noisy.remote()
    assert rt.get(a.speak.remote()) == "ok"
    _wait_for(capfd, "actor-stderr-7777")


def test_remote_node_print_reaches_driver(capfd):
    """Lines tailed on a WORKER node forward through the head to the
    driver (reference: log_monitor runs per node, publishes centrally)."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_resources={"CPU": 1.0})
    rt.init(address=c.address)
    try:
        c.add_node(num_cpus=2, resources={"special": 2.0})
        c.wait_for_nodes(2)

        @rt.remote(resources={"special": 1.0})
        def shout():
            print("hello-from-remote-node-9191")
            return 1

        assert rt.get(shout.remote(), timeout=30) == 1
        _wait_for(capfd, "hello-from-remote-node-9191")
    finally:
        rt.shutdown()
        c.shutdown()


def test_logs_wanted_gating_via_heartbeat():
    """Worker nodes only pay the tail-and-forward cost while the head
    actually has subscribers; the bit rides the heartbeat reply.
    (Drivers can only attach to the head in this architecture, so the
    subscriber set lives there.)"""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_resources={"CPU": 2.0})
    node = c.add_node(num_cpus=1)
    try:
        c.wait_for_nodes(2)
        # No driver yet: after a couple heartbeats the node must see
        # logs_wanted == False.
        time.sleep(1.0)
        assert node._head_logs_wanted is False
        rt.init(address=c.address)
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not node._head_logs_wanted:
                time.sleep(0.1)
            assert node._head_logs_wanted is True
            assert c.head._log_subscribers
        finally:
            rt.shutdown()
        deadline = time.time() + 10
        while time.time() < deadline and node._head_logs_wanted:
            time.sleep(0.1)
        assert node._head_logs_wanted is False
    finally:
        c.shutdown()


def test_log_to_driver_off_is_quiet():
    rt.init(
        num_cpus=1,
        _system_config={"log_to_driver": False},
    )
    try:

        @rt.remote
        def quiet():
            print("should-not-stream-1111")
            return 1

        assert rt.get(quiet.remote()) == 1
        time.sleep(1.0)
        # No log_lines subscription (error_event alone doesn't drive
        # the tail loop).
        daemon = rt.api._session.daemon
        assert not daemon._logs_wanted()
    finally:
        rt.shutdown()


def test_error_events_pushed_to_driver(cluster, capfd):
    """Failures a driver might never get() still surface as pushed
    error events (reference: published error messages printed by the
    driver)."""

    @rt.remote(max_restarts=0)
    class Dies:
        def boom(self):
            import os

            os._exit(1)

    d = Dies.remote()
    ref = d.boom.remote()  # fire and forget — never get()
    # Condition first, output second: under full-suite load the
    # death-detection chain (worker conn EOF -> actor DEAD -> event
    # push) can outlast a flat output poll, so wait on the observable
    # STATE with its own deadline — the error event is published
    # before the DEAD transition lands in the actor table, so once
    # the state is visible the line is already in flight.
    from ray_tpu.util import state

    deadline = time.time() + 90
    while time.time() < deadline:
        if any(
            a.get("state") == "DEAD" for a in state.list_actors()
        ):
            break
        time.sleep(0.2)
    else:
        raise AssertionError("actor never reached DEAD state")
    _wait_for(capfd, "actor ")


def test_error_event_from_remote_node_reaches_driver(capfd):
    """A failure detected on a WORKER node forwards through the head
    to the driver (publish_event relay)."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_resources={"CPU": 1.0})
    rt.init(address=c.address)
    try:
        c.add_node(num_cpus=1, resources={"special": 1.0})
        c.wait_for_nodes(2)

        @rt.remote(resources={"special": 1.0}, max_restarts=0)
        class RemoteDies:
            def boom(self):
                import os

                os._exit(1)

        d = RemoteDies.remote()
        d.boom.remote()  # never get()  # rt: noqa[RT106] — the test IS about an unobserved death
        _wait_for(capfd, "dead:")
    finally:
        rt.shutdown()
        c.shutdown()
