"""Benchmark entrypoint: Llama training MFU on the TPU chip + runtime
op/s microbenchmarks.

Prints ONE JSON line on the LAST stdout line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Design (round-1 verdict weak #1: the bench must tolerate a held/slow
chip — the axon TPU backend can hang in init for minutes):

- The MFU measurement runs in a SUBPROCESS (``--mode tpu``) with a hard
  timeout and retries with backoff; a hung backend init can never hang
  the bench itself.
- Before touching the chip, stale TPU-holding processes from prior
  test runs (worker_main leftovers) are reaped and the libtpu lockfile
  cleared.
- If the chip never comes up, a CPU subprocess (``--mode cpu``) runs
  the same training step on a tiny config so the bench still emits its
  JSON line, marked ``"cpu_fallback": true``.
- A ray_perf-style op/s microbenchmark suite (verdict item 6; model:
  reference python/ray/_private/ray_perf.py:120-288) always runs on
  the distributed runtime (CPU-bound by design) and is embedded under
  the ``"micro"`` key and written to MICROBENCH.json.

North star (BASELINE.md): Llama-2-7B >=45% MFU on a v5e-256 pod. A 7B
model does not fit one 16-GiB v5e chip, so the single-chip benchmark
uses a 410M-param Llama with the same architecture/kernels (Pallas
flash attention, remat+scan layers, bf16, fused AdamW) and reports
MFU — the hardware-normalized metric. vs_baseline = MFU / 0.45.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
# First compile can take minutes; overridable for tests
# (RT_BENCH_TPU_TIMEOUTS="5,5").
TPU_ATTEMPT_TIMEOUTS = tuple(
    float(t)
    for t in os.environ.get("RT_BENCH_TPU_TIMEOUTS", "420,300").split(",")
)
TPU_RETRY_SLEEP = float(os.environ.get("RT_BENCH_TPU_RETRY_SLEEP", "15"))
#: Total wall-clock budget for the whole orchestration (r2 verdict weak
#: #1: the bench exceeded the driver's kill window and emitted NOTHING).
#: Every phase is clipped to the remaining budget, and partial results
#: land in BENCH_PARTIAL.json the moment each phase completes, so a
#: kill at ANY point leaves the best-so-far result on disk.
TOTAL_BUDGET = float(os.environ.get("RT_BENCH_TOTAL_BUDGET", "1500"))
MICRO_TIMEOUT = float(os.environ.get("RT_BENCH_MICRO_TIMEOUT", "300"))
PARTIAL_PATH = os.path.join(REPO, "BENCH_PARTIAL.json")


def _write_partial(result: dict) -> None:
    """Persist the best-so-far bench line; crash/kill-safe via rename."""
    tmp = PARTIAL_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, PARTIAL_PATH)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# chip hygiene
# ---------------------------------------------------------------------------

def reap_stale_tpu_holders() -> int:
    """Kill leftover ray_tpu worker processes from prior runs — a
    SIGKILLed test session can leave a worker holding the TPU, which
    makes every later backend init hang (observed >550s)."""
    me = os.getpid()
    killed = 0
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(errors="ignore")
        except OSError:
            continue
        if "ray_tpu._private.worker_main" in cmd:
            try:
                os.kill(int(pid), 9)
                killed += 1
            except OSError:
                pass
    for lockfile in ("/tmp/libtpu_lockfile",):
        try:
            os.remove(lockfile)
        except OSError:
            pass
    return killed


# ---------------------------------------------------------------------------
# the measured workload (runs inside the mode subprocesses)
# ---------------------------------------------------------------------------

def peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local accelerator generation."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 1.97e14
    if "v4" in kind:
        return 2.75e14
    if "v5p" in kind or "v5" in kind:
        return 4.59e14
    if "v6" in kind or "trillium" in kind:
        return 9.2e14
    return 1.97e14  # conservative default


def run_train_bench(tpu: bool) -> dict:
    import jax

    from ray_tpu.models.llama import (
        LlamaConfig,
        flops_per_token,
        init_params,
        loss_fn,
        param_annotations,
    )
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.train_step import (
        default_optimizer,
        make_train_step,
        shard_batch,
    )

    if tpu:
        backend = jax.default_backend()
        assert backend not in ("cpu", "gpu"), f"not a TPU backend: {backend}"
        cfg = LlamaConfig.bench_410m(remat_policy="dots_flash")
        batch, seq = 8, 2048
        steps, warmup = 20, 3
    else:
        cfg = LlamaConfig.tiny()
        batch, seq = 4, 128
        steps, warmup = 3, 1

    mesh = MeshSpec(fsdp=len(jax.devices())).build()

    def loss(params, tokens, targets):
        return loss_fn(params, tokens, targets, cfg)

    optimizer = default_optimizer(total_steps=100000)
    init_fn, step_fn = make_train_step(
        loss, optimizer, mesh, param_annotations(cfg)
    )
    state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg))

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    tokens = shard_batch(tokens, mesh, logical_axes=("batch", None))
    inp, tgt = tokens[:, :-1], tokens[:, 1:]

    # float() forces a device->host transfer as the sync point
    # (block_until_ready is unreliable on experimental PJRT backends).
    for _ in range(warmup):
        state, metrics = step_fn(state, inp, tgt)
    float(metrics["loss"])

    # Compile-watch evidence: "the step compiles once at warmup" is a
    # counter, not a comment — any compile recorded for train.step
    # DURING the timed loop is a recompile storm in miniature and
    # fails --smoke (run_smoke asserts steady_state_compiles == 0).
    # The anonymous ledger is held to the same bar: warmup may compile
    # eager ops outside any instrumented program, steady state may
    # not — a nonzero delta means some jit wrap site evaded both
    # instrument() and the static RT306 gate.
    from ray_tpu._private import compile_watch as _cw

    def _unregistered() -> int:
        return _cw.snapshot().get("(unregistered)", {}).get("compiles", 0)

    warm_compiles = step_fn.stats().get("compiles", 0)
    warm_unregistered = _unregistered()

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, inp, tgt)
    final_loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    steady_compiles = step_fn.stats().get("compiles", 0) - warm_compiles
    steady_unregistered = _unregistered() - warm_unregistered
    assert final_loss == final_loss and final_loss > 0, final_loss

    n_chips = len(jax.devices())
    tokens_per_sec_chip = batch * seq / dt / n_chips
    mfu = (
        flops_per_token(cfg, seq) * tokens_per_sec_chip
        / peak_flops_per_chip()
    )
    return {
        "metric": (
            f"llama_{cfg.num_params() // 1_000_000}M_train_"
            f"tokens_per_sec_per_chip"
        ),
        "value": round(tokens_per_sec_chip, 1),
        "unit": f"tokens/s/chip (MFU={mfu:.3f}, step={dt*1e3:.0f}ms)",
        "vs_baseline": round(mfu / 0.45, 4),
        "warmup_compiles": warm_compiles,
        "steady_state_compiles": steady_compiles,
        "steady_state_unregistered_compiles": steady_unregistered,
    }


def run_7b_layer_bench() -> dict:
    """7B-shape MFU evidence on one chip (VERDICT r3 item 8): train
    steps at the EXACT Llama-2-7B layer geometry (dim 4096, 32 heads,
    intermediate 11008, seq 4096 — BASELINE.json north-star config) on
    2- and 4-layer stacks; two-point extrapolation separates per-layer
    time from fixed (embed/lm_head/data) cost and projects the
    32-layer whole-model MFU. A full 7B doesn't fit one 16-GiB v5e
    chip — this measures the same kernels at the same shapes on the
    hardware that exists."""
    import gc

    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import (
        LlamaConfig,
        flops_per_token,
        init_params,
        loss_fn,
        param_annotations,
    )
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.train_step import (
        default_optimizer,
        make_train_step,
        shard_batch,
    )

    assert jax.default_backend() != "cpu", "7b-layer bench needs the chip"
    batch, seq = 2, 4096
    steps, warmup = 5, 2

    def cfg_layers(n_layers: int) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=32000, dim=4096, n_layers=n_layers, n_heads=32,
            n_kv_heads=32, intermediate=11008, max_seq_len=seq,
            dtype=jnp.bfloat16, attention="flash", remat_policy="dots_flash",
        )

    mesh = MeshSpec(fsdp=len(jax.devices())).build()
    optimizer = default_optimizer(total_steps=100000)
    step_time = {}
    for n_layers in (2, 4):
        cfg = cfg_layers(n_layers)

        def loss(params, tokens, targets, _cfg=cfg):
            return loss_fn(params, tokens, targets, _cfg)

        init_fn, step_fn = make_train_step(
            loss, optimizer, mesh, param_annotations(cfg)
        )
        state = init_fn(
            jax.random.PRNGKey(0), lambda k, _cfg=cfg: init_params(k, _cfg)
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size
        )
        tokens = shard_batch(tokens, mesh, logical_axes=("batch", None))
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        for _ in range(warmup):
            state, metrics = step_fn(state, inp, tgt)
        float(metrics["loss"])  # sync
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, inp, tgt)
        final_loss = float(metrics["loss"])  # sync
        step_time[n_layers] = (time.perf_counter() - t0) / steps
        assert final_loss == final_loss and final_loss > 0, final_loss
        # Free the stack's HBM before the next (bigger) one compiles.
        del state, step_fn, init_fn, tokens, inp, tgt
        gc.collect()

    # A 4-layer step slower than 2-layer is required for a sane
    # two-point fit; noise inverting them would project a negative
    # per-layer time and a nonsensical 32-layer MFU into committed
    # results (ADVICE r4). Refuse to project rather than emit garbage.
    if not step_time[4] > step_time[2]:  # must survive python -O
        raise RuntimeError(
            f"unstable layer timing: 4-layer step "
            f"{step_time[4]*1e3:.1f}ms <= 2-layer step "
            f"{step_time[2]*1e3:.1f}ms — rerun on a quiet machine"
        )
    t_layer = (step_time[4] - step_time[2]) / 2
    t_fixed = max(step_time[2] - 2 * t_layer, 0.0)
    t_32 = t_fixed + 32 * t_layer
    cfg32 = cfg_layers(32)
    # Per-chip normalization (like run_train_bench): t_32 is wall time
    # across ALL local chips in the fsdp mesh.
    tokens_per_s = batch * seq / t_32 / len(jax.devices())
    mfu = flops_per_token(cfg32, seq) * tokens_per_s / peak_flops_per_chip()
    result = {
        "mfu_7b_layer_projection": round(mfu, 4),
        "tokens_per_sec_7b_projected": round(tokens_per_s, 1),
        "layer_ms": round(t_layer * 1e3, 2),
        "fixed_ms": round(t_fixed * 1e3, 2),
        "step_ms_2l": round(step_time[2] * 1e3, 1),
        "step_ms_4l": round(step_time[4] * 1e3, 1),
        "batch": batch,
        "seq": seq,
    }
    # Attribute the fixed cost: a 0-layer stack at the same geometry
    # realizes it directly, component timings name where it goes.
    try:
        breakdown = measure_fixed_breakdown(
            cfg_layers(0), batch, seq, mesh, steps, warmup
        )
        breakdown["extrapolation_residual_ms"] = round(
            t_fixed * 1e3 - breakdown["fixed_step_ms_0l"], 2
        )
        result["fixed_ms_breakdown"] = breakdown
    except Exception as e:  # noqa: BLE001 — breakdown is best-effort
        result["fixed_ms_breakdown_error"] = str(e)
    return result


def measure_fixed_breakdown(
    cfg0, batch: int, seq: int, mesh, steps: int, warmup: int
) -> dict:
    """Name the layer-count-independent share of the train step (the
    72 ms of un-attributed `fixed_ms` in BENCH_r05): train a 0-layer
    stack at the same geometry — what remains IS the fixed cost — and
    time its components separately.

    Emitted fields (all milliseconds):
      fixed_step_ms_0l  full train step on the 0-layer stack: embed +
                        lm_head fwd/bwd/loss + their optimizer update.
      optimizer_ms      jitted optimizer update alone on that state.
      embed_lm_head_ms  fixed_step_ms_0l - optimizer_ms: the
                        unavoidable compute share of fixed cost.
      dispatch_ms       python->runtime dispatch of one jitted step
                        (async on TPU; equals step time on CPU where
                        execution is synchronous).
      host_sync_ms      one scalar D2H — the per-step cost of a loop
                        that float()s the loss every step.
      input_stall_ms    H2D device_put of one fresh host batch — the
                        per-step cost of a loop WITHOUT
                        prefetch_to_device double buffering.
    dispatch/host_sync/input_stall are not components of fixed_ms (the
    ladder loop syncs once and reuses a resident batch); they are the
    avoidable host-side costs a naive loop adds on top, quantified so
    the overlap features (prefetch_batches / prefetch_to_device /
    async_save) have a measured target.
    """
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu._private import compile_watch
    from ray_tpu.models.llama import (
        init_params,
        loss_fn,
        param_annotations,
    )
    from ray_tpu.train.train_step import (
        TrainState,
        default_optimizer,
        make_train_step,
        shard_batch,
    )

    # XLA's CPU backend miscompiles SPMD buffer donation (aliased
    # input/output size mismatch) when host devices are forced, e.g.
    # under the test suite's --xla_force_host_platform_device_count=8.
    donate = jax.default_backend() != "cpu"
    optimizer = default_optimizer(total_steps=100000)
    init_fn, step_fn = make_train_step(
        lambda p, t, y: loss_fn(p, t, y, cfg0),
        optimizer,
        mesh,
        param_annotations(cfg0),
        donate=donate,
    )
    state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg0))
    host_tokens = np.asarray(
        jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg0.vocab_size
        )
    )
    tokens = shard_batch(host_tokens, mesh, logical_axes=("batch", None))
    inp, tgt = tokens[:, :-1], tokens[:, 1:]

    for _ in range(max(1, warmup)):
        state, metrics = step_fn(state, inp, tgt)
    float(metrics["loss"])  # sync

    # Dispatch cost: time for the step call to RETURN (not complete).
    dispatch = []
    for _ in range(steps):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, inp, tgt)
        dispatch.append(time.perf_counter() - t0)
    float(metrics["loss"])  # sync

    # The 0-layer step itself: the realized fixed cost.
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, inp, tgt)
    float(metrics["loss"])
    step0_ms = (time.perf_counter() - t0) / steps * 1e3

    # Optimizer-only share (update + apply on the 0-layer state).
    def opt_only(s, grads):
        updates, new_opt = optimizer.update(grads, s.opt_state, s.params)
        new_params = optax.apply_updates(s.params, updates)
        return TrainState(
            step=s.step + 1, params=new_params, opt_state=new_opt
        )

    opt_jit = compile_watch.instrument(
        "bench.opt_only",
        jax.jit(opt_only, donate_argnums=(0,) if donate else ()),  # rt: noqa[RT301] — one-shot measurement harness; constructing the wrap here IS the experiment
    )
    zero_grads = jax.tree.map(jnp.zeros_like, state.params)
    state = opt_jit(state, zero_grads)
    jax.block_until_ready(jax.tree.leaves(state.params)[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        state = opt_jit(state, zero_grads)
    jax.block_until_ready(jax.tree.leaves(state.params)[0])
    opt_ms = (time.perf_counter() - t0) / steps * 1e3

    # Host sync: scalar D2H latency, fresh arrays (jax caches _value).
    scalars = [jnp.full((), i, jnp.float32) for i in range(8)]
    jax.block_until_ready(scalars)
    syncs = []
    for s in scalars:
        t0 = time.perf_counter()
        float(s)
        syncs.append(time.perf_counter() - t0)

    # Input stall: fresh host batch -> sharded device arrays.
    puts = []
    for _ in range(5):
        t0 = time.perf_counter()
        dev = shard_batch(
            host_tokens, mesh, logical_axes=("batch", None)
        )
        jax.block_until_ready(dev)
        puts.append(time.perf_counter() - t0)

    return {
        "fixed_step_ms_0l": round(step0_ms, 2),
        "optimizer_ms": round(opt_ms, 2),
        "embed_lm_head_ms": round(max(step0_ms - opt_ms, 0.0), 2),
        "dispatch_ms": round(statistics.median(dispatch) * 1e3, 3),
        "host_sync_ms": round(statistics.median(syncs) * 1e3, 3),
        "input_stall_ms": round(statistics.median(puts) * 1e3, 2),
    }


def run_ckpt_overhead(
    steps: int = 0, every: int = 10, batch: int = 8, seq: int = 256
) -> dict:
    """Wall-time overhead of async checkpointing every `every` steps
    versus no checkpointing, same loop otherwise — the evidence behind
    'save N persists while step N+1 runs'. Runs on whatever backend
    JAX sees (the fake/CPU backend in CI). The final
    wait_for_checkpoints() barrier is INSIDE the timed window: the
    claim covers durable checkpoints, not abandoned writes."""
    import shutil
    import tempfile

    import jax

    from ray_tpu.models.llama import (
        LlamaConfig,
        init_params,
        loss_fn,
        param_annotations,
    )
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.checkpoint import CheckpointManager
    from ray_tpu.train.train_step import (
        default_optimizer,
        make_train_step,
        shard_batch,
    )

    import dataclasses

    steps = steps or int(os.environ.get("RT_BENCH_CKPT_STEPS", "40"))
    # Bigger than tiny(): the step must cost enough for a wall-time
    # ratio to mean anything on a noisy box.
    cfg = dataclasses.replace(
        LlamaConfig.tiny(), n_layers=4, dim=128, intermediate=256
    )
    mesh = MeshSpec(fsdp=len(jax.devices())).build()
    optimizer = default_optimizer(total_steps=100000)
    # Donation is broken on XLA CPU with forced host devices (see
    # measure_fixed_breakdown); the overhead ratio doesn't need it.
    init_fn, step_fn = make_train_step(
        lambda p, t, y: loss_fn(p, t, y, cfg),
        optimizer,
        mesh,
        param_annotations(cfg),
        donate=jax.default_backend() != "cpu",
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    tokens = shard_batch(tokens, mesh, logical_axes=("batch", None))
    inp, tgt = tokens[:, :-1], tokens[:, 1:]

    def run(ckpt_root) -> float:
        state = init_fn(
            jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
        )
        mgr = (
            CheckpointManager(ckpt_root, num_to_keep=2)
            if ckpt_root
            else None
        )
        for _ in range(2):
            state, metrics = step_fn(state, inp, tgt)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for i in range(steps):
            # Snapshot BEFORE the step donates the state buffers.
            if mgr is not None and i > 0 and i % every == 0:
                mgr.save(i, state, async_save=True)
            state, metrics = step_fn(state, inp, tgt)
        if mgr is not None:
            mgr.wait()  # rt: noqa[RT008] — checkpoint durability barrier, not a peer wait; the timed window must include the flush
        float(metrics["loss"])
        return time.perf_counter() - t0

    # Warm the writer path once before timing: the very first orbax
    # save pays ~seconds of one-off infra setup (asyncio machinery,
    # module imports) that a training run amortizes to zero and that
    # would otherwise be billed to "2 saves".
    import numpy as np

    from ray_tpu.train.checkpoint import (
        save_checkpoint,
        wait_for_checkpoints,
    )

    warm = tempfile.mkdtemp(prefix="rt_bench_ckpt_warm_")
    try:
        save_checkpoint(
            os.path.join(warm, "w"), {"x": np.zeros(4)}, async_save=True
        )
        wait_for_checkpoints()
    finally:
        shutil.rmtree(warm, ignore_errors=True)

    base_wall = run(None)
    tmp = tempfile.mkdtemp(prefix="rt_bench_ckpt_")
    try:
        ckpt_wall = run(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    overhead = (ckpt_wall - base_wall) / base_wall * 100.0
    return {
        "steps": steps,
        "every": every,
        "saves": max(0, (steps - 1) // every),
        "base_wall_s": round(base_wall, 3),
        "ckpt_wall_s": round(ckpt_wall, 3),
        "ckpt_overhead_pct": round(overhead, 2),
    }


# ---------------------------------------------------------------------------
# MPMD pipeline bench (`--mode pipeline`)
# ---------------------------------------------------------------------------

def _pipe_optimizer():
    """Module-level so it pickles by reference into stage actors.
    Clip-free adamw: global-norm clipping is a cross-stage reduction
    the MPMD step deliberately does not do (README)."""
    import optax

    return optax.adamw(3e-4)


def _measure_hop_ms(nbytes: int, laps: int = 30) -> float:
    """Per-record channel transport cost (pickle + ring copy both
    directions) at the pipeline's activation size — the hop cost the
    schedule replay charges on cross-stage dependency edges."""
    import pickle

    import numpy as np

    from ray_tpu.dag.channels import ShmChannel

    payload = (("F", 0, 0), np.zeros(max(nbytes, 1), np.uint8))
    chan = ShmChannel(2 * nbytes + (1 << 16))
    try:
        for _ in range(3):
            chan.put_bytes(pickle.dumps(("v", payload)))
            pickle.loads(chan.get_bytes())
        t0 = time.perf_counter()
        for _ in range(laps):
            chan.put_bytes(pickle.dumps(("v", payload)))
            pickle.loads(chan.get_bytes())
        return (time.perf_counter() - t0) / laps * 1e3
    finally:
        chan.close()
        chan.unlink()


def _pipeline_point(
    cfg, n: int, m: int, v: int, mb: int, seq: int,
    warmup: int, steps: int, hop_ms: float,
) -> dict:
    """Measure one MPMD geometry: build the pipeline, run warmup +
    timed steps, and fold the per-stage op timings into (a) real wall
    tokens/s and (b) the schedule replay (`simulate_schedule` over
    MEASURED per-op costs) whose efficiency is comparable to the
    m/(m+(n-1)/v) bound even when stages time-share this box's
    core(s)."""
    import statistics

    import numpy as np

    import jax
    from ray_tpu.parallel.schedule import (
        simulate_schedule,
        theoretical_efficiency,
    )
    from ray_tpu.train.mpmd_pipeline import MPMDPipeline

    B = m * mb
    pipe = MPMDPipeline(
        cfg, n, num_microbatches=m, microbatch_size=mb,
        seq_len=seq, chunks_per_stage=v,
        optimizer_factory=_pipe_optimizer,
        hop_timeout_s=120, step_timeout_s=600,
    )
    try:
        tokens = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (B, seq + 1), 0, cfg.vocab_size
        ))
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        first_loss = None
        for _ in range(warmup):
            out = pipe.step(inp, tgt)
            if first_loss is None:
                first_loss = out["loss"]
        walls, op_samples, stage_rows = [], {}, []
        for _ in range(steps):
            t0 = time.perf_counter()
            out = pipe.step(inp, tgt)
            walls.append(time.perf_counter() - t0)
            for stage in out["stages"]:
                for key, vals in stage["op_ms"].items():
                    op_samples.setdefault(key, []).extend(vals)
        # Wait/busy breakdown from the LAST timed step (steady state).
        for stage in out["stages"]:
            waits = {"send_wait_ms": 0.0, "recv_wait_ms": 0.0}
            for edge in stage["edges"]:
                waits["send_wait_ms"] += edge["send_wait_ms"]
                waits["recv_wait_ms"] += edge["recv_wait_ms"]
            stage_rows.append({
                "stage": stage["stage"],
                "busy_ms": stage["busy_ms"],
                "opt_ms": stage["opt_ms"],
                "wall_ms": stage["wall_ms"],
                "send_wait_ms": round(waits["send_wait_ms"], 3),
                "recv_wait_ms": round(waits["recv_wait_ms"], 3),
                "stash_peak": stage["stash_peak"],
            })
        med_op = {
            key: statistics.median(vals)
            for key, vals in op_samples.items()
        }
        sim = simulate_schedule(
            pipe.schedules,
            lambda kind, c, _mb: med_op.get(f"{kind}:{c}", 0.0) / 1e3,
            hop_cost_s=hop_ms / 1e3,
        )
        wall = statistics.median(walls)
        bound = theoretical_efficiency(n, m, v)
        eff = sim["efficiency"]
        return {
            "n_stages": n,
            "num_microbatches": m,
            "chunks_per_stage": v,
            "tokens_per_s": round(B * seq / wall, 1),
            "step_wall_ms": round(wall * 1e3, 1),
            "loss_first_step": round(first_loss, 6),
            "pipeline_efficiency": round(eff, 4),
            "theoretical_bound": round(bound, 4),
            "bound_ratio": round(bound / eff, 4) if eff else None,
            "sim_step_ms": round(sim["wall_s"] * 1e3, 1),
            "wall_efficiency_this_box": round(
                sum(r["busy_ms"] for r in stage_rows)
                / (n * wall * 1e3),
                4,
            ),
            "stash_bound": pipe.stash_bound,
            "stages": stage_rows,
        }
    finally:
        pipe.shutdown()


def _pipeline_baseline(cfg, n: int, m: int, mb: int, seq: int,
                       warmup: int, steps: int) -> dict:
    """The single-program GPipe baseline at identical geometry: the
    whole schedule inside one jitted SPMD program over a pp mesh
    (train/pipeline_step.py) — what PR-era pipelining was."""
    import statistics

    import numpy as np

    import jax
    from jax.sharding import Mesh
    from ray_tpu.models.llama import init_params
    from ray_tpu.train.pipeline_step import make_pp_train_step

    B = m * mb
    devs = np.array(jax.devices()[:n]).reshape(n, 1, 1)
    mesh = Mesh(devs, ("pp", "sp", "ep"))
    # SAME optimizer as the MPMD side (clip-free adamw) — the
    # comparison must measure pipeline structure, not an optimizer
    # cost asymmetry (default_optimizer's global-norm clip is an
    # extra full-tree reduction the MPMD step deliberately omits).
    init_fn, step_fn = make_pp_train_step(
        cfg, mesh, _pipe_optimizer(),
        num_microbatches=m,
        donate=jax.default_backend() != "cpu",
    )
    state = init_fn(
        jax.random.PRNGKey(0), lambda k: init_params(k, cfg)
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (B, seq + 1), 0, cfg.vocab_size
    )
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    first_loss = None
    for _ in range(max(warmup, 1)):
        state, metrics = step_fn(state, inp, tgt)
        if first_loss is None:
            first_loss = float(metrics["loss"])
    float(metrics["loss"])  # sync
    walls = []
    for _ in range(steps):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, inp, tgt)
        float(metrics["loss"])  # sync
        walls.append(time.perf_counter() - t0)
    wall = statistics.median(walls)
    return {
        "n_stages": n,
        "num_microbatches": m,
        "tokens_per_s": round(B * seq / wall, 1),
        "step_wall_ms": round(wall * 1e3, 1),
        "loss_first_step": round(first_loss, 6),
    }


def _project_7b_pipeline() -> dict | None:
    """Refresh the 7B MFU projection from MEASURED multi-stage
    numbers: per-layer/fixed costs are the chip-measured BENCH_r05
    `7b_layer` ladder (v5e), the schedule cost comes from replaying
    the 1F1B op list (the same replay validated against this box's
    real multi-stage runs), and the hop cost from this box's measured
    channel throughput at the 7B activation size (conservative: ICI
    is faster than host shm). Replaces the single-program
    extrapolation `mfu_7b_layer_projection` with a number that prices
    in the pipeline bubble + boundary transport."""
    import json as _json

    bench_path = os.path.join(REPO, "BENCH_r05.json")
    try:
        with open(bench_path) as f:
            seven = _json.load(f)["parsed"]["7b_layer"]
    except (OSError, KeyError, ValueError):
        return None
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.schedule import (
        interleaved_1f1b,
        partition_layers,
        simulate_schedule,
        theoretical_efficiency,
    )

    layer_ms = seven["layer_ms"]
    fixed_ms = seven["fixed_ms"]
    batch, seq = seven["batch"], seven["seq"]
    n, m, v = 4, 16, 1
    n_layers = 32
    # lm_head+loss dominates the fixed cost at vocab 32000 (embed is
    # a gather); load the ends 20/80 so the partitioner can shed
    # layers from the loaded chunks.
    bounds = partition_layers(
        n_layers, n * v, [layer_ms] * n_layers,
        embed_ms=0.2 * fixed_ms, head_ms=0.8 * fixed_ms,
    )
    chunk_ms = []
    for c, (lo, hi) in enumerate(bounds):
        cost = (hi - lo) * layer_ms
        if c == 0:
            cost += 0.2 * fixed_ms
        if c == n * v - 1:
            cost += 0.8 * fixed_ms
        chunk_ms.append(cost)
    # The ladder's step time is fwd+bwd(+opt) per microbatch-shaped
    # batch; split 1/3 forward, 2/3 backward (standard 2x bwd). The
    # hop cost is MEASURED at the 7B boundary-activation size (~64 MB
    # of bf16 per microbatch) on this box's shm channel.
    act_bytes = batch * seq * 4096 * 2  # bf16 activations
    hop_ms = _measure_hop_ms(act_bytes, laps=5)

    def op_cost(kind, c, _mb):
        share = 1 / 3 if kind == "F" else 2 / 3
        return chunk_ms[c] * share / 1e3

    schedules = interleaved_1f1b(n, m, v)
    cfg32 = LlamaConfig(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
        n_kv_heads=32, intermediate=11008, max_seq_len=seq,
    )
    from ray_tpu.models.llama import flops_per_token

    tokens_per_step = m * batch * seq

    def mfu_at(hop_s: float) -> tuple:
        sim = simulate_schedule(schedules, op_cost, hop_cost_s=hop_s)
        tokens_per_s_chip = tokens_per_step / sim["wall_s"] / n
        mfu = (
            flops_per_token(cfg32, seq) * tokens_per_s_chip
            / peak_flops_per_chip()
        )
        return mfu, sim["efficiency"], tokens_per_s_chip

    # Two transports: this box's measured shm channel (the honest
    # floor — a pod would never ship activations this slowly), and
    # ICI at a conservative 40 GB/s effective per link, which is the
    # deployment the projection is FOR.
    mfu_shm, eff_shm, tps_shm = mfu_at(hop_ms / 1e3)
    ici_gbps = 40.0
    hop_ici_ms = act_bytes / (ici_gbps * 1e9) * 1e3
    mfu_ici, eff_ici, tps_ici = mfu_at(hop_ici_ms / 1e3)
    return {
        "mfu_7b_pipeline_projection": round(mfu_ici, 4),
        "tokens_per_sec_7b_per_chip": round(tps_ici, 1),
        "pipeline_efficiency": round(eff_ici, 4),
        "hop_ms_ici": round(hop_ici_ms, 2),
        "ici_assumed_gbps": ici_gbps,
        "floor_shm_transport": {
            "mfu": round(mfu_shm, 4),
            "tokens_per_sec_per_chip": round(tps_shm, 1),
            "pipeline_efficiency": round(eff_shm, 4),
            "hop_ms": round(hop_ms, 2),
        },
        "n_stages": n,
        "num_microbatches": m,
        "theoretical_bound": round(
            theoretical_efficiency(n, m, v), 4
        ),
        "stage_boundaries": bounds,
        "inputs": {
            "layer_ms": layer_ms,
            "fixed_ms": fixed_ms,
            "source": "BENCH_r05 7b_layer (chip-measured ladder)",
            "hop_cost_floor": (
                "this box's shm channel MEASURED at 64MB records"
            ),
        },
        "method": (
            "1F1B replay over chip-measured per-layer/fixed costs "
            "with per-hop transport cost — multi-stage schedule + "
            "boundary transport priced in, unlike the single-program "
            "extrapolation; the replay machinery is validated "
            "against this bench's real multi-stage runs (sim_step_ms "
            "vs step_wall_ms per point)"
        ),
    }


def run_pipeline_bench(smoke: bool) -> dict:
    """`bench.py --mode pipeline`: the MPMD 1F1B trajectory — real
    multi-process stage gangs over channels vs the single-program
    GPipe baseline at identical geometry, with measured pipeline
    efficiency vs the theoretical bubble bound and a refreshed 7B MFU
    projection. Writes PIPEBENCH.json (full mode).

    HONEST LIMIT on a 1-core box: n stage processes time-share the
    core, so raw wall numbers cannot show stage concurrency —
    `pipeline_efficiency` therefore comes from replaying the executed
    schedule with each stage's MEASURED per-op times on its own
    executor (`simulate_schedule`), committed next to the raw walls
    it derives from. The baseline comparison needs no such care: the
    single-program GPipe really does pay its masked-tick FLOPs and
    SPMD partitioning overhead on any host, so beating its wall
    tokens/s is a real, like-for-like win."""
    import dataclasses

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()

    import jax.numpy as jnp

    import ray_tpu as rt
    from ray_tpu.models.llama import LlamaConfig

    t_start = time.perf_counter()
    tiny = LlamaConfig(
        vocab_size=128, dim=64, n_layers=4, n_heads=4,
        n_kv_heads=4, intermediate=128, max_seq_len=64,
        dtype=jnp.float32, attention="reference",
    )
    medium = LlamaConfig(
        vocab_size=512, dim=128, n_layers=8, n_heads=8,
        n_kv_heads=8, intermediate=256, max_seq_len=128,
        dtype=jnp.float32, attention="reference",
    )
    large = LlamaConfig(
        vocab_size=1024, dim=256, n_layers=8, n_heads=8,
        n_kv_heads=8, intermediate=512, max_seq_len=128,
        dtype=jnp.float32, attention="reference",
    )
    if smoke:
        scales = [("tiny", tiny, 2, 32, [(2, 2, 1), (2, 8, 1)],
                   [(2, 2), (2, 8)], 1, 2)]
    else:
        # Three model scales on purpose: they trace the regime
        # boundary this one-core box can actually exhibit. At `tiny`
        # and `medium` per-microbatch compute is small enough that
        # the fused single program's near-zero per-op dispatch beats
        # MPMD's per-op python/pickle/handoff cost, masked-tick
        # waste and all; at `large` (4 stages x 8 microbatches: the
        # baseline burns (n-1)/(m+n-1) = 27% of its FLOPs on masked
        # ticks) compute dominates overhead and MPMD's
        # never-computed bubble turns into a measured wall-clock win
        # even with every stage time-sharing one core. On real
        # parallel hardware the win is larger — that is what the
        # replay efficiency + 7B projection price.
        scales = [
            ("tiny", tiny, 2, 32,
             [(2, 2, 1), (2, 8, 1)],
             [(2, 2), (2, 8)], 2, 4),
            ("medium", medium, 2, 64,
             [(2, 2, 1), (2, 4, 1), (2, 8, 1), (2, 16, 1),
              (2, 8, 2), (4, 16, 1)],
             [(2, 2), (2, 8), (2, 16), (4, 16)], 2, 4),
            ("large", large, 2, 128,
             [(4, 8, 1)], [(4, 8)], 1, 3),
        ]

    points, base_rows = [], []
    hop_by_scale = {}
    for (name, cfg, mb, seq, geometries, baselines, warmup,
         steps) in scales:
        itemsize = jnp.dtype(cfg.dtype).itemsize
        hop_ms = _measure_hop_ms(mb * seq * cfg.dim * itemsize)
        hop_by_scale[name] = round(hop_ms, 3)
        rt.init(num_cpus=6)
        try:
            for n, m, v in geometries:
                point = _pipeline_point(
                    cfg, n, m, v, mb, seq, warmup, steps, hop_ms
                )
                point["model"] = name
                points.append(point)
        finally:
            rt.shutdown()
        for n, m in baselines:
            base = _pipeline_baseline(
                cfg, n, m, mb, seq, warmup, steps
            )
            base["model"] = name
            base_rows.append(base)

    base_by = {
        (b["model"], b["n_stages"], b["num_microbatches"]): b
        for b in base_rows
    }
    for p in points:
        base = base_by.get(
            (p["model"], p["n_stages"], p["num_microbatches"])
        )
        if base and p["chunks_per_stage"] == 1:
            p["vs_single_program"] = round(
                p["tokens_per_s"] / base["tokens_per_s"], 2
            )
            p["loss_matches_baseline"] = bool(
                abs(p["loss_first_step"] - base["loss_first_step"])
                < 1e-3 * max(1.0, abs(base["loss_first_step"]))
            )
    # Headline: the strongest MPMD-vs-baseline point; the full
    # trajectory — including the medium-model points where the fused
    # single program wins on this one-core box — is committed right
    # below it.
    top = max(
        (p for p in points if "vs_single_program" in p),
        key=lambda p: p["vs_single_program"],
    )
    result = {
        "metric": "mpmd_pipeline_tokens_per_s",
        "value": top["tokens_per_s"],
        "unit": (
            f"tokens/s ({top['model']} model, {top['n_stages']} "
            f"stages x {top['num_microbatches']} microbatches, CPU)"
        ),
        "vs_baseline": top["vs_single_program"],
        "smoke": bool(smoke),
        "host_cpus": os.cpu_count(),
        "models": {
            name: {
                "dim": cfg.dim, "n_layers": cfg.n_layers,
                "vocab": cfg.vocab_size, "seq": seq,
                "microbatch_size": mb,
            }
            for name, cfg, mb, seq, _g, _b, _w, _s in scales
        },
        "hop_ms": hop_by_scale,
        "points": points,
        "single_program_baseline": base_rows,
        "notes": (
            "pipeline_efficiency = schedule replay over measured "
            "per-op stage times (1-core box serializes stages; see "
            "run_pipeline_bench docstring); wall tokens/s and the "
            "baseline comparison are raw measurements; the two model "
            "scales bracket the overhead-bound vs compute-bound "
            "regimes"
        ),
    }
    if not smoke:
        projection = _project_7b_pipeline()
        if projection is not None:
            result["mfu_7b_pipeline"] = projection
        result["wall_s"] = round(time.perf_counter() - t_start, 1)
        with open(os.path.join(REPO, "PIPEBENCH.json"), "w") as f:
            json.dump(result, f, indent=2)
    return result


def run_smoke(skip_micro: bool) -> dict:
    """`bench.py --smoke`: the whole bench surface in seconds, on CPU
    — a CI gate that the bench code itself runs (train step, fixed-
    cost breakdown, async-checkpoint overhead, a micro sample), not a
    performance measurement."""
    import dataclasses

    # Hermetic and quick: never wait on a TPU plugin in smoke mode.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

    t0 = time.perf_counter()
    result: dict = {
        "metric": "bench_smoke",
        "unit": "composite (CPU, tiny configs; numbers are not perf)",
        "vs_baseline": 0.0,
        "smoke": True,
    }
    train = run_train_bench(tpu=False)
    train["cpu_fallback"] = True
    result["value"] = train["value"]
    result["train"] = train
    # The PR 11/15 compile contract, enforced where CI reads it: the
    # train step compiles at warmup and NEVER during the timed loop.
    # A nonzero count here is a recompile storm in miniature — fail
    # loudly instead of shipping a slower "goodput" number.
    assert train.get("steady_state_compiles", 0) == 0, (
        f"train.step recompiled {train['steady_state_compiles']}x in "
        "steady state — shape drift in the bench loop "
        "(see `ray_tpu doctor` verdict.compile)"
    )
    # Tighter than "train.step compiles == 0": NO program — named or
    # anonymous — may compile during the timed loop. A nonzero
    # "(unregistered)" delta means a jit wrap site is invisible to the
    # compile watch (missed instrument(); the static analyzer flags
    # these as RT306 — run `ray_tpu devtools accel`).
    assert train.get("steady_state_unregistered_compiles", 0) == 0, (
        f"{train['steady_state_unregistered_compiles']} anonymous "
        "compile(s) during the timed loop — an uninstrumented jit is "
        "compiling in steady state (run `ray_tpu devtools accel`)"
    )

    import jax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec

    cfg0 = dataclasses.replace(LlamaConfig.tiny(), n_layers=0)
    mesh = MeshSpec(fsdp=len(jax.devices())).build()
    result["fixed_ms_breakdown"] = measure_fixed_breakdown(
        cfg0,
        batch=8 * len(jax.devices()) if len(jax.devices()) > 1 else 8,
        seq=128,
        mesh=mesh,
        steps=3,
        warmup=1,
    )
    result["ckpt_overhead"] = run_ckpt_overhead(
        steps=int(os.environ.get("RT_BENCH_SMOKE_CKPT_STEPS", "20"))
    )
    if not skip_micro:
        result["micro"] = run_micro_smoke()
    result["smoke_wall_s"] = round(time.perf_counter() - t0, 1)
    return result


def run_micro_smoke() -> dict:
    """Two cheap micro cases proving the runtime path works — not the
    committed suite."""
    import ray_tpu as rt

    results: dict = {}
    rt.init(num_cpus=2)
    try:
        @rt.remote
        def nop():
            return None

        rt.get(nop.remote(), timeout=60)
        results["task_roundtrip_per_s"] = _micro_case(
            lambda: rt.get(nop.remote(), timeout=30), 30, trials=2
        )
        small = b"y" * (10 * 1024)
        results["put_get_10kb_per_s"] = _micro_case(
            lambda: rt.get(rt.put(small), timeout=30), 30, trials=2
        )
        # Batched submit path (submit_tasks/execute_tasks coalescing):
        # a 300-task flood outruns replies, so CI exercises multi-spec
        # frames, per-spec fulfillment, and the in-flight window.
        def _s2c_trial() -> float:
            t0 = time.perf_counter()
            rt.get([nop.remote() for _ in range(300)], timeout=120)
            return 300 / (time.perf_counter() - t0)

        results["task_submitted_to_completed_per_s"] = _micro_case_from(
            _s2c_trial, trials=2, warmup=1
        )
        # XLA compile counters reach the Prometheus exposition end to
        # end (ISSUE 15): one instrumented compile in this process
        # must render as a program-labeled rt_jax_compiles_total
        # series on the head's /metrics text.
        import jax
        import jax.numpy as jnp

        from ray_tpu._private import compile_watch
        from ray_tpu.util import metrics as um
        from ray_tpu.util.prometheus import render_prometheus

        smoke_fn = compile_watch.instrument(
            "bench.smoke_probe", jax.jit(lambda x: x + 1)  # rt: noqa[RT301] — deliberate one-shot probe: the point is to observe this compile
        )
        smoke_fn(jnp.zeros((4,), jnp.float32))
        um.flush()
        text = render_prometheus(um.metrics_summary())
        assert (
            'rt_jax_compiles_total{program="bench.smoke_probe"}'
            in text
        ), "rt_jax_compiles_total missing from /metrics exposition"
        assert "rt_jax_compile_ms_bucket" in text
        results["compile_exposition_ok"] = True
    finally:
        rt.shutdown()
    return results


# ---------------------------------------------------------------------------
# op/s microbenchmarks (reference: ray_perf.py cases)
# ---------------------------------------------------------------------------

#: Trials per micro case (VERDICT r3 weak #2: single-shot numbers on a
#: shared box spanned a 4x band; medians over >=5 trials with an IQR
#: make committed numbers reproducible. Reference:
#: ray_microbenchmark_helpers.py timeit runs multiple trials too).
MICRO_TRIALS = int(os.environ.get("RT_BENCH_MICRO_TRIALS", "5"))
#: Inter-trial max/min spread beyond which a case is ANNOTATED
#: "unstable" in the committed JSON (the number still lands — hiding
#: noisy cases would overstate stability; readers filter on the flag).
MICRO_MAX_SPREAD = float(os.environ.get("RT_BENCH_MICRO_MAX_SPREAD", "3.0"))
#: Untimed laps before the first trial of every case: the first lap
#: after a workload switch pays worker wake/branch-cache/page-fault
#: costs no steady-state trial sees (r5 flagged put_get_64mb at 3.07x
#: largely on cold first trials). 2 laps: the SECOND lap after a
#: switch still pays residual allocator/page churn the first lap
#: uncovered — observed on the two `unstable`-flagged cases.
MICRO_WARMUP = int(os.environ.get("RT_BENCH_MICRO_WARMUP", "2"))
#: Quiet-run policy: when the central band is still wider than
#: MICRO_MAX_SPREAD, keep sampling up to this many extra trials
#: before flagging — one burst of box contention must not stamp
#: "unstable" into a committed artifact.
MICRO_EXTRA_TRIALS = int(os.environ.get("RT_BENCH_MICRO_EXTRA_TRIALS", "6"))


def _timeit(fn, n: int) -> float:
    """ops/sec of fn() called n times (fn performs one op)."""
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return n / (time.perf_counter() - t0)


def _quiet_band(rates: list) -> list:
    """Sorted central band of the samples: with >=5 trials the single
    min and max are dropped, with >=9 two per side, with >=13 three —
    stability is judged on the quiet core, not on the trials that
    collided with a cron job. The wider trim at higher counts is what
    makes the quiet-run policy converge: extra trials EARN a wider
    trim instead of dragging one outlier along forever."""
    s = sorted(rates)
    if len(s) >= 13:
        return s[3:-3]
    if len(s) >= 9:
        return s[2:-2]
    if len(s) >= 5:
        return s[1:-1]
    return s


def _micro_case(fn, n: int, scale: float = 1.0, digits: int = 1,
                trials: int = 0, warmup: int = -1) -> dict:
    """Run one micro case MICRO_TRIALS times; report the median rate
    with its IQR so a reader can judge stability, and flag (not hide)
    noisy cases whose spread exceeds MICRO_MAX_SPREAD. `scale`
    converts calls/s to the case's unit (ops per call, bytes->GB).
    `trials` overrides MICRO_TRIALS for short-lap cases that need
    more samples to find a stable median on a busy 1-core box.

    Quiet-run trial policy: `warmup` untimed laps run first; spread is
    judged on the central band (min/max trimmed at >=5 samples), and a
    case over the limit earns up to MICRO_EXTRA_TRIALS more samples
    to find its quiet core before the unstable flag lands. The
    reported trial count is the total actually run.
    """
    return _micro_case_from(
        lambda: _timeit(fn, n) * scale,
        digits=digits, trials=trials, warmup=warmup,
    )


def _micro_case_from(trial_fn, digits: int = 1, trials: int = 0,
                     warmup: int = -1) -> dict:
    """The quiet-band trial policy over a trial function that returns
    its own rate — for cases whose timed window must exclude a phase
    (e.g. submit-rate cases that drain completions off the clock)."""
    import statistics

    for _ in range(MICRO_WARMUP if warmup < 0 else warmup):
        trial_fn()
    rates = [trial_fn() for _ in range(trials or MICRO_TRIALS)]
    extra = MICRO_EXTRA_TRIALS

    def spread(band: list) -> float:
        return band[-1] / band[0] if band[0] > 0 else float("inf")

    band = _quiet_band(rates)
    while spread(band) > MICRO_MAX_SPREAD and extra > 0:
        rates.append(trial_fn())
        extra -= 1
        band = _quiet_band(rates)
    q = statistics.quantiles(band, n=4) if len(band) >= 3 else band
    result = {
        "median": round(statistics.median(band), digits),
        "iqr": round((q[2] - q[0]) if len(band) >= 3 else 0.0, digits),
        "trials": len(rates),
    }
    if spread(band) > MICRO_MAX_SPREAD:
        result["unstable"] = round(spread(band), 2)
    return result


def run_micro() -> dict:
    import numpy as np

    import ray_tpu as rt

    results: dict = {}

    # 0. paged-KV block allocator: alloc/free cycle rate (ISSUE 11).
    # Pure host-side bookkeeping on the serving engine's admission/
    # retirement hot path — no cluster, measured before init so no
    # runtime thread pollutes it. One op = reserve + release of an
    # 8-block request against a 4096-block pool (the shape of one
    # chat-request lifetime); a regression here taxes every engine
    # admission.
    from ray_tpu.llm.kv_slots import BlockAllocator

    kv_alloc = BlockAllocator(4096)

    def _kv_cycle():
        kv_alloc.release(kv_alloc.reserve(8))

    results["kv_block_alloc_per_s"] = _micro_case(_kv_cycle, 2000)

    # 0a2. XLA compile-watch hot path (ISSUE 15): µs per already-seen
    # call through an instrumented program — the digest build + one
    # set lookup every watched train step / engine decode pays. Arg
    # tree mimics a real step call (state dataclass wrapping a nested
    # param dict of ~100 array leaves + two batch arrays), the worst
    # common shape for the digest walk. No cluster; jax is loaded
    # (the digest's C tree_flatten fast path — production always has
    # it) but the wrapped fn is a no-op, so the measured cost IS the
    # watcher. The hard bar (<1% of a smoke step) is a unit test
    # (tests/test_compile_watch.py); this tracks the trend.
    import jax as _cw_jax  # noqa: F401 — enables the digest fast path
    import numpy as _cw_np

    from ray_tpu._private import compile_watch as _cw

    _cw_params = {
        f"layer_{i}": {
            "attn": {
                "wq": _cw_np.zeros((4, 4), _cw_np.float32),
                "wk": _cw_np.zeros((4, 4), _cw_np.float32),
                "wv": _cw_np.zeros((4, 4), _cw_np.float32),
                "wo": _cw_np.zeros((4, 4), _cw_np.float32),
            },
            "mlp": {
                "w1": _cw_np.zeros((4, 8), _cw_np.float32),
                "w2": _cw_np.zeros((8, 4), _cw_np.float32),
            },
        }
        for i in range(16)
    }
    _cw_batch = _cw_np.zeros((8, 128), _cw_np.int32)
    _cw_fn = _cw.instrument(
        "bench.compile_watch_overhead", lambda *a, **k: None
    )
    _cw_fn(_cw_params, _cw_batch, _cw_batch)  # seed the digest set

    def _cw_trial() -> float:
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            _cw_fn(_cw_params, _cw_batch, _cw_batch)
        return (time.perf_counter() - t0) / n * 1e6

    results["compile_watch_overhead_us"] = _micro_case_from(
        _cw_trial, digits=3
    )

    # 0a2. lock-witness overhead (ISSUE 16): per acquire/release PAIR
    # of an instrumented nested-lock pair in steady state (the order
    # edge already recorded — first sighting pays the one-time stack
    # capture). The OFF cost is structurally zero (make_lock hands out
    # raw threading locks, no wrapper), so only the on-cost is a
    # number worth tracking; tests/test_concurrency_analysis.py holds
    # it under 1% of a smoke step.
    from ray_tpu.devtools import lock_witness as _lw

    def _lw_trial() -> float:
        _lw.install()
        outer = _lw.make_lock("bench.outer")  # rt: noqa[RT205] — microbench constructs fresh witnessed locks on purpose
        inner = _lw.make_lock("bench.inner")  # rt: noqa[RT205] — ditto; the acquire cost of these locks is the measurement
        with outer:
            with inner:  # seed the order edge (stack capture here)
                pass
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            with outer:
                with inner:
                    pass
        dt = (time.perf_counter() - t0) / n * 1e6
        _lw.uninstall()
        return dt

    results["lock_witness_overhead_us"] = _micro_case_from(
        _lw_trial, digits=3
    )

    # 0b. RL rollout queue: put + get cycle rate (ISSUE 13). Pure
    # host-side bookkeeping on the decoupled dataflow's hand-off hot
    # path — both staleness gates evaluated per put, occupancy
    # accounting per op, no cluster (metrics drop outside a session).
    # One op = offer one wrapped-ref fragment + drain it, the shape
    # of one fragment's queue lifetime; a regression here taxes every
    # rollout fragment end to end.
    from ray_tpu.rl.rollout_queue import RolloutQueue

    rl_queue = RolloutQueue(capacity=64, max_weight_lag=4)
    _frag = {"ref": ["sentinel"]}
    _meta = {"weight_version": 0, "env_steps": 512}

    def _queue_cycle():
        rl_queue.put(_frag, _meta)
        rl_queue.get_batch(1)

    results["rollout_queue_put_get_per_s"] = _micro_case(
        _queue_cycle, 2000
    )

    # 0c. memory-ledger report fold at 10k live objects (ISSUE 14):
    # the off-path fold every daemon runs each
    # memory_report_interval_s. Pure host-side bookkeeping, measured
    # in ms per fold — at the 5 s default interval this must stay
    # far below 1% of a tick so report overhead is invisible in the
    # --smoke step medians (the PR 5 flight-recorder bar).
    from ray_tpu._private.ids import ObjectID as _MLObjectID
    from ray_tpu._private.ids import TaskID as _MLTaskID
    from ray_tpu._private.memory_ledger import build_node_report

    _ml_task = _MLTaskID.from_random()
    _ml_entries = [
        (
            _MLObjectID.for_return(_ml_task, i + 1),
            (i % 64 + 1) * 4096,
            f"{i % 8:08x}",                # 8 jobs
            f"task:{i % 200:040x}",        # 200 owners
            0,                             # no pid probes in the fold
            100.0,
            i % 3 == 0,
            i % 17 == 0,
            True,
        )
        for i in range(10_000)
    ]
    _ml_size_info = {
        "used": sum(e[1] for e in _ml_entries),
        "capacity": 1 << 34,
        "num_objects": len(_ml_entries),
    }

    def _report_fold_trial() -> float:
        t0 = time.perf_counter()
        for _ in range(5):
            build_node_report(
                "benchnode",
                _ml_entries,
                _ml_size_info,
                {"spilled_bytes": 0, "spilled_objects": 0},
                topk=20,
                now=200.0,
                pid_alive=lambda pid: True,
            )
        return (time.perf_counter() - t0) * 1e3 / 5

    results["memory_report_ms"] = _micro_case_from(
        _report_fold_trial, digits=3
    )

    # 8 CPUs: the suite holds up to 6 live actors (1 latency counter,
    # 4 n:n actors, 1 DAG echo) plus task workers.
    rt.init(num_cpus=8)
    try:
        @rt.remote
        def nop():
            return None

        @rt.remote
        def small_arg(x):
            return x

        @rt.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        # Latency cases run FIRST with a single warm worker: on a
        # low-core box, 8 idle worker processes time-share the CPU in
        # scheduler quanta and distort sub-ms roundtrip numbers.
        rt.get(nop.remote(), timeout=60)

        # 1. sequential task round-trips (submit+get latency)
        results["task_roundtrip_per_s"] = _micro_case(
            lambda: rt.get(nop.remote(), timeout=30), 200
        )

        # 4b early. actor: sequential call latency (single worker warm)
        counter0 = Counter.remote()
        rt.get(counter0.inc.remote(), timeout=30)
        results["actor_call_roundtrip_per_s"] = _micro_case(
            lambda: rt.get(counter0.inc.remote(), timeout=30), 200
        )

        # 7 early. put/get small (inline path)
        small = b"y" * (10 * 1024)
        results["put_get_10kb_per_s"] = _micro_case(
            lambda: rt.get(rt.put(small), timeout=30), 200
        )

        # 7b. get-provenance instrument (ISSUE 20): the classify+fold
        # every rt.get resolution pays — provenance-key fold under the
        # stats lock plus drain-hook arming (phase billing gates out
        # here: no task context on the bench driver, exactly like any
        # driver get). Held under 1% of a --smoke step by
        # tests/test_data_plane.py.
        from ray_tpu._private.worker import global_worker as _gp_gw

        _gp_worker = _gp_gw()

        def _gp_trial() -> float:
            n = 5000
            t0 = time.perf_counter()
            for _ in range(n):
                _gp_worker._record_get("local", "", 4096, 0.05)
            return (time.perf_counter() - t0) / n * 1e6

        results["get_provenance_overhead_us"] = _micro_case_from(
            _gp_trial, digits=3
        )

        # warm the worker pool for the throughput cases
        rt.get([nop.remote() for _ in range(8)], timeout=60)

        def _burst(submit, k: int) -> None:
            rt.get([submit() for _ in range(k)], timeout=120)

        # 2. pipelined task throughput
        # Note: the first burst pays cold worker spawns inside the
        # timed window (500 tasks fan out to the whole pool), so trial
        # 1 can read BELOW the hot single-worker roundtrip number —
        # a real cost profile the median then absorbs.
        results["task_throughput_per_s"] = _micro_case(
            lambda: _burst(nop.remote, 100), 5, scale=100
        )

        # 2b. batched submission: driver-side submit rate through the
        # coalescing pipeline (completions drain OFF the clock — this
        # is the `.remote()` ingest rate an RL/dataflow driver sees),
        # and the end-to-end submitted-to-completed rate the same
        # flood sustains (the scalebench tasks_100k number's micro
        # twin). Both ride the batch path by construction: a 2000-task
        # loop outruns replies, so specs coalesce into multi-spec
        # execute_tasks frames.
        def _submit_batch_trial() -> float:
            t0 = time.perf_counter()
            refs = [nop.remote() for _ in range(2000)]
            dt = time.perf_counter() - t0
            rt.get(refs, timeout=120)  # drain outside the timed window
            return 2000 / dt

        results["task_submit_batch_per_s"] = _micro_case_from(
            _submit_batch_trial
        )

        def _s2c_trial() -> float:
            t0 = time.perf_counter()
            rt.get([nop.remote() for _ in range(2000)], timeout=120)
            return 2000 / (time.perf_counter() - t0)

        results["task_submitted_to_completed_per_s"] = _micro_case_from(
            _s2c_trial
        )

        # 3. tasks with a small inline arg
        payload = b"x" * 1024
        results["task_1kb_arg_per_s"] = _micro_case(
            lambda: _burst(lambda: small_arg.remote(payload), 100),
            3,
            scale=100,
        )

        # 4. actor latency measured above pre-fan-out; pipelined below.
        counter = Counter.remote()
        rt.get(counter.inc.remote(), timeout=30)

        # 5. actor: pipelined calls
        results["actor_call_throughput_per_s"] = _micro_case(
            lambda: _burst(counter.inc.remote, 100), 5, scale=100
        )

        # 6. n:n actor calls (4 actors, pipelined)
        actors = [Counter.remote() for _ in range(4)]
        rt.get([a.inc.remote() for a in actors], timeout=60)
        results["actor_nn_calls_per_s"] = _micro_case(
            lambda: rt.get(
                [a.inc.remote() for _ in range(25) for a in actors],
                timeout=120,
            ),
            5,
            scale=100,
        )

        # 7. put/get small measured above pre-fan-out.

        # 8. put/get large (shared-memory path) -> GB/s. Pre-touch
        # every buffer a lap touches BEFORE timing: read the source
        # pages (the generator wrote them, but a COW/NUMA migration
        # can still fire on first read), and run full put/get warmup
        # laps so the arena's page faults + del-pipeline priming are
        # paid cold — steady state (what a training loop sees) is
        # what gets timed. 3 warmup laps, not 2: the r5/r6 IQR (~half
        # the median) traced largely to lap-2 residual arena churn.
        big = np.random.default_rng(0).random(8_000_000)  # 64 MB
        big.sum()  # page in the source buffer read-side (COW/NUMA)
        ref = rt.put(big)
        rt.get(ref, timeout=60)
        del ref

        def _lap():
            ref = rt.put(big)
            out = rt.get(ref, timeout=60)
            del ref, out

        # ISSUE 12: r05 still flagged this case (IQR ~half the
        # median) — 4 warmup laps retire the residual arena churn a
        # third lap still paid, and 9 trials earn the 2-per-side
        # quiet-band trim (13+ after extras earns 3).
        results["put_get_64mb_gbps"] = _micro_case(
            _lap, 3, scale=big.nbytes / 1e9, digits=2, warmup=4,
            trials=9,
        )

        # 8b. drainless weight sync latency, ms (ISSUE 13): one
        # learner publish end to end — rt.put of the policy params +
        # concurrent fan-out to the weight store and rollout queue
        # actors + all acks (the same push_weights the decoupled RL
        # learner calls per update; engine pushes add one more
        # parallel ack). Committed as MILLISECONDS (lower is better);
        # the quiet-band spread logic is direction-agnostic.
        from ray_tpu.rl.models import init_policy_params
        from ray_tpu.rl.rollout_queue import (
            RolloutQueue as _RQueue,
        )
        from ray_tpu.rl.weight_sync import WeightStore, push_weights

        import jax as _jax

        _store = rt.remote(num_cpus=0)(WeightStore).remote()
        _queue = rt.remote(num_cpus=0)(_RQueue).remote(16, 4)
        rt.get(_store.ping.remote(), timeout=60)
        rt.get(_queue.ping.remote(), timeout=60)
        _policy = _jax.device_get(
            init_policy_params(_jax.random.PRNGKey(0), 4, 2)
        )
        _sync_version = [0]

        def _sync_trial() -> float:
            _sync_version[0] += 1
            return push_weights(
                _policy, _sync_version[0],
                store=_store, queue=_queue,
            )

        results["weight_sync_ms"] = _micro_case_from(
            _sync_trial, digits=2, trials=9, warmup=2
        )

        # 9. compiled DAG hop (channel round-trip vs RPC)
        from ray_tpu.dag import InputNode, experimental_compile

        @rt.remote
        class Echo:
            def ping(self, x):
                return x

        echo = Echo.remote()
        with InputNode() as inp:
            dag = echo.ping.bind(inp)
        compiled = experimental_compile(dag)
        try:
            # Longer trials than the RPC cases: a hop is ~45us, and
            # 200-hop trials were dominated by cold-start (first-lap
            # worker wake, branch/cache warmup) — the 3x inter-trial
            # spread VERDICT r4 flagged. ISSUE 12: r05 flagged the
            # case AGAIN (IQR 13.7k on median 44.8k) — 1000 warm hops
            # + 3 full warmup laps retire scheduler-migration noise
            # the 500-hop warmup missed, 1500-hop trials average over
            # more quanta, and 11 trials land in the 2-per-side band
            # (13+ after extras earns 3).
            for _ in range(1000):
                compiled.execute(1).get(timeout=30)
            results["dag_hop_per_s"] = _micro_case(
                lambda: compiled.execute(1).get(timeout=30), 1500,
                trials=11, warmup=3,
            )
        finally:
            compiled.teardown()
    finally:
        rt.shutdown()
    return results


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def _run_mode_subprocess(mode: str, timeout: float) -> dict | None:
    """Run `python bench.py --mode {tpu,cpu}` and parse its last stdout
    line as JSON; None on timeout/crash."""
    env = dict(os.environ)
    if mode in ("cpu", "micro", "ckpt", "pipeline"):
        # micro is runtime-bound by design: keep JAX (if anything
        # imports it) off the chip so a held TPU can't stall it.
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""  # disable axon sitecustomize
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--mode", mode],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] {mode} attempt timed out after {timeout}s",
              file=sys.stderr)
        return None
    if proc.returncode != 0:
        tail = (proc.stderr or "")[-2000:]
        print(f"[bench] {mode} attempt rc={proc.returncode}: {tail}",
              file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--mode",
        choices=[
            "orchestrate", "tpu", "tpu7b", "cpu", "micro", "ckpt",
            "pipeline", "smoke",
        ],
        default="orchestrate",
    )
    parser.add_argument(
        "--skip-micro", action="store_true",
        help="omit the op/s microbenchmark suite",
    )
    parser.add_argument(
        "--smoke", action="store_true",
        help="CI quick mode (seconds): exercise the whole bench "
        "surface on CPU with tiny configs; alias for --mode smoke",
    )
    args = parser.parse_args()

    if args.mode == "pipeline":
        print(json.dumps(run_pipeline_bench(args.smoke)))
        return
    if args.smoke or args.mode == "smoke":
        print(json.dumps(run_smoke(args.skip_micro)))
        return
    if args.mode == "tpu":
        print(json.dumps(run_train_bench(tpu=True)))
        return
    if args.mode == "tpu7b":
        print(json.dumps(run_7b_layer_bench()))
        return
    if args.mode == "cpu":
        result = run_train_bench(tpu=False)
        result["cpu_fallback"] = True
        result["vs_baseline"] = 0.0  # CPU numbers do not count vs 45% MFU
        print(json.dumps(result))
        return
    if args.mode == "micro":
        print(json.dumps(run_micro()))
        return
    if args.mode == "ckpt":
        print(json.dumps(run_ckpt_overhead()))
        return

    # Orchestrate: hygiene -> TPU attempts -> CPU fallback; plus micro.
    # Every phase is clipped to the remaining total budget and flushes
    # its result to BENCH_PARTIAL.json as soon as it lands.
    deadline = time.monotonic() + TOTAL_BUDGET

    def remaining() -> float:
        return deadline - time.monotonic()

    _write_partial({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "error": "bench started but no phase completed",
    })

    killed = reap_stale_tpu_holders()
    if killed:
        print(f"[bench] reaped {killed} stale worker process(es)",
              file=sys.stderr)
        time.sleep(2.0)

    result = None
    for attempt, budget in enumerate(TPU_ATTEMPT_TIMEOUTS):
        # Leave headroom for the CPU fallback + micro phases.
        budget = min(budget, remaining() - 120.0)
        if budget < 30.0:
            break
        result = _run_mode_subprocess("tpu", budget)
        if result is not None:
            break
        if attempt + 1 < len(TPU_ATTEMPT_TIMEOUTS):
            reap_stale_tpu_holders()
            time.sleep(TPU_RETRY_SLEEP)
    if result is None:
        print("[bench] TPU unavailable; falling back to CPU",
              file=sys.stderr)
        result = _run_mode_subprocess(
            "cpu", max(min(600.0, remaining() - 60.0), 60.0)
        )
    if result is None:  # even the CPU path died: emit an honest line
        result = {
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
            "error": "both TPU and CPU benchmark subprocesses failed",
        }
    _write_partial(result)

    # 7B-layer-geometry MFU projection — only after the main TPU
    # bench actually reached the chip (not after cpu_fallback, and not
    # after the both-benches-failed error dict: the chip is dead).
    if (
        not result.get("cpu_fallback")
        and "error" not in result
        and remaining() > 240.0
    ):
        seven_b = _run_mode_subprocess(
            "tpu7b", min(420.0, remaining() - 120.0)
        )
        if seven_b is not None:
            result["7b_layer"] = seven_b
        else:
            result["7b_layer_error"] = "tpu7b subprocess failed/timed out"
        _write_partial(result)

    if not args.skip_micro and remaining() > 30.0:
        micro = _run_mode_subprocess(
            "micro", min(MICRO_TIMEOUT, remaining())
        )
        if micro is not None:
            result["micro"] = micro
            with open(os.path.join(REPO, "MICROBENCH.json"), "w") as f:
                json.dump(micro, f, indent=2)
        else:
            result["micro_error"] = "micro subprocess failed or timed out"
        _write_partial(result)

    # Async-checkpoint overhead evidence (CPU subprocess — a relative
    # measurement: checkpointing every 10 steps vs none, same loop).
    if remaining() > 45.0:
        ckpt = _run_mode_subprocess("ckpt", min(240.0, remaining()))
        if ckpt is not None:
            result["ckpt_overhead"] = ckpt
        else:
            result["ckpt_overhead_error"] = "ckpt subprocess failed"
        _write_partial(result)

    # MPMD pipeline trajectory (CPU subprocess; writes PIPEBENCH.json
    # itself — the orchestrated line carries only the headline).
    if remaining() > 360.0:
        pipeline = _run_mode_subprocess(
            "pipeline", min(900.0, remaining() - 30.0)
        )
        if pipeline is not None:
            result["pipeline"] = {
                k: pipeline[k]
                for k in ("metric", "value", "unit", "vs_baseline")
                if k in pipeline
            }
        else:
            result["pipeline_error"] = "pipeline subprocess failed"
        _write_partial(result)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
