"""Benchmark: Llama training-step throughput + MFU on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference's north star (BASELINE.md) is Llama-2-7B pretraining at
>=45% MFU on a v5e-256 pod; a 7B model does not fit one 16-GiB v5e
chip, so the single-chip benchmark uses a 410M-param Llama with the
same architecture/kernels (Pallas flash attention, remat+scan layers,
bf16, fused AdamW step) and reports MFU — the hardware-normalized
metric the north star is defined in. vs_baseline = achieved_MFU / 0.45.
"""

from __future__ import annotations

import json
import time


def peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local accelerator generation."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 1.97e14
    if "v4" in kind:
        return 2.75e14
    if "v5p" in kind or "v5" in kind:
        return 4.59e14
    if "v6" in kind or "trillium" in kind:
        return 9.2e14
    return 1.97e14  # conservative default


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import (
        LlamaConfig,
        flops_per_token,
        init_params,
        loss_fn,
        param_annotations,
    )
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.train_step import (
        default_optimizer,
        make_train_step,
        shard_batch,
    )

    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    if on_tpu:
        cfg = LlamaConfig.bench_410m()
        batch, seq = 8, 2048
        steps, warmup = 20, 3
    else:  # CI fallback so the bench always emits a line
        cfg = LlamaConfig.tiny()
        batch, seq = 4, 128
        steps, warmup = 3, 1

    mesh = MeshSpec(fsdp=len(jax.devices())).build()

    def loss(params, tokens, targets):
        return loss_fn(params, tokens, targets, cfg)

    optimizer = default_optimizer(total_steps=100000)
    init_fn, step_fn = make_train_step(
        loss, optimizer, mesh, param_annotations(cfg)
    )
    state = init_fn(jax.random.PRNGKey(0), lambda k: init_params(k, cfg))

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    tokens = shard_batch(tokens, mesh, logical_axes=("batch", None))
    inp, tgt = tokens[:, :-1], tokens[:, 1:]

    # float() forces a device->host transfer as the sync point
    # (block_until_ready is unreliable on experimental PJRT backends).
    for _ in range(warmup):
        state, metrics = step_fn(state, inp, tgt)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, inp, tgt)
    final_loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    assert final_loss == final_loss and final_loss > 0, final_loss

    n_chips = len(jax.devices())
    tokens_per_sec_chip = batch * seq / dt / n_chips
    mfu = (
        flops_per_token(cfg, seq) * tokens_per_sec_chip
        / peak_flops_per_chip()
    )
    print(
        json.dumps(
            {
                "metric": (
                    f"llama_{cfg.num_params() // 1_000_000}M_train_"
                    f"tokens_per_sec_per_chip"
                ),
                "value": round(tokens_per_sec_chip, 1),
                "unit": f"tokens/s/chip (MFU={mfu:.3f}, step={dt*1e3:.0f}ms)",
                "vs_baseline": round(mfu / 0.45, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
