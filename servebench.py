"""Serving benchmark: open-loop Poisson traffic against the
continuous-batching LLM engine (ray_tpu/llm) through the full serve
path — HTTP proxy -> router -> replica -> engine — in the
bench.py/scalebench.py JSON-trajectory idiom.

Prints ONE JSON line on the LAST stdout line and writes the full
result to SERVEBENCH.json:

  {"metric": "servebench_tokens_per_s", "value": N, "points": [...],
   "baseline": [...], "comparison": {...}, ...}

Design:

* OPEN-LOOP arrivals: a seeded exponential inter-arrival clock fires
  requests regardless of completions (closed-loop clients hide
  queueing collapse; open-loop is the "millions of users" shape).
  Each request runs in its own thread: POST /llm with a token-id
  prompt, stream the chunked response, timestamp every chunk.
* Mixed lengths: every request is a per-family SHARED SYSTEM PREFIX
  (`--prefix-len` tokens, the realistic chat shape and what the
  paged cache's prefix reuse feeds on) plus a random tail sampled
  from a short/long mix (seeded), exercising several prefill buckets
  and ragged completions.
* Multi-family points tag requests with `serve_multiplexed_model_id`
  so the proxy/router exercise the multiplex path and BOTH families'
  engines decode concurrently (the smoke gate asserts it).
* The BASELINE redeploys the same app with the engine kill switch
  off (`engine_enabled=False`): every request runs its own
  `generate_stream()` — serialize-per-request serving — at the same
  offered load, so the comparison isolates continuous batching.
* `--replicas N` (ISSUE 11) adds a horizontal-scale pass: the same
  app at num_replicas=N behind the same proxy, driven at
  `--multi-loads`, with the router spreading by least-outstanding-
  tokens and SLO admission shedding (503 + Retry-After) counted per
  point — the result's `multi_replica.scaling` block compares the
  multi-replica peak against this run's own single-replica points.
* Engine visibility: each point samples `/api/serve` (occupancy,
  batch p50, paged-KV blocks, prefix hits) while traffic runs, and
  the result records whether the engine + prefix-cache series render
  on the Prometheus exposition.

Metrics per point: p50/p99 time-to-first-token, p50/p99 per-token
latency (mean inter-token gap per request, percentiled over
requests), aggregate tokens/s, achieved vs offered load, errors,
sheds (503s).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
OUT_PATH = os.path.join(REPO, "SERVEBENCH.json")

TINY_CONFIG = {
    "vocab_size": 128, "dim": 64, "n_layers": 2, "n_heads": 4,
    "n_kv_heads": 2, "intermediate": 128, "max_seq_len": 256,
    "dtype": "float32",
}
#: Default (non-smoke) model: big enough that batched GEMMs amortize
#: per-step dispatch, small enough to serve from one CPU test box.
BASE_CONFIG = {
    "vocab_size": 512, "dim": 256, "n_layers": 4, "n_heads": 8,
    "n_kv_heads": 4, "intermediate": 512, "max_seq_len": 512,
    "dtype": "float32",
}


def _percentile(values, q):
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(
        len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1))))
    )
    return ordered[idx]


class _RequestResult:
    __slots__ = (
        "ok", "error", "ttft_ms", "per_token_ms", "tokens",
        "start", "end", "family",
    )

    def __init__(self):
        self.ok = False
        self.error = ""
        self.ttft_ms = 0.0
        self.per_token_ms = 0.0
        self.tokens = 0
        self.start = 0.0
        self.end = 0.0
        self.family = ""


def _one_request(port, route, payload, family, timeout_s):
    """POST the prompt, stream the chunked body, time every chunk."""
    result = _RequestResult()
    result.family = family
    result.start = time.perf_counter()
    conn = http.client.HTTPConnection(
        "127.0.0.1", port, timeout=timeout_s
    )
    try:
        headers = {"Content-Type": "application/json"}
        if family:
            headers["serve_multiplexed_model_id"] = family
        conn.request(
            "POST", route, body=json.dumps(payload), headers=headers
        )
        resp = conn.getresponse()
        if resp.status != 200:
            result.error = f"http {resp.status}"
            resp.read()
            return result
        first = None
        arrivals = []
        buffered = b""
        while True:
            data = resp.read1(65536)
            now = time.perf_counter()
            if not data:
                break
            if first is None:
                first = now
            buffered += data
            arrivals.extend(
                (now,) * (data.count(b" "))
            )
        result.end = time.perf_counter()
        result.tokens = len(buffered.split())
        if first is None or not result.tokens:
            result.error = "empty stream"
            return result
        result.ttft_ms = (first - result.start) * 1e3
        if len(arrivals) > 1:
            result.per_token_ms = (
                (arrivals[-1] - arrivals[0])
                / (len(arrivals) - 1)
                * 1e3
            )
        result.ok = True
        return result
    except Exception as e:  # noqa: BLE001 — recorded per request
        result.error = repr(e)
        result.end = time.perf_counter()
        return result
    finally:
        conn.close()


def _sample_engine_state(route_key):
    """One /api/serve-equivalent snapshot of the deployment's engine
    occupancy (serve.status_detail serves the same payload)."""
    try:
        import ray_tpu.serve as serve

        row = serve.status_detail().get(route_key) or {}
        families = row.get("engine") or {}
        return {
            "slots_used": float(row.get("engine_slots_used", 0.0)),
            "families_active": sum(
                1 for f in families.values()
                if f.get("slots_used", 0.0) > 0
            ),
            "batch_p50": max(
                (f.get("batch_p50", 0.0) for f in families.values()),
                default=0.0,
            ),
            "families": sorted(families),
            "kv_blocks_used": float(
                row.get("engine_kv_blocks_used", 0.0)
            ),
            "prefix_hits": float(row.get("engine_prefix_hits", 0.0)),
            "prefix_misses": float(
                row.get("engine_prefix_misses", 0.0)
            ),
        }
    except Exception:
        return {}


def _prefix_totals():
    """Cumulative prefix-cache counters off the head's metric table
    (the /metrics numbers, summed over label sets)."""
    try:
        from ray_tpu.util.metrics import metrics_summary

        summary = metrics_summary()

        def total(name):
            series = (summary.get(name, {}).get("by_tags") or {})
            return sum(
                float(s.get("total", 0.0) or 0.0)
                for s in series.values()
            )

        return {
            "hits": total("serve_engine_prefix_hits_total"),
            "misses": total("serve_engine_prefix_misses_total"),
            "tokens_saved": total(
                "serve_engine_prefix_tokens_saved_total"
            ),
        }
    except Exception:
        return {}


def run_point(
    *,
    port,
    route,
    route_key,
    offered_rps,
    duration_s,
    families,
    prompt_mix,
    max_new_mix,
    seed,
    system_prefixes=None,
    request_timeout_s=60.0,
):
    """One offered-load point: Poisson arrivals for `duration_s`.
    `system_prefixes` maps family -> shared prompt-prefix token list
    prepended to every request (prompt_mix bounds the RANDOM TAIL)."""
    rng = random.Random(seed)
    results = []
    results_lock = threading.Lock()
    threads = []
    samples = []
    stop_sampling = threading.Event()

    def sampler():
        while not stop_sampling.is_set():
            sample = _sample_engine_state(route_key)
            if sample:
                samples.append(sample)
            stop_sampling.wait(0.5)

    sampler_thread = threading.Thread(target=sampler, daemon=True)
    sampler_thread.start()

    def fire(payload, family):
        result = _one_request(
            port, route, payload, family, request_timeout_s
        )
        with results_lock:
            results.append(result)

    t0 = time.perf_counter()
    next_at = t0
    while True:
        next_at += rng.expovariate(offered_rps)
        if next_at - t0 > duration_s:
            break
        delay = next_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        lo, hi = prompt_mix[rng.randrange(len(prompt_mix))]
        tail = [
            rng.randrange(1, 100) for _ in range(rng.randint(lo, hi))
        ]
        family = families[rng.randrange(len(families))]
        prefix = list((system_prefixes or {}).get(family, ()))
        payload = {
            "prompt": prefix + tail,
            "max_new_tokens": max_new_mix[
                rng.randrange(len(max_new_mix))
            ],
        }
        thread = threading.Thread(
            target=fire, args=(payload, family), daemon=True
        )
        thread.start()
        threads.append(thread)
    for thread in threads:
        thread.join(timeout=request_timeout_s)
    stop_sampling.set()
    sampler_thread.join(timeout=2)

    done = [r for r in results if r.ok]
    errors = [r for r in results if not r.ok]
    # SLO admission + proxy sheds (503 + Retry-After): counted
    # separately from hard errors — a shed is the system WORKING
    # under overload, not failing.
    sheds = [r for r in errors if r.error.startswith("http 503")]
    window_end = max((r.end for r in done), default=time.perf_counter())
    wall = max(1e-9, window_end - t0)
    total_tokens = sum(r.tokens for r in done)
    ttfts = [r.ttft_ms for r in done]
    per_token = [r.per_token_ms for r in done if r.per_token_ms > 0]
    return {
        "offered_rps": offered_rps,
        "achieved_rps": round(len(done) / wall, 2),
        "duration_s": duration_s,
        "mix": sorted(set(families)),
        "requests": len(results),
        "completed": len(done),
        "errors": len(errors) - len(sheds),
        "shed": len(sheds),
        "error_sample": errors[0].error if errors else "",
        "tokens": total_tokens,
        "tokens_per_s": round(total_tokens / wall, 1),
        "ttft_ms": {
            "p50": round(_percentile(ttfts, 0.50), 1),
            "p99": round(_percentile(ttfts, 0.99), 1),
        },
        "per_token_ms": {
            "p50": round(_percentile(per_token, 0.50), 2),
            "p99": round(_percentile(per_token, 0.99), 2),
        },
        "engine": {
            "max_slots_used": max(
                (s["slots_used"] for s in samples), default=0.0
            ),
            "max_concurrent_families": max(
                (s["families_active"] for s in samples), default=0
            ),
            "batch_p50": max(
                (s["batch_p50"] for s in samples), default=0.0
            ),
            "families_seen": sorted(
                {f for s in samples for f in s.get("families", [])}
            ),
            "max_kv_blocks_used": max(
                (s.get("kv_blocks_used", 0.0) for s in samples),
                default=0.0,
            ),
            # Cumulative counters at the point's last sample (the
            # trajectory across points shows the hit-rate ramp).
            "prefix_hits": max(
                (s.get("prefix_hits", 0.0) for s in samples),
                default=0.0,
            ),
            "prefix_misses": max(
                (s.get("prefix_misses", 0.0) for s in samples),
                default=0.0,
            ),
        },
    }


def _deploy(families, engine_cfg, engine_enabled, version,
            num_replicas=1):
    import ray_tpu.serve as serve
    from ray_tpu.llm import build_llm_app

    app = build_llm_app(
        families,
        engine=engine_cfg,
        engine_enabled=engine_enabled,
        num_replicas=num_replicas,
        max_ongoing_requests=max(16, engine_cfg.get("slots", 4) * 4),
    )
    # Version forces a replica replacement on redeploy (engine -> a
    # fresh baseline replica, not a warm reuse).
    app.deployment.version = version
    return serve.run(app, name="llm", route_prefix="/llm")


def _system_prefixes(families, prefix_len):
    """Deterministic per-family shared system prompt (the prefix-
    cache workload: every request for a family starts with these
    tokens, like a chat system prompt)."""
    out = {}
    for i, family in enumerate(sorted(families)):
        rng = random.Random(1000 + i)
        out[family] = [
            rng.randrange(1, 100) for _ in range(prefix_len)
        ]
    return out


def _warm(port, families, prompt_mix, system_prefixes, replicas=1):
    """Warm requests per family per prompt-length BUCKET EDGE so
    every jit compile lands outside the measured windows (the paged
    engine compiles once per geometry, but the engine-off baseline
    still compiles once per prefill bucket). With multiple replicas,
    each edge fires a CONCURRENT wave of 2x replicas requests — the
    least-outstanding-tokens router spreads a concurrent wave, so
    every replica gets its compiles (and its prefix-cache seed) with
    high probability; sequential warmups would all land on one idle
    replica after another by tie-break luck."""
    wave = max(1, 2 * replicas) if replicas > 1 else 1
    for family in families:
        prefix = list(system_prefixes.get(family, ()))
        for edge in sorted({n for pair in prompt_mix for n in pair}):
            prompt = (prefix + list(range(1, edge + 1)))
            results = []
            threads = []

            def fire():
                results.append(_one_request(
                    port, "/llm",
                    {"prompt": prompt, "max_new_tokens": 2},
                    family, timeout_s=600.0,
                ))

            for _ in range(wave):
                t = threading.Thread(target=fire, daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=600.0)
            if not any(r.ok for r in results):
                raise RuntimeError(
                    f"warmup failed for {family}: "
                    f"{results[0].error if results else 'no result'}"
                )


def run_bench(args) -> dict:
    import ray_tpu as rt
    import ray_tpu.serve as serve

    t_start = time.perf_counter()
    smoke = args.smoke
    model = dict(TINY_CONFIG if smoke else BASE_CONFIG)
    engine_cfg = {
        "slots": 4 if smoke else 8,
        "max_len": 96 if smoke else 192,
        "prefill_chunk": 8 if smoke else 16,
        "max_new_tokens": 64,
    }
    families = {
        "tiny-a": {"kind": "init", "seed": 0, "config": model},
        "tiny-b": {"kind": "init", "seed": 1, "config": model},
    }
    prompt_mix = ((4, 8), (12, 16)) if smoke else ((8, 16), (24, 48))
    max_new_mix = (8, 16) if smoke else (16, 32)
    prefix_len = (
        args.prefix_len if args.prefix_len is not None
        else (16 if smoke else 32)
    )
    prefixes = _system_prefixes(families, prefix_len)
    # The top load must OVERSUBSCRIBE a single decode stream (arrival
    # rate x per-request service time > 1) or continuous batching has
    # nothing to batch — the measured smoke points sit above the
    # serialize-per-request capacity and below the engine's.
    loads = args.loads or ((8.0, 24.0) if smoke else (6.0, 14.0))
    duration = args.duration or (8.0 if smoke else 16.0)

    # Replica actors each claim one LOGICAL cpu slot; declare enough
    # for the --replicas pass (scheduling tokens, not cores — on a
    # small box the replicas time-share, which is exactly the
    # saturation behavior the bench measures).
    rt.init(num_cpus=max(os.cpu_count() or 1, args.replicas))
    port = serve.start(http_port=0, per_node=False)
    route_key = "llm/llm"
    result = {
        "metric": "servebench_tokens_per_s",
        "unit": "tokens/s",
        "smoke": bool(smoke),
        "model": model,
        "engine_config": engine_cfg,
        "loads_rps": list(loads),
        "duration_s": duration,
        "prefix_len": prefix_len,
        "points": [],
        "baseline": [],
    }
    try:
        _deploy(families, engine_cfg, True, "engine-1")
        _warm(port, list(families), prompt_mix, prefixes)
        for i, load in enumerate(loads):
            # First point: single family. Later points: the full
            # multi-family mix (the multiplex-under-load case).
            mix = (
                ["tiny-a"] if i == 0 else list(families)
            )
            result["points"].append(
                run_point(
                    port=port,
                    route="/llm",
                    route_key=route_key,
                    offered_rps=load,
                    duration_s=duration,
                    families=mix,
                    prompt_mix=prompt_mix,
                    max_new_mix=max_new_mix,
                    seed=100 + i,
                    system_prefixes=prefixes,
                )
            )
        result["prefix"] = _prefix_totals()

        # Engine + prefix-cache series visible on the exposition?
        try:
            from ray_tpu.util.metrics import metrics_summary
            from ray_tpu.util.prometheus import render_prometheus

            text = render_prometheus(metrics_summary())
            result["metrics_visible"] = {
                "prometheus_engine_series": (
                    "serve_engine_slots_used{" in text
                    and "serve_engine_step_batch_bucket{" in text
                ),
                "prometheus_prefix_series": (
                    "serve_engine_prefix_hits_total{" in text
                    and "serve_engine_kv_blocks_used{" in text
                ),
                "api_serve_engine": bool(
                    (
                        serve.status_detail()
                        .get(route_key, {})
                        .get("engine")
                    )
                ),
            }
        except Exception as e:  # noqa: BLE001 — recorded
            result["metrics_visible"] = {"error": repr(e)}

        if not args.no_baseline:
            # Same app, kill switch OFF: per-request generate_stream,
            # measured at the same top offered load + mix.
            _deploy(families, engine_cfg, False, "baseline-1")
            _warm(port, list(families), prompt_mix, prefixes)
            for i, load in enumerate(loads):
                mix = ["tiny-a"] if i == 0 else list(families)
                result["baseline"].append(
                    run_point(
                        port=port,
                        route="/llm",
                        route_key=route_key,
                        offered_rps=load,
                        duration_s=duration,
                        families=mix,
                        prompt_mix=prompt_mix,
                        max_new_mix=max_new_mix,
                        seed=100 + i,  # same arrival/length sequence
                        system_prefixes=prefixes,
                    )
                )
            top = result["points"][-1]
            base = result["baseline"][-1]
            result["comparison"] = {
                "offered_rps": top["offered_rps"],
                "engine_tokens_per_s": top["tokens_per_s"],
                "baseline_tokens_per_s": base["tokens_per_s"],
                "speedup": round(
                    top["tokens_per_s"]
                    / max(1e-9, base["tokens_per_s"]),
                    2,
                ),
                "engine_ttft_p99_ms": top["ttft_ms"]["p99"],
                "baseline_ttft_p99_ms": base["ttft_ms"]["p99"],
            }

        if args.replicas > 1:
            # Horizontal-scale pass (ISSUE 11): same app + engine
            # config, N replicas behind the same proxy; the router
            # spreads by least-outstanding-tokens and SLO admission
            # sheds (counted per point) once every replica's queue is
            # over threshold.
            _deploy(
                families, engine_cfg, True,
                f"engine-x{args.replicas}",
                num_replicas=args.replicas,
            )
            _warm(
                port, list(families), prompt_mix, prefixes,
                replicas=args.replicas,
            )
            multi_loads = args.multi_loads or (
                (12.0, 24.0) if smoke else (14.0, 24.0, 28.0)
            )
            multi_points = []
            for i, load in enumerate(multi_loads):
                multi_points.append(
                    run_point(
                        port=port,
                        route="/llm",
                        route_key=route_key,
                        offered_rps=load,
                        duration_s=duration,
                        families=list(families),
                        prompt_mix=prompt_mix,
                        max_new_mix=max_new_mix,
                        seed=200 + i,
                        system_prefixes=prefixes,
                    )
                )
            single_peak = max(
                result["points"],
                key=lambda p: p["achieved_rps"],
            )
            multi_peak = max(
                multi_points, key=lambda p: p["achieved_rps"]
            )
            result["multi_replica"] = {
                "replicas": args.replicas,
                "loads_rps": list(multi_loads),
                "points": multi_points,
                "scaling": {
                    "single_replica_peak_rps": (
                        single_peak["achieved_rps"]
                    ),
                    "multi_replica_peak_rps": (
                        multi_peak["achieved_rps"]
                    ),
                    "scale_factor": round(
                        multi_peak["achieved_rps"]
                        / max(1e-9, single_peak["achieved_rps"]),
                        2,
                    ),
                    "single_ttft_p50_at_peak_ms": (
                        single_peak["ttft_ms"]["p50"]
                    ),
                    "multi_ttft_p50_at_peak_ms": (
                        multi_peak["ttft_ms"]["p50"]
                    ),
                },
            }
            result["prefix"] = _prefix_totals()
        result["value"] = result["points"][-1]["tokens_per_s"]
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            rt.shutdown()
        except Exception:
            pass
    result["wall_s"] = round(time.perf_counter() - t_start, 1)
    return result


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--smoke", action="store_true",
        help="tiny model + short windows: the whole serve path on "
        "CPU in about a minute (CI-gated by "
        "tests/test_servebench_smoke.py)",
    )
    parser.add_argument(
        "--loads", type=lambda s: [float(x) for x in s.split(",")],
        default=None, help="offered-load points, req/s (e.g. 4,12)",
    )
    parser.add_argument(
        "--duration", type=float, default=None,
        help="seconds per load point",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="skip the engine-off comparison pass",
    )
    parser.add_argument(
        "--replicas", type=int, default=1,
        help="run an extra horizontal-scale pass at this many "
        "replicas (results under the 'multi_replica' key)",
    )
    parser.add_argument(
        "--multi-loads",
        type=lambda s: [float(x) for x in s.split(",")],
        default=None,
        help="offered-load points for the --replicas pass, req/s",
    )
    parser.add_argument(
        "--prefix-len", type=int, default=None,
        help="shared system-prompt tokens prepended to every "
        "request per family (default 32, 16 with --smoke; 0 "
        "disables the prefix workload)",
    )
    parser.add_argument(
        "--out", default=OUT_PATH,
        help="result JSON path (default SERVEBENCH.json)",
    )
    args = parser.parse_args()
    result = run_bench(args)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
