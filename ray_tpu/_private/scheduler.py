"""Resource accounting and local task scheduling.

Models the reference's two-level scheduler (reference:
src/ray/raylet/scheduling/cluster_task_manager.cc:44 picks a node;
local_task_manager.cc:122 dispatches to leased workers against
per-node resource instances; fixed-point resource arithmetic in
src/ray/common/scheduling/fixed_point.h).

`ResourceSet` uses integer milli-units (the reference's FixedPoint uses
1/10000 units) so fractional resources like `num_cpus=0.5` are exact.
`LocalScheduler` keeps a FIFO-with-skips queue: a task is dispatchable
when its resources fit and its argument objects are local (the
reference's DependencyManager gate, raylet/dependency_manager.h).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

MILLI = 1000


class ResourceSet:
    """Fixed-point (milli-unit) resource vector keyed by name."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: Optional[Dict[str, float]] = None):
        self._amounts: Dict[str, int] = {}
        for name, value in (amounts or {}).items():
            milli = int(round(value * MILLI))
            if milli != 0:
                self._amounts[name] = milli

    @classmethod
    def _from_milli(cls, amounts: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._amounts = {k: v for k, v in amounts.items() if v != 0}
        return rs

    def fits_in(self, other: "ResourceSet") -> bool:
        return all(
            other._amounts.get(name, 0) >= milli
            for name, milli in self._amounts.items()
        )

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._amounts)
        for name, milli in other._amounts.items():
            out[name] = out.get(name, 0) - milli
        return ResourceSet._from_milli(out)

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._amounts)
        for name, milli in other._amounts.items():
            out[name] = out.get(name, 0) + milli
        return ResourceSet._from_milli(out)

    def get(self, name: str) -> float:
        return self._amounts.get(name, 0) / MILLI

    def to_dict(self) -> Dict[str, float]:
        return {k: v / MILLI for k, v in self._amounts.items()}

    def is_empty(self) -> bool:
        return not self._amounts

    def __repr__(self) -> str:
        return f"ResourceSet({self.to_dict()})"


class LocalScheduler:
    """Single-node resource pool + pending-task queue.

    Dispatch is driven by `maybe_dispatch`, called whenever capacity or
    dependency state changes; the provided callbacks decide worker
    availability (reference: LocalTaskManager::
    DispatchScheduledTasksToWorkers).
    """

    def __init__(self, total: ResourceSet):
        self._total = total
        self._available = total
        self._lock = threading.RLock()
        # task_id -> (ResourceSet, spec); insertion-ordered for FIFO.
        self._queue: "OrderedDict" = OrderedDict()
        self._running: Dict[object, ResourceSet] = {}

    # ---- capacity ----
    def total(self) -> ResourceSet:
        return self._total

    def available(self) -> ResourceSet:
        with self._lock:
            return self._available

    def add_capacity(self, extra: ResourceSet) -> None:
        with self._lock:
            self._total = self._total.add(extra)
            self._available = self._available.add(extra)

    def remove_capacity(self, extra: ResourceSet) -> None:
        with self._lock:
            self._total = self._total.subtract(extra)
            self._available = self._available.subtract(extra)

    def try_reserve(self, request: ResourceSet) -> bool:
        """Atomically carve `request` out of this node's pool (both
        total and available) — the placement-group bundle prepare step
        (reference: raylet/placement_group_resource_manager.h 2PC).
        Fails if the resources are not currently free."""
        with self._lock:
            if not request.fits_in(self._available):
                return False
            self._total = self._total.subtract(request)
            self._available = self._available.subtract(request)
            return True

    # ---- queueing ----
    def enqueue(self, task_id, request: ResourceSet, spec) -> None:
        with self._lock:
            self._queue[task_id] = (request, spec)

    def cancel(self, task_id) -> bool:
        with self._lock:
            return self._queue.pop(task_id, None) is not None

    def drain_queued(self, predicate) -> list:
        """Remove and return the specs of queued tasks matching
        `predicate(spec)` (used to fail tasks stranded by a removed
        placement group's resources)."""
        drained = []
        with self._lock:
            for task_id in list(self._queue):
                _, spec = self._queue[task_id]
                if predicate(spec):
                    del self._queue[task_id]
                    drained.append(spec)
        return drained

    def queued_count(self) -> int:
        with self._lock:
            return len(self._queue)

    def count_queued(self, predicate) -> int:
        """Number of queued tasks whose spec matches `predicate`."""
        with self._lock:
            return sum(
                1
                for _req, spec in self._queue.values()
                if predicate(spec)
            )

    def maybe_dispatch(
        self,
        deps_ready: Callable[[object], bool],
        try_dispatch: Callable[[object, object], bool],
    ) -> int:
        """Dispatch every queued task that fits and whose deps are local.

        `try_dispatch(task_id, spec)` must return True if a worker
        accepted the task; resources stay acquired until
        `release(task_id)`. Returns number of tasks dispatched.
        """
        dispatched = 0
        while True:
            candidate = None
            with self._lock:
                for task_id, (request, spec) in self._queue.items():
                    if not request.fits_in(self._available):
                        continue
                    if not deps_ready(spec):
                        continue
                    candidate = (task_id, request, spec)
                    break
                if candidate is None:
                    return dispatched
                task_id, request, spec = candidate
                del self._queue[task_id]
                self._available = self._available.subtract(request)
                self._running[task_id] = request
            if not try_dispatch(task_id, spec):
                # No worker available: requeue at the front and stop.
                with self._lock:
                    self._available = self._available.add(request)
                    del self._running[task_id]
                    self._queue[task_id] = (request, spec)
                    self._queue.move_to_end(task_id, last=False)
                return dispatched
            dispatched += 1

    def release(self, task_id) -> None:
        with self._lock:
            request = self._running.pop(task_id, None)
            if request is not None:
                self._available = self._available.add(request)
