"""Object serialization with zero-copy buffer support.

Mirrors the contract of the reference's SerializationContext
(reference: python/ray/_private/serialization.py:122 — cloudpickle with
out-of-band pickle-protocol-5 buffers so large numpy/arrow payloads are
written/read from plasma without copies).

Here the on-wire layout is:

    [8-byte header len][pickled header][buffer 0][buffer 1]...

The header holds the protocol-5 in-band pickle bytes plus per-buffer
(offset, length, alignment) metadata. Writing into a shared-memory
object therefore needs exactly one pass over the buffers, and reading
reconstructs numpy/jax arrays as views over the mapped memory —
zero-copy, which is what lets the store feed `jax.numpy.asarray` /
dlpack without a host copy (SURVEY.md §7 hard part 3).

ObjectRefs embedded inside values are recorded in the header so the
owner can track borrowed references (reference:
core_worker/reference_count.h borrower protocol).
"""

from __future__ import annotations

import pickle
import struct
from dataclasses import dataclass, field
from typing import Any

import cloudpickle

_ALIGN = 64  # TPU-friendly alignment for zero-copy into XLA.


@dataclass
class SerializedObject:
    """A value serialized into header bytes + out-of-band buffers."""

    inband: bytes
    buffers: list[memoryview] = field(default_factory=list)

    def total_size(self) -> int:
        size = 8 + len(self._header())
        for buf in self.buffers:
            size = _align_up(size)
            size += buf.nbytes
        return size

    def _header(self) -> bytes:
        return pickle.dumps(
            {
                "inband": self.inband,
                "nbytes": [buf.nbytes for buf in self.buffers],
            },
            protocol=5,
        )

    def write_to(self, target: memoryview) -> int:
        """Write the full wire format into `target`; returns bytes used."""
        header = self._header()
        struct.pack_into(">Q", target, 0, len(header))
        target[8 : 8 + len(header)] = header
        cursor = 8 + len(header)
        for buf in self.buffers:
            cursor = _align_up(cursor)
            flat = buf.cast("B") if buf.ndim != 1 or buf.format != "B" else buf
            target[cursor : cursor + flat.nbytes] = flat
            cursor += flat.nbytes
        return cursor

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size())
        used = self.write_to(memoryview(out))
        return bytes(out[:used])


def _align_up(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SerializationContext:
    """Pickles values with out-of-band protocol-5 buffers.

    ObjectRefs embedded in values survive the trip via
    ObjectRef.__reduce__, which re-attaches them to the receiving
    process's worker and notifies the owner of the borrow."""

    def __init__(self, ref_class: type | None = None):
        self._ref_class = ref_class

    def serialize(self, value: Any) -> SerializedObject:
        buffers: list[pickle.PickleBuffer] = []
        # cloudpickle so lambdas/closures/local functions work as task
        # args and return values (reference vendors cloudpickle for the
        # same reason, python/ray/cloudpickle/).
        inband = cloudpickle.dumps(
            value, protocol=5, buffer_callback=buffers.append
        )
        return SerializedObject(
            inband=inband, buffers=[b.raw() for b in buffers]
        )

    def deserialize(
        self, data: memoryview | bytes, buffer_wrap=None
    ) -> Any:
        """Reconstruct a value; out-of-band buffers are zero-copy views
        into `data`. `buffer_wrap(mv) -> buffer` lets the caller wrap
        each out-of-band slice in a lifetime-tracking object (the
        native arena ties reader pins to buffer lifetime this way)."""
        view = memoryview(data)
        (header_len,) = struct.unpack_from(">Q", view, 0)
        header = pickle.loads(bytes(view[8 : 8 + header_len]))
        cursor = 8 + header_len
        buffers = []
        for nbytes in header["nbytes"]:
            cursor = _align_up(cursor)
            chunk = view[cursor : cursor + nbytes]
            buffers.append(
                chunk if buffer_wrap is None else buffer_wrap(chunk)
            )
            cursor += nbytes
        return pickle.loads(header["inband"], buffers=buffers)
