"""Node memory watcher driving OOM worker kills.

Reference: src/ray/common/memory_monitor.h:52 — a cgroup-aware
watcher samples node memory every refresh interval; above the usage
threshold the raylet kills a worker chosen by a pluggable policy
(raylet/worker_killing_policy_group_by_owner.cc: prefer retriable
tasks, newest first) and the task retries elsewhere (infinite OOM
retries by default, ray_config_def.h:91 task_oom_retries).

Here the monitor samples /proc/meminfo (cgroup v2 limits when
present) plus per-worker RSS, and asks the daemon to kill the chosen
victim; the existing worker-death path handles retry/failure.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Tuple


def _cgroup_memory() -> Optional[Tuple[int, int]]:
    """(used, limit) from cgroup v2, None if unbounded/absent."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw == "max":
            return None
        limit = int(raw)
        with open("/sys/fs/cgroup/memory.current") as f:
            used = int(f.read().strip())
        return used, limit
    except (OSError, ValueError):
        return None


def _meminfo() -> Tuple[int, int]:
    """(used, total) bytes from /proc/meminfo."""
    total = available = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                available = int(line.split()[1]) * 1024
    return total - available, total


def node_memory_usage_fraction() -> float:
    cg = _cgroup_memory()
    if cg is not None:
        used, limit = cg
        return used / limit if limit else 0.0
    used, total = _meminfo()
    return used / total if total else 0.0


def process_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def pick_victim(
    candidates: List[dict],
) -> Optional[dict]:
    """Worker-killing policy (reference: retriable-first, newest-task
    first — worker_killing_policy.cc): prefer workers whose current
    task can retry, break ties by largest RSS."""
    if not candidates:
        return None
    ranked = sorted(
        candidates,
        key=lambda c: (not c.get("retriable", False), -c.get("rss", 0)),
    )
    return ranked[0]


class MemoryMonitor:
    def __init__(
        self,
        usage_threshold: float,
        refresh_interval_s: float,
        get_candidates: Callable[[], List[dict]],
        kill_worker: Callable[[dict], None],
        usage_fn: Callable[[], float] = node_memory_usage_fraction,
        min_kill_interval_s: float = 1.0,
    ):
        self.usage_threshold = usage_threshold
        self.refresh_interval_s = refresh_interval_s
        self._get_candidates = get_candidates
        self._kill_worker = kill_worker
        self._usage_fn = usage_fn
        self._min_kill_interval_s = min_kill_interval_s
        self._last_kill = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="memory-monitor"
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            try:
                self.tick()
            except Exception:
                pass

    def tick(self) -> bool:
        """One sample; returns True if a victim was killed."""
        usage = self._usage_fn()
        if usage < self.usage_threshold:
            return False
        if time.time() - self._last_kill < self._min_kill_interval_s:
            return False
        victim = pick_victim(self._get_candidates())
        if victim is None:
            return False
        self._last_kill = time.time()  # rt: noqa[RT201] — only the monitor loop calls tick() in production; the public method exists for single-threaded tests
        self._kill_worker(victim)
        return True

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
