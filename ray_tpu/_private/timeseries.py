"""Head-side metrics time-series ring.

The live metrics table answers "what is the p99 NOW"; this store
answers "when did it get slow". A bounded ring of periodic snapshots
— each a compacted copy of the head's aggregate metric table — is
appended by the head daemon every `metrics_timeseries_interval_s`
seconds and queried through the `metrics_timeseries` RPC /
``/api/timeseries?name=...&since=...``. Counters in consecutive
snapshots are rate-computable by differencing; histogram snapshots
carry count/sum plus reservoir percentiles so p50/p99 TRENDS survive
past the live 1024-sample reservoir window.

Reference analogy: the reference ships series to an external
Prometheus whose TSDB keeps history; the rebuild keeps a bounded
in-head window so trend diagnosis needs no external infrastructure.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: Scalar keys copied into a snapshot per metric/tag-set. Buckets and
#: sample reservoirs stay out: a snapshot must be O(series), not
#: O(observations).
_SCALAR_KEYS = (
    "total",
    "value",
    "count",
    "sum",
    "min",
    "max",
    "p50",
    "p95",
    "p99",
)


def compact_summary(summary: Dict[str, dict]) -> Dict[str, dict]:
    """Strip a `metrics_summary` mapping down to the scalar series a
    snapshot retains: kind + scalars, per-tag-set scalars, per-node
    values. Descriptions, bucket tables and reservoirs are dropped —
    they are reconstructable from (or only meaningful against) the
    live table."""
    out: Dict[str, dict] = {}
    for name, entry in summary.items():
        compact: dict = {"kind": entry.get("kind")}
        for key in _SCALAR_KEYS:
            if key in entry:
                compact[key] = entry[key]
        by_tags = entry.get("by_tags")
        if by_tags:
            compact["by_tags"] = {
                flat: {
                    key: series[key]
                    for key in _SCALAR_KEYS
                    if key in series
                }
                for flat, series in by_tags.items()
            }
        by_node = entry.get("by_node")
        if by_node:
            compact["by_node"] = dict(by_node)
        out[name] = compact
    return out


class TimeSeriesStore:
    """Bounded ring of ``{"time": t, "metrics": {name: compact}}``
    snapshots. Appends evict the oldest snapshot past `max_snapshots`
    — history is a window, not a database."""

    def __init__(self, max_snapshots: int = 720):
        self._ring: deque = deque(maxlen=max(2, int(max_snapshots)))
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def max_snapshots(self) -> int:
        return self._ring.maxlen

    def append(
        self, metrics: Dict[str, dict], now: Optional[float] = None
    ) -> None:
        snapshot = {
            "time": time.time() if now is None else float(now),
            "metrics": metrics,
        }
        # Same lock as query(): iterating a deque while another
        # thread appends raises "deque mutated during iteration".
        with self._lock:
            self._ring.append(snapshot)

    def query(
        self,
        name: Optional[str] = None,
        since: float = 0.0,
        limit: int = 0,
    ) -> List[dict]:
        """Snapshots newer than `since`, oldest first. With `name`,
        each snapshot's `metrics` is filtered to that single series
        (snapshots in which the series did not exist yet are
        skipped); `limit` keeps the NEWEST snapshots."""
        with self._lock:
            snapshots = list(self._ring)
        if since:
            snapshots = [
                s for s in snapshots if s["time"] > float(since)
            ]
        if name is not None:
            snapshots = [
                {"time": s["time"], "metrics": {name: s["metrics"][name]}}
                for s in snapshots
                if name in s["metrics"]
            ]
        if limit and limit > 0:
            snapshots = snapshots[-int(limit):]
        return snapshots
