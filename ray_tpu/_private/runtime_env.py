"""Runtime environments: per-task/actor execution context.

Reference: python/ray/_private/runtime_env/ — env_vars, working_dir,
py_modules (plugin.py's RuntimeEnvPlugin registry; working_dir.py
packages the directory and workers download+cache it by content hash).
Here packaging rides the cluster KV store (the reference uses GCS
packages the same way): the driver zips working_dir/py_modules into
KV under a content hash, workers extract once into a node-local cache
and prepend to sys.path. env_vars apply around task execution and are
restored afterwards (shared workers); actors keep their env for life
(they pin their worker).

`pip`/`conda`/`uv` fields raise RuntimeEnvSetupError: the deployment
environment is hermetic (no package installs at runtime); images are
the supported isolation unit.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

from .. import exceptions as exc

_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_CACHE_ROOT = "/tmp/rt_runtime_env_cache"

# Extension point (reference: runtime_env/plugin.py): name -> callable
# (value, context_dict) -> None, run worker-side inside apply.
PLUGINS: Dict[str, Any] = {}

_KNOWN_FIELDS = {
    "env_vars",
    "working_dir",
    "py_modules",
    "pip",
    "conda",
    "uv",
}


def _zip_dir(path: str, prefix: str = "") -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                if rel.startswith(".git" + os.sep):
                    continue
                zf.write(
                    full, os.path.join(prefix, rel) if prefix else rel
                )
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise exc.RuntimeEnvSetupError(
            f"packaged dir {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})"
        )
    return data


def prepare_runtime_env(
    env: Optional[dict], worker
) -> Optional[dict]:
    """Driver-side: validate + package + upload; returns the wire form
    embedded in the task spec."""
    if not env:
        return None
    unknown = set(env) - _KNOWN_FIELDS - set(PLUGINS)
    if unknown:
        raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
    for banned in ("pip", "conda", "uv"):
        if env.get(banned):
            raise exc.RuntimeEnvSetupError(
                f"runtime_env[{banned!r}] is unsupported: runtime "
                "package installation is disabled in this environment; "
                "bake dependencies into the image instead"
            )
    wire: Dict[str, Any] = {}
    if env.get("env_vars"):
        wire["env_vars"] = {
            str(k): str(v) for k, v in env["env_vars"].items()
        }
    if env.get("working_dir"):
        wire["working_dir"] = _upload_dir(env["working_dir"], worker)
    if env.get("py_modules"):
        # Each module dir is zipped under its own name so the extracted
        # cache dir is the importable parent on sys.path.
        wire["py_modules"] = [
            _upload_dir(m, worker, nest_under_name=True)
            for m in env["py_modules"]
        ]
    for name in PLUGINS:
        if name in env:
            wire[name] = env[name]
    return wire


# Driver-side upload memo: (worker generation, realpath, dir
# signature) -> wire dict. Submitting many tasks with the same
# runtime_env must not re-zip the tree or re-download the package per
# submit (reference: URI caching in runtime_env/working_dir.py).
_upload_memo: Dict[tuple, dict] = {}


def _dir_signature(path: str) -> tuple:
    """Cheap change detector: (file count, total size, max mtime)."""
    count = total = 0
    latest = 0.0
    for root, _, files in os.walk(path):
        for name in files:
            try:
                st = os.stat(os.path.join(root, name))
            except OSError:
                continue
            count += 1
            total += st.st_size
            latest = max(latest, st.st_mtime)
    return (count, total, latest)


def _upload_dir(path: str, worker, nest_under_name: bool = False) -> dict:
    if not os.path.isdir(path):
        raise exc.RuntimeEnvSetupError(
            f"runtime_env dir {path!r} does not exist"
        )
    real = os.path.realpath(path)
    memo_key = (
        worker.generation,
        real,
        nest_under_name,
        _dir_signature(real),
    )
    cached = _upload_memo.get(memo_key)
    if cached is not None:
        return cached
    data = _zip_dir(
        path, prefix=os.path.basename(path.rstrip(os.sep))
        if nest_under_name
        else "",
    )
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"__rt_pkg__{digest}"
    # Existence check via key listing (never downloads the package).
    if key not in worker.call("kv_keys", prefix=key).get("keys", []):
        worker.call("kv_put", key=key, value=data)
    wire = {"key": key, "hash": digest, "name": os.path.basename(path)}
    _upload_memo[memo_key] = wire
    return wire


def _fetch_package(pkg: dict, worker) -> str:
    """Worker-side: download + extract once per content hash."""
    target = os.path.join(_CACHE_ROOT, pkg["hash"])
    if os.path.isdir(target):
        return target
    reply = worker.call("kv_get", key=pkg["key"])
    if reply.get("value") is None:
        raise exc.RuntimeEnvSetupError(
            f"package {pkg['key']} missing from cluster KV"
        )
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    tmp = target + f".tmp{os.getpid()}"
    with zipfile.ZipFile(io.BytesIO(reply["value"])) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        # Another worker won the race; its copy is identical.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


@contextmanager
def apply_runtime_env(wire: Optional[dict], worker, *, restore: bool = True):
    """Worker-side: enter the env around task execution. restore=False
    for actors (they own their worker for life)."""
    if not wire:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_path = list(sys.path)
    saved_cwd = os.getcwd()
    try:
        for key, value in (wire.get("env_vars") or {}).items():
            saved_env[key] = os.environ.get(key)
            os.environ[key] = value
        if wire.get("working_dir"):
            workdir = _fetch_package(wire["working_dir"], worker)
            os.chdir(workdir)
            sys.path.insert(0, workdir)
        for pkg in wire.get("py_modules") or []:
            sys.path.insert(0, _fetch_package(pkg, worker))
        for name, hook in PLUGINS.items():
            if name in wire:
                hook(wire[name], {"worker": worker})
        yield
    finally:
        if restore:
            for key, value in saved_env.items():
                if value is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = value
            sys.path[:] = saved_path
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
