"""Runtime environments: per-task/actor execution context.

Reference: python/ray/_private/runtime_env/ — env_vars, working_dir,
py_modules (plugin.py's RuntimeEnvPlugin registry; working_dir.py
packages the directory and workers download+cache it by content hash).
Here packaging rides the cluster KV store (the reference uses GCS
packages the same way): the driver zips working_dir/py_modules into
KV under a content hash, workers extract once into a node-local cache
and prepend to sys.path. env_vars apply around task execution and are
restored afterwards (shared workers); actors keep their env for life
(they pin their worker).

`pip` creates a node-local virtualenv per requirements hash (reference:
runtime_env/pip.py builds a virtualenv + pip-installs into it, cached
by a hash of the spec) and prepends its site-packages around task
execution; restore also evicts the env's modules from sys.modules so
shared workers stay clean. The hermetic deployment has no package
index, so requirements must resolve offline (local wheels/dirs) —
network installs surface as RuntimeEnvSetupError exactly like a failed
pip would. `conda`/`uv` raise RuntimeEnvSetupError: not installed in
the image; `pip` is the supported installer.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

from .. import exceptions as exc

_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_CACHE_ROOT = "/tmp/rt_runtime_env_cache"

# Extension point (reference: runtime_env/plugin.py): name -> callable
# (value, context_dict) -> None, run worker-side inside apply.
PLUGINS: Dict[str, Any] = {}

_KNOWN_FIELDS = {
    "env_vars",
    "working_dir",
    "py_modules",
    "pip",
    "conda",
    "uv",
}


def _zip_dir(path: str, prefix: str = "") -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                if rel.startswith(".git" + os.sep):
                    continue
                zf.write(
                    full, os.path.join(prefix, rel) if prefix else rel
                )
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise exc.RuntimeEnvSetupError(
            f"packaged dir {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})"
        )
    return data


def prepare_runtime_env(
    env: Optional[dict], worker
) -> Optional[dict]:
    """Driver-side: validate + package + upload; returns the wire form
    embedded in the task spec."""
    if not env:
        return None
    unknown = set(env) - _KNOWN_FIELDS - set(PLUGINS)
    if unknown:
        raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
    for banned in ("conda", "uv"):
        if env.get(banned):
            raise exc.RuntimeEnvSetupError(
                f"runtime_env[{banned!r}] is unsupported: {banned} is "
                "not installed in this image; use runtime_env['pip'] "
                "or bake dependencies into the image"
            )
    wire: Dict[str, Any] = {}
    if env.get("pip"):
        wire["pip"] = _normalize_pip(env["pip"], worker)
    if env.get("env_vars"):
        wire["env_vars"] = {
            str(k): str(v) for k, v in env["env_vars"].items()
        }
    if env.get("working_dir"):
        wire["working_dir"] = _upload_dir(env["working_dir"], worker)
    if env.get("py_modules"):
        # Each module dir is zipped under its own name so the extracted
        # cache dir is the importable parent on sys.path.
        wire["py_modules"] = [
            _upload_dir(m, worker, nest_under_name=True)
            for m in env["py_modules"]
        ]
    for name in PLUGINS:
        if name in env:
            wire[name] = env[name]
    return wire


# Driver-side upload memo: (worker generation, realpath, dir
# signature) -> wire dict. Submitting many tasks with the same
# runtime_env must not re-zip the tree or re-download the package per
# submit (reference: URI caching in runtime_env/working_dir.py).
_upload_memo: Dict[tuple, dict] = {}


def _dir_signature(path: str) -> tuple:
    """Cheap change detector: (file count, total size, max mtime)."""
    count = total = 0
    latest = 0.0
    for root, _, files in os.walk(path):
        for name in files:
            try:
                st = os.stat(os.path.join(root, name))
            except OSError:
                continue
            count += 1
            total += st.st_size
            latest = max(latest, st.st_mtime)
    return (count, total, latest)


def _upload_dir(path: str, worker, nest_under_name: bool = False) -> dict:
    if not os.path.isdir(path):
        raise exc.RuntimeEnvSetupError(
            f"runtime_env dir {path!r} does not exist"
        )
    real = os.path.realpath(path)
    memo_key = (
        worker.generation,
        real,
        nest_under_name,
        _dir_signature(real),
    )
    cached = _upload_memo.get(memo_key)
    if cached is not None:
        return cached
    data = _zip_dir(
        path, prefix=os.path.basename(path.rstrip(os.sep))
        if nest_under_name
        else "",
    )
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"__rt_pkg__{digest}"
    # Existence check via key listing (never downloads the package).
    if key not in worker.call("kv_keys", prefix=key).get("keys", []):
        worker.call("kv_put", key=key, value=data)
    wire = {"key": key, "hash": digest, "name": os.path.basename(path)}
    _upload_memo[memo_key] = wire
    return wire


def _normalize_pip(spec, worker) -> dict:
    """Driver-side pip spec -> wire form {packages, hash} (reference:
    pip.py accepts a list or {'packages': [...]}; the cache key is a
    hash of the normalized spec). Local wheels/dirs upload to the
    cluster KV — workers on other nodes have no shared filesystem, so
    paths must ship as content, the same way working_dir does."""
    if isinstance(spec, dict):
        packages = list(spec.get("packages") or [])
    elif isinstance(spec, (list, tuple)):
        packages = list(spec)
    else:
        raise exc.RuntimeEnvSetupError(
            f"runtime_env['pip'] must be a list of requirements or "
            f"{{'packages': [...]}}, got {type(spec).__name__}"
        )
    if not all(isinstance(p, str) for p in packages):
        raise exc.RuntimeEnvSetupError(
            "runtime_env['pip'] entries must be strings"
        )
    # Path detection follows pip's syntax (./foo, /abs, ~/x, archive
    # suffixes) — a bare requirement name that happens to collide with
    # a cwd entry stays a requirement. Hashing is content-addressed,
    # so a rebuilt wheel or edited source dir busts the env cache.
    norm: list = []
    sig = []
    for p in packages:
        px = os.path.expanduser(p)
        if _looks_like_path(p) and os.path.exists(px):
            real = os.path.realpath(px)
            if os.path.isdir(real):
                entry = {"dir": _upload_dir(real, worker)}
                sig.append("dir:" + entry["dir"]["hash"])
            else:
                entry = {"file": _upload_file(real, worker)}
                sig.append("file:" + entry["file"]["hash"])
            norm.append(entry)
        else:
            norm.append(p)
            sig.append(p)
    digest = hashlib.sha256(
        "\n".join(sorted(sig)).encode()
    ).hexdigest()[:16]
    return {"packages": norm, "hash": digest}


def _upload_file(path: str, worker) -> dict:
    """Content-address one local file (wheel/archive) into the KV."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise exc.RuntimeEnvSetupError(
            f"pip requirement {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})"
        )
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"__rt_pkg__{digest}"
    if key not in worker.call("kv_keys", prefix=key).get("keys", []):
        worker.call("kv_put", key=key, value=data)
    return {"key": key, "hash": digest, "name": os.path.basename(path)}


def _fetch_file(entry: dict, worker) -> str:
    """Worker-side: materialize an uploaded file requirement, keeping
    its original basename (pip parses wheel names)."""
    dirpath = os.path.join(_CACHE_ROOT, "files", entry["hash"])
    path = os.path.join(dirpath, entry["name"])
    if os.path.exists(path):
        return path
    reply = worker.call("kv_get", key=entry["key"])
    if reply.get("value") is None:
        raise exc.RuntimeEnvSetupError(
            f"pip package {entry['key']} missing from cluster KV"
        )
    os.makedirs(dirpath, exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(reply["value"])
    os.replace(tmp, path)
    return path


_ARCHIVE_SUFFIXES = (".whl", ".tar.gz", ".zip", ".tar.bz2")


def _looks_like_path(req: str) -> bool:
    """pip's convention: only explicit path forms are paths."""
    return (
        req.startswith(("/", "./", "../", "~"))
        or req.endswith(_ARCHIVE_SUFFIXES)
        or os.sep in req
    )


def _ensure_pip_env(pip_wire: dict, worker) -> str:
    """Worker-side: build (once per requirements hash per node) an
    isolated package dir via host `pip install --target` and return it
    for sys.path prepending. A full virtualenv would add interpreter
    symlinks nothing executes — the path prepend IS the isolation unit
    here (the reference swaps worker interpreters instead,
    runtime_env/pip.py). Concurrency-safe via build-in-tmp-then-rename."""
    import subprocess

    target = os.path.join(_CACHE_ROOT, "pip-" + pip_wire["hash"])
    if os.path.isdir(target):
        return target
    # Materialize uploaded local requirements (wheels/source dirs)
    # from the cluster KV onto this node first.
    reqs = []
    for entry in pip_wire["packages"]:
        if isinstance(entry, str):
            reqs.append(entry)
        elif "file" in entry:
            reqs.append(_fetch_file(entry["file"], worker))
        else:
            reqs.append(_fetch_package(entry["dir"], worker))
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    tmp = target + f".tmp{os.getpid()}"
    try:
        os.makedirs(tmp, exist_ok=True)
        try:
            proc = subprocess.run(
                [
                    sys.executable, "-m", "pip", "install",
                    "--quiet", "--disable-pip-version-check",
                    "--no-input", "--target", tmp,
                    *reqs,
                ],
                capture_output=True,
                text=True,
                timeout=600,
            )
        except subprocess.TimeoutExpired as e:
            raise exc.RuntimeEnvSetupError(
                f"pip install timed out after 600s for runtime_env"
                f"{pip_wire['packages']}"
            ) from e
        if proc.returncode != 0:
            raise exc.RuntimeEnvSetupError(
                "pip install failed for runtime_env"
                f"{pip_wire['packages']}:\n{proc.stderr[-2000:]}"
            )
        try:
            os.rename(tmp, target)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # lost the race
    finally:
        if os.path.isdir(tmp):
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return target


def _fetch_package(pkg: dict, worker) -> str:
    """Worker-side: download + extract once per content hash."""
    target = os.path.join(_CACHE_ROOT, pkg["hash"])
    if os.path.isdir(target):
        return target
    reply = worker.call("kv_get", key=pkg["key"])
    if reply.get("value") is None:
        raise exc.RuntimeEnvSetupError(
            f"package {pkg['key']} missing from cluster KV"
        )
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    tmp = target + f".tmp{os.getpid()}"
    with zipfile.ZipFile(io.BytesIO(reply["value"])) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        # Another worker won the race; its copy is identical.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


@contextmanager
def apply_runtime_env(wire: Optional[dict], worker, *, restore: bool = True):
    """Worker-side: enter the env around task execution. restore=False
    for actors (they own their worker for life)."""
    if not wire:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_path = list(sys.path)
    saved_cwd = os.getcwd()
    pip_site: Optional[str] = None
    try:
        for key, value in (wire.get("env_vars") or {}).items():
            saved_env[key] = os.environ.get(key)
            os.environ[key] = value
        if wire.get("pip"):
            import importlib

            pip_site = _ensure_pip_env(wire["pip"], worker)
            sys.path.insert(0, pip_site)
            # Subprocesses the task spawns inherit the env too.
            saved_env.setdefault(
                "PYTHONPATH", os.environ.get("PYTHONPATH")
            )
            os.environ["PYTHONPATH"] = os.pathsep.join(
                p for p in (pip_site, os.environ.get("PYTHONPATH")) if p
            )
            importlib.invalidate_caches()
        if wire.get("working_dir"):
            workdir = _fetch_package(wire["working_dir"], worker)
            os.chdir(workdir)
            sys.path.insert(0, workdir)
        for pkg in wire.get("py_modules") or []:
            sys.path.insert(0, _fetch_package(pkg, worker))
        for name, hook in PLUGINS.items():
            if name in wire:
                hook(wire[name], {"worker": worker})
        yield
    finally:
        if restore:
            for key, value in saved_env.items():
                if value is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = value
            sys.path[:] = saved_path
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            if pip_site is not None:
                # Evict the env's modules so a later task on this
                # shared worker can't import them via sys.modules
                # (the reference avoids this by dedicating workers per
                # env; we restore instead). Namespace packages have
                # __file__=None but carry the env dir in __path__.
                for name, mod in list(sys.modules.items()):
                    file = getattr(mod, "__file__", None) or ""
                    paths = list(getattr(mod, "__path__", None) or [])
                    if file.startswith(pip_site) or any(
                        str(p).startswith(pip_site) for p in paths
                    ):
                        del sys.modules[name]
