"""Runtime environments: per-task/actor execution context.

Reference: python/ray/_private/runtime_env/ — env_vars, working_dir,
py_modules (plugin.py's RuntimeEnvPlugin registry; working_dir.py
packages the directory and workers download+cache it by content hash).
Here packaging rides the cluster KV store (the reference uses GCS
packages the same way): the driver zips working_dir/py_modules into
KV under a content hash, workers extract once into a node-local cache
and prepend to sys.path. env_vars apply around task execution and are
restored afterwards (shared workers); actors keep their env for life
(they pin their worker).

`pip` creates a node-local virtualenv per requirements hash (reference:
runtime_env/pip.py builds a virtualenv + pip-installs into it, cached
by a hash of the spec) and prepends its site-packages around task
execution; restore also evicts the env's modules from sys.modules so
shared workers stay clean. The hermetic deployment has no package
index, so requirements must resolve offline (local wheels/dirs) —
network installs surface as RuntimeEnvSetupError exactly like a failed
pip would.

`uv` and `conda` ship as RuntimeEnvPlugin implementations (reference:
runtime_env/uv.py, conda.py) gated on their binaries being on PATH —
validated driver-side for fail-fast on images that don't carry them.
Third-party extensions subclass RuntimeEnvPlugin and register_plugin().
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import shutil
import sys
import zipfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

from .. import exceptions as exc

_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_CACHE_ROOT = "/tmp/rt_runtime_env_cache"

_KNOWN_FIELDS = {
    "env_vars",
    "working_dir",
    "py_modules",
    "pip",
}


class RuntimeEnvContext:
    """Mutation surface handed to plugins worker-side. Changes made
    through it are recorded into apply_runtime_env's save/restore
    state, so a shared task worker returns to a clean slate; direct
    os.environ/sys.path writes from a plugin would leak."""

    def __init__(self, worker, saved_env: Dict[str, Any]):
        self.worker = worker
        self._saved_env = saved_env

    def set_env(self, key: str, value: str) -> None:
        self._saved_env.setdefault(key, os.environ.get(key))
        os.environ[key] = str(value)

    def prepend_sys_path(self, path: str) -> None:
        # apply_runtime_env snapshots the whole sys.path; prepends are
        # rolled back wholesale.
        sys.path.insert(0, path)
        self.set_env(
            "PYTHONPATH",
            os.pathsep.join(
                p for p in (path, os.environ.get("PYTHONPATH")) if p
            ),
        )


class RuntimeEnvPlugin:
    """Extension point (reference: runtime_env/plugin.py
    RuntimeEnvPlugin — name, priority, create/modify_context hooks).

    A plugin owns one runtime_env key. Lifecycle:
      * validate(value, worker) — DRIVER-side at submit: check the
        value, package/upload anything local, return the wire form.
      * create(wire_value, worker) — WORKER-side, once per distinct
        wire value per process (memoized on the pickled value):
        expensive materialization (build an env, download) happens
        here; the return value is the plugin's state.
      * modify_context(state, wire_value, ctx) — WORKER-side on every
        task apply: activate the state via the RuntimeEnvContext
        (env vars, sys.path); keep it cheap.
    Plugins run in ascending `priority` (built-in fields first)."""

    name: str = ""
    priority: int = 10

    def validate(self, value, worker):
        return value

    def create(self, value, worker):
        return None

    def modify_context(self, state, value, ctx: RuntimeEnvContext):
        pass


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}
#: (plugin name, pickled wire value) -> created state, per process.
_plugin_state: Dict[tuple, Any] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name or plugin.name in _KNOWN_FIELDS:
        raise ValueError(
            f"plugin name {plugin.name!r} is empty or shadows a "
            f"built-in runtime_env field"
        )
    _PLUGINS[plugin.name] = plugin


def unregister_plugin(name: str) -> None:
    _PLUGINS.pop(name, None)


_external_loaded = False
_external_error: Optional[BaseException] = None


def _load_external_plugins() -> None:
    """Load plugins named by RT_RUNTIME_ENV_PLUGINS (comma-separated
    `module.path:ClassName` or `/abs/file.py:ClassName`) — reference:
    RAY_RUNTIME_ENV_PLUGINS. Driver and workers are separate
    processes; the env var (inherited through the daemon's worker
    env) is what makes a registration visible on both sides.

    A load failure is latched and re-raised on EVERY later call: a
    typo'd entry must keep failing tasks loudly, not fail once and
    then let everything run without the plugin's environment."""
    global _external_loaded, _external_error
    if _external_loaded:
        if _external_error is not None:
            raise exc.RuntimeEnvSetupError(
                f"RT_RUNTIME_ENV_PLUGINS failed to load: "
                f"{_external_error}"
            ) from _external_error
        return
    spec = os.environ.get("RT_RUNTIME_ENV_PLUGINS", "")
    try:
        for item in filter(None, (s.strip() for s in spec.split(","))):
            path, _, clsname = item.partition(":")
            if not clsname:
                raise exc.RuntimeEnvSetupError(
                    f"RT_RUNTIME_ENV_PLUGINS entry {item!r} must be "
                    "module:ClassName or /file.py:ClassName"
                )
            import importlib
            import importlib.util

            if path.endswith(".py"):
                modname = "_rt_env_plugin_" + hashlib.sha256(
                    path.encode()
                ).hexdigest()[:8]
                loaded = importlib.util.spec_from_file_location(
                    modname, path
                )
                mod = importlib.util.module_from_spec(loaded)
                sys.modules[modname] = mod
                loaded.loader.exec_module(mod)
            else:
                mod = importlib.import_module(path)
            register_plugin(getattr(mod, clsname)())
    except BaseException as e:
        _external_error = e
        _external_loaded = True
        raise
    _external_loaded = True


def _zip_dir(path: str, prefix: str = "") -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                if rel.startswith(".git" + os.sep):
                    continue
                zf.write(
                    full, os.path.join(prefix, rel) if prefix else rel
                )
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise exc.RuntimeEnvSetupError(
            f"packaged dir {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})"
        )
    return data


def prepare_runtime_env(
    env: Optional[dict], worker
) -> Optional[dict]:
    """Driver-side: validate + package + upload; returns the wire form
    embedded in the task spec."""
    if not env:
        return None
    _load_external_plugins()
    unknown = set(env) - _KNOWN_FIELDS - set(_PLUGINS)
    if unknown:
        raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
    wire: Dict[str, Any] = {}
    if env.get("pip"):
        wire["pip"] = _normalize_pip(env["pip"], worker)
    if env.get("env_vars"):
        wire["env_vars"] = {
            str(k): str(v) for k, v in env["env_vars"].items()
        }
    if env.get("working_dir"):
        wire["working_dir"] = _upload_dir(env["working_dir"], worker)
    if env.get("py_modules"):
        # Each module dir is zipped under its own name so the extracted
        # cache dir is the importable parent on sys.path.
        wire["py_modules"] = [
            _upload_dir(m, worker, nest_under_name=True)
            for m in env["py_modules"]
        ]
    for name, plugin in _PLUGINS.items():
        if name in env:
            wire[name] = plugin.validate(env[name], worker)
    return wire


# Driver-side upload memo: (worker generation, realpath, dir
# signature) -> wire dict. Submitting many tasks with the same
# runtime_env must not re-zip the tree or re-download the package per
# submit (reference: URI caching in runtime_env/working_dir.py).
_upload_memo: Dict[tuple, dict] = {}


def _dir_signature(path: str) -> tuple:
    """Cheap change detector: (file count, total size, max mtime)."""
    count = total = 0
    latest = 0.0
    for root, _, files in os.walk(path):
        for name in files:
            try:
                st = os.stat(os.path.join(root, name))
            except OSError:
                continue
            count += 1
            total += st.st_size
            latest = max(latest, st.st_mtime)
    return (count, total, latest)


def _upload_dir(path: str, worker, nest_under_name: bool = False) -> dict:
    if not os.path.isdir(path):
        raise exc.RuntimeEnvSetupError(
            f"runtime_env dir {path!r} does not exist"
        )
    real = os.path.realpath(path)
    memo_key = (
        worker.generation,
        real,
        nest_under_name,
        _dir_signature(real),
    )
    cached = _upload_memo.get(memo_key)
    if cached is not None:
        return cached
    data = _zip_dir(
        path, prefix=os.path.basename(path.rstrip(os.sep))
        if nest_under_name
        else "",
    )
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"__rt_pkg__{digest}"
    # Existence check via key listing (never downloads the package).
    if key not in worker.call("kv_keys", prefix=key).get("keys", []):
        worker.call("kv_put", key=key, value=data)
    wire = {"key": key, "hash": digest, "name": os.path.basename(path)}
    _upload_memo[memo_key] = wire
    return wire


def _normalize_pip(spec, worker) -> dict:
    """Driver-side pip spec -> wire form {packages, hash} (reference:
    pip.py accepts a list or {'packages': [...]}; the cache key is a
    hash of the normalized spec). Local wheels/dirs upload to the
    cluster KV — workers on other nodes have no shared filesystem, so
    paths must ship as content, the same way working_dir does."""
    if isinstance(spec, dict):
        packages = list(spec.get("packages") or [])
    elif isinstance(spec, (list, tuple)):
        packages = list(spec)
    else:
        raise exc.RuntimeEnvSetupError(
            f"runtime_env['pip'] must be a list of requirements or "
            f"{{'packages': [...]}}, got {type(spec).__name__}"
        )
    if not all(isinstance(p, str) for p in packages):
        raise exc.RuntimeEnvSetupError(
            "runtime_env['pip'] entries must be strings"
        )
    # Path detection follows pip's syntax (./foo, /abs, ~/x, archive
    # suffixes) — a bare requirement name that happens to collide with
    # a cwd entry stays a requirement. Hashing is content-addressed,
    # so a rebuilt wheel or edited source dir busts the env cache.
    norm: list = []
    sig = []
    for p in packages:
        px = os.path.expanduser(p)
        if _looks_like_path(p) and os.path.exists(px):
            real = os.path.realpath(px)
            if os.path.isdir(real):
                entry = {"dir": _upload_dir(real, worker)}
                sig.append("dir:" + entry["dir"]["hash"])
            else:
                entry = {"file": _upload_file(real, worker)}
                sig.append("file:" + entry["file"]["hash"])
            norm.append(entry)
        else:
            norm.append(p)
            sig.append(p)
    digest = hashlib.sha256(
        "\n".join(sorted(sig)).encode()
    ).hexdigest()[:16]
    return {"packages": norm, "hash": digest}


def _upload_file(path: str, worker) -> dict:
    """Content-address one local file (wheel/archive) into the KV."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise exc.RuntimeEnvSetupError(
            f"pip requirement {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})"
        )
    digest = hashlib.sha256(data).hexdigest()[:16]
    key = f"__rt_pkg__{digest}"
    if key not in worker.call("kv_keys", prefix=key).get("keys", []):
        worker.call("kv_put", key=key, value=data)
    return {"key": key, "hash": digest, "name": os.path.basename(path)}


def _fetch_file(entry: dict, worker) -> str:
    """Worker-side: materialize an uploaded file requirement, keeping
    its original basename (pip parses wheel names)."""
    dirpath = os.path.join(_CACHE_ROOT, "files", entry["hash"])
    path = os.path.join(dirpath, entry["name"])
    if os.path.exists(path):
        return path
    reply = worker.call("kv_get", key=entry["key"])
    if reply.get("value") is None:
        raise exc.RuntimeEnvSetupError(
            f"pip package {entry['key']} missing from cluster KV"
        )
    os.makedirs(dirpath, exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(reply["value"])
    os.replace(tmp, path)
    return path


_ARCHIVE_SUFFIXES = (".whl", ".tar.gz", ".zip", ".tar.bz2")


def _looks_like_path(req: str) -> bool:
    """pip's convention: only explicit path forms are paths."""
    return (
        req.startswith(("/", "./", "../", "~"))
        or req.endswith(_ARCHIVE_SUFFIXES)
        or os.sep in req
    )


def _ensure_pip_env(pip_wire: dict, worker, tool: str = "pip") -> str:
    """Worker-side: build (once per requirements hash per node) an
    isolated package dir via `pip install --target` (or uv's
    equivalent) and return it for sys.path prepending. A full
    virtualenv would add interpreter symlinks nothing executes — the
    path prepend IS the isolation unit here (the reference swaps
    worker interpreters instead, runtime_env/pip.py). Concurrency-safe
    via build-in-tmp-then-rename."""
    import subprocess

    target = os.path.join(_CACHE_ROOT, f"{tool}-" + pip_wire["hash"])
    if os.path.isdir(target):
        return target
    # Materialize uploaded local requirements (wheels/source dirs)
    # from the cluster KV onto this node first.
    reqs = []
    for entry in pip_wire["packages"]:
        if isinstance(entry, str):
            reqs.append(entry)
        elif "file" in entry:
            reqs.append(_fetch_file(entry["file"], worker))
        else:
            reqs.append(_fetch_package(entry["dir"], worker))
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    tmp = target + f".tmp{os.getpid()}"
    if tool == "uv":
        # --python pins resolution to the worker's interpreter
        # (reference: runtime_env/uv.py passes the same).
        cmd = [
            "uv", "pip", "install", "--quiet",
            "--python", sys.executable, "--target", tmp, *reqs,
        ]
    else:
        cmd = [
            sys.executable, "-m", "pip", "install",
            "--quiet", "--disable-pip-version-check",
            "--no-input", "--target", tmp, *reqs,
        ]
    try:
        os.makedirs(tmp, exist_ok=True)
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=600,
            )
        except subprocess.TimeoutExpired as e:
            raise exc.RuntimeEnvSetupError(
                f"{tool} install timed out after 600s for runtime_env"
                f"{pip_wire['packages']}"
            ) from e
        if proc.returncode != 0:
            raise exc.RuntimeEnvSetupError(
                f"{tool} install failed for runtime_env"
                f"{pip_wire['packages']}:\n{proc.stderr[-2000:]}"
            )
        try:
            os.rename(tmp, target)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # lost the race
    finally:
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
    return target


def _fetch_package(pkg: dict, worker) -> str:
    """Worker-side: download + extract once per content hash."""
    target = os.path.join(_CACHE_ROOT, pkg["hash"])
    if os.path.isdir(target):
        return target
    reply = worker.call("kv_get", key=pkg["key"])
    if reply.get("value") is None:
        raise exc.RuntimeEnvSetupError(
            f"package {pkg['key']} missing from cluster KV"
        )
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    tmp = target + f".tmp{os.getpid()}"
    with zipfile.ZipFile(io.BytesIO(reply["value"])) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        # Another worker won the race; its copy is identical.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


@contextmanager
def apply_runtime_env(wire: Optional[dict], worker, *, restore: bool = True):
    """Worker-side: enter the env around task execution. restore=False
    for actors (they own their worker for life)."""
    if not wire:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_path = list(sys.path)
    saved_cwd = os.getcwd()
    pip_site: Optional[str] = None
    try:
        for key, value in (wire.get("env_vars") or {}).items():
            saved_env[key] = os.environ.get(key)
            os.environ[key] = value
        if wire.get("pip"):
            import importlib

            pip_site = _ensure_pip_env(wire["pip"], worker)
            sys.path.insert(0, pip_site)
            # Subprocesses the task spawns inherit the env too.
            saved_env.setdefault(
                "PYTHONPATH", os.environ.get("PYTHONPATH")
            )
            os.environ["PYTHONPATH"] = os.pathsep.join(
                p for p in (pip_site, os.environ.get("PYTHONPATH")) if p
            )
            importlib.invalidate_caches()
        if wire.get("working_dir"):
            workdir = _fetch_package(wire["working_dir"], worker)
            os.chdir(workdir)
            sys.path.insert(0, workdir)
        for pkg in wire.get("py_modules") or []:
            sys.path.insert(0, _fetch_package(pkg, worker))
        _load_external_plugins()
        orphaned = set(wire) - _KNOWN_FIELDS - set(_PLUGINS)
        if orphaned:
            # The driver validated these through a plugin that is not
            # registered HERE (RT_RUNTIME_ENV_PLUGINS missing from the
            # worker env). Running without the requested environment
            # would be a silent wrong answer.
            raise exc.RuntimeEnvSetupError(
                f"runtime_env fields {sorted(orphaned)} have no "
                "registered plugin on this worker; set "
                "RT_RUNTIME_ENV_PLUGINS cluster-wide"
            )
        ctx = RuntimeEnvContext(worker, saved_env)
        for plugin in sorted(
            _PLUGINS.values(), key=lambda p: p.priority
        ):
            if plugin.name not in wire:
                continue
            value = wire[plugin.name]
            state_key = (plugin.name, pickle.dumps(value))
            if state_key not in _plugin_state:
                _plugin_state[state_key] = plugin.create(value, worker)
            plugin.modify_context(
                _plugin_state[state_key], value, ctx
            )
        yield
    finally:
        if restore:
            for key, value in saved_env.items():
                if value is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = value
            sys.path[:] = saved_path
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            if pip_site is not None:
                # Evict the env's modules so a later task on this
                # shared worker can't import them via sys.modules
                # (the reference avoids this by dedicating workers per
                # env; we restore instead). Namespace packages have
                # __file__=None but carry the env dir in __path__.
                for name, mod in list(sys.modules.items()):
                    file = getattr(mod, "__file__", None) or ""
                    paths = list(getattr(mod, "__path__", None) or [])
                    if file.startswith(pip_site) or any(
                        str(p).startswith(pip_site) for p in paths
                    ):
                        del sys.modules[name]


# ---------------------------------------------------------------------------
# built-in plugins: uv and conda (reference: runtime_env/uv.py, conda.py)
# ---------------------------------------------------------------------------

class UvPlugin(RuntimeEnvPlugin):
    """runtime_env={"uv": ["pkg", ...]} or {"uv": {"packages": [...]}}.

    Same wire shape and node-local cache as `pip` (the spec normalizer
    and the --target package-dir builder are shared), installed by the
    uv binary instead. Gated driver-side on `uv` being on PATH so an
    image without it fails at submit, not on a remote worker."""

    name = "uv"
    priority = 5

    def validate(self, value, worker):
        if shutil.which("uv") is None:
            raise exc.RuntimeEnvSetupError(
                "runtime_env['uv'] requires the uv binary on PATH; "
                "this image does not carry it — use runtime_env"
                "['pip'] or bake dependencies into the image"
            )
        return _normalize_pip(value, worker)

    def create(self, value, worker):
        if shutil.which("uv") is None:
            raise exc.RuntimeEnvSetupError(
                "runtime_env['uv']: uv binary missing on worker node"
            )
        return _ensure_pip_env(value, worker, tool="uv")

    def modify_context(self, state, value, ctx: RuntimeEnvContext):
        ctx.prepend_sys_path(state)


class CondaPlugin(RuntimeEnvPlugin):
    """runtime_env={"conda": {"dependencies": [...]}} builds a prefix
    env once per spec hash; {"conda": "/path/env.yml"} builds from an
    environment file; {"conda": "env-name"} activates an existing
    named env. Activation = prefix bin/ onto PATH + its site-packages
    onto sys.path (the reference swaps the worker interpreter,
    conda.py; the path prepend is this runtime's isolation unit).
    Gated driver-side on the conda binary."""

    name = "conda"
    priority = 5

    def validate(self, value, worker):
        if shutil.which("conda") is None:
            raise exc.RuntimeEnvSetupError(
                "runtime_env['conda'] requires the conda binary on "
                "PATH; this image does not carry it — use runtime_env"
                "['pip'] or bake dependencies into the image"
            )
        if isinstance(value, str) and not _looks_like_path(value):
            return {"kind": "named", "name": value}
        if isinstance(value, str):
            path = os.path.realpath(os.path.expanduser(value))
            if not os.path.isfile(path):
                raise exc.RuntimeEnvSetupError(
                    f"conda environment file {value!r} not found"
                )
            with open(path, "rb") as f:
                content = f.read()
            return {
                "kind": "file",
                "content": content,
                "hash": hashlib.sha256(content).hexdigest()[:16],
            }
        if isinstance(value, dict):
            # Only keys create() actually honors may pass: silently
            # dropping e.g. "name" or a nested pip section would build
            # a DIFFERENT environment than the spec describes while
            # the hash pretends otherwise.
            unsupported = set(value) - {"dependencies", "channels"}
            if unsupported:
                raise exc.RuntimeEnvSetupError(
                    f"conda spec dict keys {sorted(unsupported)} are "
                    "not supported (supported: dependencies, "
                    "channels); use the environment-file form "
                    '({"conda": "/path/env.yml"}) for full specs'
                )
            blob = repr(sorted(value.items())).encode()
            return {
                "kind": "spec",
                "spec": value,
                "hash": hashlib.sha256(blob).hexdigest()[:16],
            }
        raise exc.RuntimeEnvSetupError(
            "runtime_env['conda'] must be an env name, an environment "
            f"file path, or a spec dict; got {type(value).__name__}"
        )

    def create(self, value, worker):
        import subprocess

        if shutil.which("conda") is None:
            raise exc.RuntimeEnvSetupError(
                "runtime_env['conda']: conda binary missing on node"
            )
        if value["kind"] == "named":
            proc = subprocess.run(
                ["conda", "run", "-n", value["name"], "python", "-c",
                 "import sys; print(sys.prefix)"],
                capture_output=True, text=True, timeout=120,
            )
            if proc.returncode != 0:
                raise exc.RuntimeEnvSetupError(
                    f"conda env {value['name']!r} not activatable:\n"
                    f"{proc.stderr[-1000:]}"
                )
            return proc.stdout.strip()
        prefix = os.path.join(_CACHE_ROOT, "conda-" + value["hash"])
        if os.path.isdir(prefix):
            return prefix
        os.makedirs(_CACHE_ROOT, exist_ok=True)
        tmp = prefix + f".tmp{os.getpid()}"
        try:
            if value["kind"] == "file":
                envfile = tmp + ".yml"
                with open(envfile, "wb") as f:
                    f.write(value["content"])
                # No -y: `conda env create` never prompts, and the
                # flag only exists on conda >= 24.3.
                cmd = ["conda", "env", "create", "-p", tmp,
                       "-f", envfile]
            else:
                deps = value["spec"].get("dependencies", [])
                bad = [d for d in deps if not isinstance(d, str)]
                if bad:
                    raise exc.RuntimeEnvSetupError(
                        "conda spec dicts support string dependencies "
                        f"only (got {bad!r}); nested pip sections need "
                        "the environment-file form: "
                        '{"conda": "/path/env.yml"}'
                    )
                channels = []
                for channel in value["spec"].get("channels", []):
                    channels += ["-c", str(channel)]
                cmd = [
                    "conda", "create", "-y", "-p", tmp,
                    *channels, *deps,
                ]
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=1800
            )
            if proc.returncode != 0:
                raise exc.RuntimeEnvSetupError(
                    f"conda env build failed:\n{proc.stderr[-2000:]}"
                )
            try:
                os.rename(tmp, prefix)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        finally:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
            try:
                os.remove(tmp + ".yml")
            except OSError:
                pass
        return prefix

    def modify_context(self, state, value, ctx: RuntimeEnvContext):
        import glob

        ctx.set_env(
            "PATH",
            os.pathsep.join(
                p
                for p in (
                    os.path.join(state, "bin"),
                    os.environ.get("PATH"),
                )
                if p
            ),
        )
        ctx.set_env("CONDA_PREFIX", state)
        for site in glob.glob(
            os.path.join(state, "lib", "python*", "site-packages")
        ):
            ctx.prepend_sys_path(site)


register_plugin(UvPlugin())
register_plugin(CondaPlugin())
