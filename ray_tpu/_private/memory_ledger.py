"""Cluster memory & per-job usage ledger.

The object plane already *tracks* everything the ledger needs — per
object ownership, size, pin state, spill state live in the daemon's
object table (`daemon.ObjectEntry`) — it just never *exported* it:
the metrics pipe only carried node-level aggregates
(`rt_object_store_bytes_used`, `rt_spilled_bytes`), so nobody could
answer whose bytes fill an arena or which job's pins block eviction.

Reference: the plasma store + raylet keep per-object ownership and
spill URLs queryable cluster-wide (`ray memory`,
util/state/memory_utils.py over ObjectTableData); the multi-tenant
scheduling literature (PAPERS.md ring-all-reduce fair-share) needs
*measured* per-job usage before quotas can be enforced. This module is
that measurement substrate.

Two halves:

* ``build_node_report`` — a pure fold of one node's object-table
  snapshot into a compact per-node memory report: arena used/capacity,
  per-(job, owner) byte totals, the top-K largest live objects,
  dead-owner pin candidates (owner pid probed node-locally), and the
  spill/restore op counters rates are differenced from. Runs OFF the
  hot path, on each daemon's memory-report tick
  (``memory_report_interval_s``) — the microbench ``memory_report_ms``
  keeps the fold honest at 10k live objects.

* ``MemoryLedger`` — the head-side aggregate: latest report per node,
  per-job byte·seconds (object bytes integrated over report
  intervals) and chip·seconds (from the step-telemetry records already
  flowing), spill/restore rates per node, and the doctor's
  ``verdict.memory``: nodes near arena capacity, leak suspects
  (objects held past ``doctor_leak_age_s`` by dead owners), and spill
  thrash (restore rate ≈ spill rate — the store is paging, not
  spilling cold data).

Exported series (ride ``metrics_summary`` → Prometheus ``/metrics``
and the head's time-series ring, so trends survive the live window):

* ``rt_job_object_bytes``             gauge    {job}
* ``rt_job_object_byte_seconds_total`` counter {job}
* ``rt_job_chip_seconds_total``        counter {job}
* ``rt_object_owner_bytes``           gauge    {job, owner kind}

Data-plane provenance (ISSUE 20): the ledger additionally folds the
two record kinds the object read path emits through the metrics pipe —
``transfer`` records (one per completed/aborted pull or spill restore,
emitted by the daemon that moved the bytes) and ``get`` records
(per-(provenance, src, task-class) aggregates drained from each
worker's get path, never one record per get) — into a bounded
per-(job, src_node, dst_node) transfer matrix plus per-job locality
counters:

* ``rt_object_transfer_bytes_total``  counter  {job, src, dst}
* ``rt_object_pull_ms``               gauge    {job, src, dst} (mean)
* ``rt_job_locality_hits_total``      counter  {job}
* ``rt_job_locality_misses_total``    counter  {job}
* ``rt_object_spills_total`` / ``rt_object_restores_total`` gain
  per-``{job}`` tag series merged alongside the core per-node series

Label cardinality is bounded by construction: jobs are few, src/dst
are NODE ids (the matrix is at most jobs x nodes^2, flows evicted past
``_MAX_FLOWS``), and the owner label carries the owning-context KIND
(driver/task/actor), never a per-entity id — a per-id or per-flow-id
label would mint one Prometheus series per task/transfer over the
cluster's lifetime, the exact pattern lint rule RT010 bans. The full
per-owner map is served by ``memory_summary`` / ``/api/memory``; the
full matrix (with task-class attribution) by ``transfer_summary`` /
``/api/transfers``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "build_node_report",
    "MemoryLedger",
    "NEAR_CAPACITY_FRACTION",
    "PROVENANCE_CLASSES",
]

#: Arena used/capacity fraction past which a node is "near capacity"
#: in the doctor verdict (above the spill threshold's 0.8 steady
#: state: a node the spiller cannot keep under 0.9 is in trouble).
NEAR_CAPACITY_FRACTION = 0.9

#: Dead-owner candidates carried per node report (size-descending;
#: a leak worth paging about is big, and the bound keeps reports
#: O(topk), not O(objects)).
_MAX_DEAD_OWNER_OBJECTS = 64

#: Spill ops per window below which thrash detection stays quiet —
#: a handful of pressure-driven spills is normal operation.
_THRASH_MIN_OPS = 4

#: Jobs tracked in the byte·s / chip·s accumulators before the
#: smallest consumers are evicted (bounded head memory forever).
_MAX_JOBS = 256

#: (job, src, dst) transfer-matrix rows kept before the smallest flow
#: is evicted — at most jobs x nodes^2 in practice, this cap is the
#: backstop against job churn minting rows forever.
_MAX_FLOWS = 512

#: (job, task-class) get-attribution rows kept (task classes are code,
#: bounded in practice; the cap bounds adversarial name churn).
_MAX_TASK_ROWS = 256

#: Remote bytes a task class must pull before the misplacement verdict
#: will convict it — nobody gets paged over a 100 KB arg.
_MISPLACED_MIN_BYTES = 1 << 20

#: Get provenance classes the worker read path reports (see
#: worker._record_get): where the resolved bytes actually came from.
PROVENANCE_CLASSES = (
    "inline",          # small value answered from a cache / the head table
    "local",           # local arena hit (the copy was already here)
    "pull",            # pulled from a remote node's arena
    "restore_local",   # restored from THIS node's spill storage
    "restore_remote",  # pulled from a REMOTE node's spill storage
)


def _flat_owner(job: str, owner: str) -> str:
    return f"{job}|{owner}"


def build_node_report(
    node: str,
    entries: Iterable[tuple],
    size_info: dict,
    spill_stats: Optional[dict] = None,
    spill_ops: int = 0,
    restore_ops: int = 0,
    job_spill_ops: Optional[Dict[str, int]] = None,
    job_restore_ops: Optional[Dict[str, int]] = None,
    topk: int = 20,
    now: Optional[float] = None,
    pid_alive: Optional[Callable[[int], bool]] = None,
) -> dict:
    """Fold one node's object-table snapshot into a memory report.

    ``entries`` is an iterable of tuples
    ``(oid, size, job, owner, owner_pid, created_ts, pinned, spilled,
    in_shm)`` — ``oid`` anything with ``.hex()`` (hex is only paid for
    the few objects that land in top-K / candidate lists). Pure except
    for the owner-pid liveness probe, which runs once per distinct pid
    and only for pids that produced still-held bytes.
    """
    now = time.time() if now is None else float(now)
    if pid_alive is None:
        pid_alive = _default_pid_alive()
    owners: Dict[str, dict] = {}
    attributed = 0
    shm_bytes = 0
    top: List[tuple] = []
    dead: List[tuple] = []
    alive_cache: Dict[int, bool] = {}

    def _alive(pid: int) -> bool:
        cached = alive_cache.get(pid)
        if cached is None:
            cached = alive_cache[pid] = bool(pid_alive(pid))
        return cached

    n_entries = 0
    for (
        oid,
        size,
        job,
        owner,
        owner_pid,
        created_ts,
        pinned,
        spilled,
        in_shm,
    ) in entries:
        n_entries += 1
        size = int(size)
        if in_shm:
            shm_bytes += size
        if job:
            row = owners.get(_flat_owner(job, owner))
            if row is None:
                row = owners[_flat_owner(job, owner)] = {
                    "job": job,
                    "owner": owner,
                    "bytes": 0,
                    "objects": 0,
                    "pinned_objects": 0,
                    "spilled_bytes": 0,
                }
            if in_shm:
                row["bytes"] += size
                attributed += size
            if spilled:
                row["spilled_bytes"] += size
            row["objects"] += 1
            if pinned:
                row["pinned_objects"] += 1
        record = (size, oid, job, owner, owner_pid, created_ts, pinned, spilled)
        top.append(record)
        if owner_pid and not _alive(owner_pid):
            dead.append(record)
    top.sort(key=lambda r: r[0], reverse=True)
    dead.sort(key=lambda r: r[0], reverse=True)

    def _obj_row(record: tuple) -> dict:
        size, oid, job, owner, owner_pid, created_ts, pinned, spilled = record
        return {
            "object_id": oid.hex() if hasattr(oid, "hex") else str(oid),
            "size": size,
            "job": job,
            "owner": owner,
            "owner_pid": owner_pid,
            "owner_alive": _alive(owner_pid) if owner_pid else True,
            "age_s": round(now - created_ts, 3) if created_ts else 0.0,
            "pinned": bool(pinned),
            "spilled": bool(spilled),
        }

    used = int(size_info.get("used", 0))
    spill_stats = spill_stats or {}
    return {
        "node": node,
        "time": now,
        "arena_used": used,
        "arena_capacity": int(size_info.get("capacity", 0)),
        "arena_objects": int(size_info.get("num_objects", 0)),
        "tracked_objects": n_entries,
        "shm_bytes": shm_bytes,
        "spilled_bytes": int(spill_stats.get("spilled_bytes", 0)),
        "spilled_objects": int(spill_stats.get("spilled_objects", 0)),
        "spill_ops_total": int(spill_ops),
        "restore_ops_total": int(restore_ops),
        # Cumulative per-job op counts (satellite: the verdict's
        # restore-dominated call must be job-named, and node-level
        # totals can't say WHOSE working set is paging). Latest-report
        # semantics like every other field: the ledger sums the latest
        # value across nodes, it never differences these.
        "job_spill_ops": {
            str(j): int(n) for j, n in (job_spill_ops or {}).items()
        },
        "job_restore_ops": {
            str(j): int(n) for j, n in (job_restore_ops or {}).items()
        },
        "owners": owners,
        "attributed_bytes": attributed,
        # Attribution is judged against what the arena reports in use:
        # allocator slack and ownerless objects both show up here.
        "attribution_fraction": round(attributed / used, 4) if used else 1.0,
        "top_objects": [_obj_row(r) for r in top[: max(0, int(topk))]],
        "dead_owner_objects": [
            _obj_row(r) for r in dead[:_MAX_DEAD_OWNER_OBJECTS]
        ],
    }


def _default_pid_alive() -> Callable[[int], bool]:
    def alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True  # exists, other uid
        except OSError:
            return True  # can't tell: never convict on a probe error
        return True

    return alive


class MemoryLedger:
    """Head-side aggregate over per-node memory reports.

    Bounded: one latest report per node, one accumulator row per job
    (smallest evicted past ``_MAX_JOBS``), rates from differencing the
    previous report's counters — nothing here grows with object count
    or cluster lifetime.
    """

    def __init__(self, max_owner_series: int = 20):
        self._lock = threading.Lock()
        self.reports: Dict[str, dict] = {}  # node -> latest report
        self._rates: Dict[str, dict] = {}  # node -> spill/restore rates
        self._job_byte_s: Dict[str, float] = {}
        self._job_chip_s: Dict[str, float] = {}
        self._max_owner_series = max(1, int(max_owner_series))
        # Transfer matrix: (job, src, dst) -> flow row. "bytes" counts
        # only COMPLETED transfers — an aborted pull bumps "aborted"
        # and nothing else, so a holder dying mid-pull can never be
        # double-billed as moved bytes (the retry that succeeds bills
        # them once).
        self._flows: Dict[Tuple[str, str, str], dict] = {}
        # Per-job get provenance: job -> {provenance: {gets, bytes,
        # wait_ms}} (provenance keys from PROVENANCE_CLASSES — fixed).
        self._job_prov: Dict[str, Dict[str, dict]] = {}
        # Per-job locality counters: job -> [hits, misses].
        self._locality: Dict[str, list] = {}
        # Per-(job, task-class) get attribution for the misplacement
        # verdict: remote vs local bytes, plus the src-node histogram
        # of the remote share.
        self._task_gets: Dict[Tuple[str, str], dict] = {}

    # -- folds ---------------------------------------------------------
    def fold(self, report: dict) -> None:
        """Fold one node report: replace the node's latest view,
        integrate per-job byte·seconds over the interval since the
        node's previous report, and difference spill/restore counters
        into rates."""
        node = str(report.get("node", ""))
        with self._lock:
            prev = self.reports.get(node)
            now = float(report.get("time", time.time()))
            if prev is not None:
                dt = now - float(prev.get("time", now))
                if 0.0 < dt < 3600.0:
                    for row in prev.get("owners", {}).values():
                        job = row.get("job", "")
                        if job:
                            self._bump(
                                self._job_byte_s, job, row["bytes"] * dt
                            )
                    spills = report.get("spill_ops_total", 0) - prev.get(
                        "spill_ops_total", 0
                    )
                    restores = report.get(
                        "restore_ops_total", 0
                    ) - prev.get("restore_ops_total", 0)
                    self._rates[node] = {
                        "window_s": round(dt, 3),
                        "spill_ops": max(0, spills),
                        "restore_ops": max(0, restores),
                        "spill_per_s": round(max(0, spills) / dt, 3),
                        "restore_per_s": round(max(0, restores) / dt, 3),
                    }
            self.reports[node] = report

    def add_step(self, record: dict) -> None:
        """Accumulate one step-telemetry record's chip·seconds — each
        (step, rank) record is ``step_ms`` of one chip's work for its
        job. Called at record-APPEND time (daemon
        ``_apply_metric_record``) so the accounting is exact: a
        periodic re-scan of the bounded diagnostic step ring would
        silently drop records that aged out between folds, and a
        wall-clock watermark would drop same-timestamp stragglers."""
        job = str(record.get("job", "") or "")
        if not job or record.get("warmup"):
            return
        with self._lock:
            self._bump(
                self._job_chip_s,
                job,
                float(record.get("step_ms", 0.0)) / 1000.0,
            )

    def record_transfer(
        self,
        job: str,
        src: str,
        dst: str,
        kind: str,
        nbytes: float,
        ms: float = 0.0,
    ) -> None:
        """Fold one daemon-side transfer record into the matrix.

        ``kind``: ``pull`` (remote arena -> dst), ``pull_spill``
        (remote node's SPILL storage -> dst: restore traffic that also
        crossed the wire), ``restore`` (dst's own spill -> dst arena),
        or ``aborted`` (a pull that died mid-flight: counted, never
        billed as transferred bytes)."""
        key = (str(job or ""), str(src or ""), str(dst or ""))
        with self._lock:
            row = self._flows.get(key)
            if row is None:
                row = self._flows[key] = {
                    "bytes": 0,
                    "ms": 0.0,
                    "pulls": 0,
                    "restores": 0,
                    "aborted": 0,
                    "restored_bytes": 0,
                }
                if len(self._flows) > _MAX_FLOWS:
                    victim = min(
                        (k for k in self._flows if k != key),
                        key=lambda k: self._flows[k]["bytes"],
                    )
                    self._flows.pop(victim)
            if kind == "aborted":
                row["aborted"] += 1
                return
            row["bytes"] += int(nbytes)
            row["ms"] += float(ms)
            if kind == "restore":
                row["restores"] += 1
                row["restored_bytes"] += int(nbytes)
            else:
                row["pulls"] += 1
                if kind == "pull_spill":
                    row["restored_bytes"] += int(nbytes)

    def record_gets(
        self,
        job: str,
        provenance: str,
        src: str,
        dst: str,
        task: str,
        count: float,
        nbytes: float,
        ms: float = 0.0,
    ) -> None:
        """Fold one worker-side get-provenance aggregate (a batch of
        ``count`` gets that resolved the same way): per-job provenance
        totals, the locality hit/miss counters, and the per-task-class
        remote-vs-local attribution the misplacement verdict reads."""
        job = str(job or "")
        provenance = str(provenance or "")
        if provenance not in PROVENANCE_CLASSES:
            return
        count = int(count)
        nbytes = int(nbytes)
        remote = provenance in ("pull", "restore_remote")
        with self._lock:
            prov = self._job_prov.get(job)
            if prov is None:
                if len(self._job_prov) >= _MAX_JOBS:
                    return
                prov = self._job_prov[job] = {}
            row = prov.setdefault(
                provenance, {"gets": 0, "bytes": 0, "wait_ms": 0.0}
            )
            row["gets"] += count
            row["bytes"] += nbytes
            row["wait_ms"] += float(ms)
            loc = self._locality.setdefault(job, [0, 0])
            if provenance in ("inline", "local"):
                loc[0] += count
            else:
                loc[1] += count
            tkey = (job, str(task or ""))
            trow = self._task_gets.get(tkey)
            if trow is None:
                if len(self._task_gets) >= _MAX_TASK_ROWS:
                    return
                trow = self._task_gets[tkey] = {
                    "remote_bytes": 0,
                    "local_bytes": 0,
                    "wait_ms": 0.0,
                    "by_src": {},
                }
            trow["wait_ms"] += float(ms)
            if remote:
                trow["remote_bytes"] += nbytes
                if src:
                    by_src = trow["by_src"]
                    by_src[src] = by_src.get(src, 0) + nbytes
            else:
                trow["local_bytes"] += nbytes

    def drop_node(self, node: str) -> None:
        """A node died: its arena is gone, so its report must not keep
        attributing bytes (the ledger's byte·s already banked what it
        consumed while alive)."""
        with self._lock:
            self.reports.pop(node, None)
            self._rates.pop(node, None)

    @staticmethod
    def _bump(table: Dict[str, float], key: str, amount: float) -> None:
        table[key] = table.get(key, 0.0) + amount
        if len(table) > _MAX_JOBS:
            # Never evict the key just bumped: a full table would
            # otherwise pop every NEW job's first (smallest) row on
            # insert, permanently starving job #257 of accounting.
            victim = min(
                (k for k in table if k != key), key=table.get
            )
            table.pop(victim)

    # -- views ---------------------------------------------------------
    def jobs(self) -> Dict[str, dict]:
        """Per-job usage rows across the latest node reports plus the
        integrated accumulators."""
        with self._lock:
            out: Dict[str, dict] = {}
            for report in self.reports.values():
                for row in report.get("owners", {}).values():
                    job = row.get("job", "")
                    if not job:
                        continue
                    agg = out.setdefault(
                        job,
                        {
                            "object_bytes": 0,
                            "objects": 0,
                            "pinned_objects": 0,
                            "spilled_bytes": 0,
                        },
                    )
                    agg["object_bytes"] += row["bytes"]
                    agg["objects"] += row["objects"]
                    agg["pinned_objects"] += row["pinned_objects"]
                    agg["spilled_bytes"] += row["spilled_bytes"]
            for report in self.reports.values():
                # Per-job spill/restore OPS (cumulative per node; the
                # latest reports sum to the cluster total — these are
                # never differenced, unlike the node-level rates).
                for field, src_key in (
                    ("spill_ops", "job_spill_ops"),
                    ("restore_ops", "job_restore_ops"),
                ):
                    for job, n in report.get(src_key, {}).items():
                        agg = out.setdefault(
                            job,
                            {
                                "object_bytes": 0,
                                "objects": 0,
                                "pinned_objects": 0,
                                "spilled_bytes": 0,
                            },
                        )
                        agg[field] = agg.get(field, 0) + int(n)
            for job, total in self._job_byte_s.items():
                out.setdefault(
                    job,
                    {
                        "object_bytes": 0,
                        "objects": 0,
                        "pinned_objects": 0,
                        "spilled_bytes": 0,
                    },
                )["object_byte_seconds"] = round(total, 1)
            for job, total in self._job_chip_s.items():
                out.setdefault(
                    job,
                    {
                        "object_bytes": 0,
                        "objects": 0,
                        "pinned_objects": 0,
                        "spilled_bytes": 0,
                    },
                )["chip_seconds"] = round(total, 3)
            return out

    def owners(self) -> List[dict]:
        """Per-(job, owner) rows summed across nodes, bytes
        descending (the full map; metric export truncates)."""
        with self._lock:
            merged: Dict[str, dict] = {}
            for report in self.reports.values():
                for key, row in report.get("owners", {}).items():
                    agg = merged.get(key)
                    if agg is None:
                        merged[key] = dict(row)
                    else:
                        for field in (
                            "bytes",
                            "objects",
                            "pinned_objects",
                            "spilled_bytes",
                        ):
                            agg[field] += row[field]
        return sorted(
            merged.values(), key=lambda r: r["bytes"], reverse=True
        )

    def summary(self) -> dict:
        """The cluster view `ray_tpu memory` / ``/api/memory`` serve."""
        with self._lock:
            reports = list(self.reports.values())
            rates = dict(self._rates)
        used = sum(r.get("arena_used", 0) for r in reports)
        capacity = sum(r.get("arena_capacity", 0) for r in reports)
        attributed = sum(r.get("attributed_bytes", 0) for r in reports)
        top: List[dict] = []
        for report in reports:
            top.extend(report.get("top_objects", ()))
        top.sort(key=lambda r: r.get("size", 0), reverse=True)
        return {
            "time": time.time(),
            "totals": {
                "arena_used": used,
                "arena_capacity": capacity,
                "spilled_bytes": sum(
                    r.get("spilled_bytes", 0) for r in reports
                ),
                "attributed_bytes": attributed,
                "attribution_fraction": (
                    round(attributed / used, 4) if used else 1.0
                ),
            },
            "jobs": self.jobs(),
            "owners": self.owners(),
            "top_objects": top[: self._max_owner_series],
            "nodes": reports,
            "rates": rates,
        }

    def transfer_summary(self) -> dict:
        """The data-plane view ``transfer_summary`` / ``/api/transfers``
        / ``ray_tpu memory --transfers`` serve: the full per-(job, src,
        dst) matrix (bytes descending), per-job get provenance and
        locality, the top remote-pulling task classes, and per-job
        spill/restore op totals."""
        with self._lock:
            flows = [
                {
                    "job": job,
                    "src": src,
                    "dst": dst,
                    "cross_node": bool(src and dst and src != dst),
                    **dict(row),
                    "mb_per_s": (
                        round(row["bytes"] / row["ms"] / 1e3, 2)
                        if row["ms"] > 0
                        else 0.0
                    ),
                }
                for (job, src, dst), row in self._flows.items()
            ]
            provenance = {
                job: {p: dict(r) for p, r in rows.items()}
                for job, rows in self._job_prov.items()
            }
            locality = {
                job: {
                    "hits": hits,
                    "misses": misses,
                    "hit_fraction": (
                        round(hits / (hits + misses), 4)
                        if hits + misses
                        else 1.0
                    ),
                }
                for job, (hits, misses) in self._locality.items()
            }
            tasks = [
                {
                    "job": job,
                    "task": task,
                    "remote_bytes": row["remote_bytes"],
                    "local_bytes": row["local_bytes"],
                    "wait_ms": round(row["wait_ms"], 3),
                    "by_src": dict(row["by_src"]),
                }
                for (job, task), row in self._task_gets.items()
            ]
        flows.sort(key=lambda f: f["bytes"], reverse=True)
        tasks.sort(key=lambda t: t["remote_bytes"], reverse=True)
        jobs = self.jobs()
        return {
            "time": time.time(),
            "flows": flows,
            "provenance": provenance,
            "locality": locality,
            "tasks": tasks,
            "job_spill_ops": {
                job: row["spill_ops"]
                for job, row in jobs.items()
                if row.get("spill_ops")
            },
            "job_restore_ops": {
                job: row["restore_ops"]
                for job, row in jobs.items()
                if row.get("restore_ops")
            },
        }

    def metric_entries(self) -> Dict[str, dict]:
        """The ledger's Prometheus series, shaped like
        ``metrics_summary`` entries so they ride the existing
        exposition + time-series paths unchanged."""
        jobs = self.jobs()
        entries: Dict[str, dict] = {}
        if jobs:
            entries["rt_job_object_bytes"] = {
                "kind": "gauge",
                "unit": "bytes",
                "description": "Object-store bytes attributed to each job",
                "value": sum(j["object_bytes"] for j in jobs.values()),
                "by_tags": {
                    f"job={job}": {"value": row["object_bytes"]}
                    for job, row in jobs.items()
                },
            }
            byte_s = {
                job: row["object_byte_seconds"]
                for job, row in jobs.items()
                if "object_byte_seconds" in row
            }
            if byte_s:
                entries["rt_job_object_byte_seconds_total"] = {
                    "kind": "counter",
                    "unit": "byte_seconds",
                    "description": (
                        "Object bytes integrated over time per job "
                        "(the ledger's usage-for-billing series)"
                    ),
                    "total": sum(byte_s.values()),
                    "by_tags": {
                        f"job={job}": {"total": v}
                        for job, v in byte_s.items()
                    },
                }
            chip_s = {
                job: row["chip_seconds"]
                for job, row in jobs.items()
                if "chip_seconds" in row
            }
            if chip_s:
                entries["rt_job_chip_seconds_total"] = {
                    "kind": "counter",
                    "unit": "chip_seconds",
                    "description": (
                        "Measured chip-seconds per job from step "
                        "telemetry (sum of per-rank step_ms)"
                    ),
                    "total": sum(chip_s.values()),
                    "by_tags": {
                        f"job={job}": {"total": v}
                        for job, v in chip_s.items()
                    },
                }
        owners = self.owners()
        if owners:
            # Owner label = the owning-context KIND (driver / task /
            # actor), never the id: a per-id label value mints one
            # Prometheus series per task forever (top-K per scrape
            # still churns the label set over the cluster's lifetime)
            # — the exact pattern lint rule RT010 bans. The full
            # per-owner map is served by /api/memory and the CLI.
            by_kind: Dict[str, int] = {}
            for row in owners:
                kind = (row["owner"] or "unknown").split(":", 1)[0]
                key = f"job={row['job']}|owner={kind}"
                by_kind[key] = by_kind.get(key, 0) + row["bytes"]
            entries["rt_object_owner_bytes"] = {
                "kind": "gauge",
                "unit": "bytes",
                "description": (
                    "Object-store bytes per (job, owner kind: "
                    "driver/task/actor) — per-owner detail is "
                    "/api/memory"
                ),
                "value": sum(r["bytes"] for r in owners),
                "by_tags": {
                    key: {"value": v} for key, v in by_kind.items()
                },
            }
        # Data-plane series. src_node/dst_node are NODE ids as
        # SEPARATE labels — the only identity granularity RT010
        # permits on these series (a per-object, per-transfer, or
        # fused src-dst-pair label would mint unbounded Prometheus
        # series). Tag keys stay alphabetical so they round-trip
        # through prometheus._parse_tag_key like worker-built tags.
        with self._lock:
            flows = {k: dict(v) for k, v in self._flows.items()}
            locality = {
                job: tuple(hm) for job, hm in self._locality.items()
            }
        if flows:
            entries["rt_object_transfer_bytes_total"] = {
                "kind": "counter",
                "unit": "bytes",
                "description": (
                    "Object bytes moved into each node's store per "
                    "(job, src node, dst node): pulls plus spill "
                    "restores; aborted pulls bill nothing"
                ),
                "total": sum(r["bytes"] for r in flows.values()),
                "by_tags": {
                    f"dst_node={dst}|job={job}|src_node={src}": {
                        "total": row["bytes"]
                    }
                    for (job, src, dst), row in flows.items()
                },
            }
            pull_ms = {
                key: row
                for key, row in flows.items()
                if row["pulls"] + row["restores"] > 0
            }
            if pull_ms:
                entries["rt_object_pull_ms"] = {
                    "kind": "gauge",
                    "unit": "ms",
                    "description": (
                        "Mean transfer latency per (job, src node, "
                        "dst node) flow — cumulative detail is "
                        "/api/transfers"
                    ),
                    "value": round(
                        sum(r["ms"] for r in pull_ms.values())
                        / max(
                            1,
                            sum(
                                r["pulls"] + r["restores"]
                                for r in pull_ms.values()
                            ),
                        ),
                        3,
                    ),
                    "by_tags": {
                        f"dst_node={dst}|job={job}|src_node={src}": {
                            "value": round(
                                row["ms"]
                                / (row["pulls"] + row["restores"]),
                                3,
                            )
                        }
                        for (job, src, dst), row in pull_ms.items()
                    },
                }
        if locality:
            for name, index, what in (
                ("rt_job_locality_hits_total", 0, "inline/local"),
                ("rt_job_locality_misses_total", 1, "pull/restore"),
            ):
                entries[name] = {
                    "kind": "counter",
                    "unit": "gets",
                    "description": (
                        f"rt.get resolutions per job whose bytes were "
                        f"{what}"
                    ),
                    "total": sum(hm[index] for hm in locality.values()),
                    "by_tags": {
                        f"job={job}": {"total": hm[index]}
                        for job, hm in locality.items()
                    },
                }
        # Per-job spill/restore op tag series, merged by the head's
        # metrics_summary INTO the core per-node entries of the same
        # name (node totals stay; the job dimension rides alongside).
        jobs = self.jobs()
        for name, field, what in (
            ("rt_object_spills_total", "spill_ops", "spilled"),
            ("rt_object_restores_total", "restore_ops", "restored"),
        ):
            per_job = {
                job: row[field]
                for job, row in jobs.items()
                if row.get(field)
            }
            if per_job:
                entries[name] = {
                    "kind": "counter",
                    "unit": "ops",
                    "description": (
                        f"Objects {what} (per-job attribution from "
                        "the memory ledger)"
                    ),
                    "by_tags": {
                        f"job={job}": {"total": n}
                        for job, n in per_job.items()
                    },
                }
        return entries

    # -- doctor --------------------------------------------------------
    def verdict(
        self,
        leak_age_s: float,
        now: Optional[float] = None,
        job_ended: Optional[Callable[[str], bool]] = None,
        near_capacity_fraction: float = NEAR_CAPACITY_FRACTION,
    ) -> dict:
        """``verdict.memory``: (a) nodes near arena capacity, (b) leak
        suspects — objects held past ``leak_age_s`` whose owner
        process died (node-local pid probe) or whose job already ended,
        (c) spill thrash — a window where restores keep up with
        spills, i.e. the store is paging its working set."""
        now = time.time() if now is None else float(now)
        job_ended = job_ended or (lambda job: False)
        with self._lock:
            reports = list(self.reports.values())
            rates = dict(self._rates)
        near: List[dict] = []
        suspects: List[dict] = []
        thrash: List[dict] = []
        for report in reports:
            node = report.get("node", "")
            used = report.get("arena_used", 0)
            capacity = report.get("arena_capacity", 0)
            if capacity and used / capacity >= near_capacity_fraction:
                near.append(
                    {
                        "node": node,
                        "used": used,
                        "capacity": capacity,
                        "fraction": round(used / capacity, 4),
                        "detail": (
                            f"node {node[:12]} arena at "
                            f"{100.0 * used / capacity:.0f}% of "
                            f"{capacity / 1e6:.0f} MB — spilling can't "
                            "keep up; add nodes or shed the top owners"
                        ),
                    }
                )
            seen: set = set()
            candidates = list(report.get("dead_owner_objects", ()))
            for row in report.get("top_objects", ()):
                # A clean-exited owner leaves owner_alive False too;
                # top objects additionally catch ended-job leaks whose
                # owner pid was recycled.
                if not row.get("owner_alive", True) or (
                    row.get("job") and job_ended(row["job"])
                ):
                    candidates.append(row)
            for row in candidates:
                oid = row.get("object_id", "")
                if oid in seen:
                    continue
                seen.add(oid)
                age = float(row.get("age_s", 0.0))
                if age <= leak_age_s:
                    continue
                dead_owner = not row.get("owner_alive", True)
                ended = bool(row.get("job")) and job_ended(row["job"])
                if not (dead_owner or ended):
                    continue
                why = (
                    "owner process died"
                    if dead_owner
                    else "owning job already ended"
                )
                suspects.append(
                    {
                        "node": node,
                        "object_id": oid,
                        "size": row.get("size", 0),
                        "job": row.get("job", ""),
                        "owner": row.get("owner", ""),
                        "age_s": age,
                        "pinned": row.get("pinned", False),
                        "detail": (
                            f"object {oid[:12]} "
                            f"({row.get('size', 0) / 1e6:.1f} MB, owner "
                            f"{row.get('owner', '?')}) still held "
                            f"after {age:.1f}s (> {leak_age_s:g}s leak "
                            f"deadline) but its {why} — a dropped ref "
                            "or a leaked borrow is pinning it"
                        ),
                    }
                )
        for node, rate in rates.items():
            spills = rate.get("spill_ops", 0)
            restores = rate.get("restore_ops", 0)
            if (
                spills >= _THRASH_MIN_OPS
                and restores >= 0.5 * spills
            ):
                thrash.append(
                    {
                        "node": node,
                        "spill_per_s": rate.get("spill_per_s", 0.0),
                        "restore_per_s": rate.get("restore_per_s", 0.0),
                        "detail": (
                            f"node {node[:12]} spilled {spills} and "
                            f"restored {restores} objects in "
                            f"{rate.get('window_s', 0):g}s — restore "
                            "rate ≈ spill rate means the working set "
                            "exceeds the arena (thrash), not cold data "
                            "aging out"
                        ),
                    }
                )
        suspects.sort(key=lambda s: s.get("size", 0), reverse=True)
        used = sum(r.get("arena_used", 0) for r in reports)
        attributed = sum(r.get("attributed_bytes", 0) for r in reports)
        return {
            "near_capacity": near,
            "leak_suspects": suspects,
            "spill_thrash": thrash,
            "attribution_fraction": (
                round(attributed / used, 4) if used else 1.0
            ),
            "params": {
                "leak_age_s": leak_age_s,
                "near_capacity_fraction": near_capacity_fraction,
            },
        }

    def data_verdict(
        self,
        locality_miss_threshold: float = 0.5,
        node_has_capacity: Optional[Callable[[str], bool]] = None,
        min_remote_bytes: int = _MISPLACED_MIN_BYTES,
    ) -> dict:
        """``verdict.data``: (a) the hottest cross-node flow, (b) a
        pull-dominated vs restore-dominated classification per job
        that moved bytes (restore-dominated = the working set is
        paging through spill, add memory; pull-dominated = the bytes
        crossed nodes, fix placement), (c) misplaced-task suspects —
        task classes whose gets pulled most of their bytes remotely
        while a copy-holding node had capacity to run them.

        ``node_has_capacity`` answers "could the src node have hosted
        the task" (the head passes a scheduler-view probe); with no
        probe every copy-holder is assumed to have had room — the
        conservative direction for an observability verdict would be
        the opposite, but an instrument that never convicts teaches
        nothing, and the probe is always supplied in production.
        """
        node_has_capacity = node_has_capacity or (lambda node: True)
        summary = self.transfer_summary()
        hottest = None
        for flow in summary["flows"]:
            if flow["cross_node"] and flow["bytes"] > 0:
                hottest = flow  # flows are bytes-descending
                break
        job_rows: Dict[str, dict] = {}
        for flow in summary["flows"]:
            row = job_rows.setdefault(
                flow["job"],
                {"transfer_bytes": 0, "restored_bytes": 0},
            )
            row["transfer_bytes"] += flow["bytes"]
            row["restored_bytes"] += flow["restored_bytes"]
        for job, row in job_rows.items():
            pulled = row["transfer_bytes"] - row["restored_bytes"]
            row["classification"] = (
                "restore_dominated"
                if row["restored_bytes"] >= max(1, pulled)
                else "pull_dominated"
            )
            row["restore_ops"] = summary["job_restore_ops"].get(job, 0)
        misplaced: List[dict] = []
        for trow in summary["tasks"]:
            total = trow["remote_bytes"] + trow["local_bytes"]
            if (
                trow["remote_bytes"] < min_remote_bytes
                or not total
                or trow["remote_bytes"] / total < locality_miss_threshold
            ):
                continue
            if not trow["by_src"]:
                continue
            src = max(trow["by_src"], key=trow["by_src"].get)
            if not node_has_capacity(src):
                continue
            frac = trow["remote_bytes"] / total
            misplaced.append(
                {
                    "job": trow["job"],
                    "task": trow["task"] or "driver",
                    "remote_bytes": trow["remote_bytes"],
                    "remote_fraction": round(frac, 4),
                    "src": src,
                    "wait_ms": trow["wait_ms"],
                    "detail": (
                        f"task class {trow['task'] or 'driver'!r} "
                        f"(job {trow['job'][:8]}) pulled "
                        f"{trow['remote_bytes'] / 1e6:.1f} MB remotely "
                        f"({100 * frac:.0f}% of its get bytes), mostly "
                        f"from node {src[:12]}, which had capacity — "
                        "schedule it there (or co-locate its inputs) "
                        "and those gets become local arena hits"
                    ),
                }
            )
        misplaced.sort(key=lambda s: s["remote_bytes"], reverse=True)
        return {
            "hottest_flow": hottest,
            "jobs": job_rows,
            "locality": summary["locality"],
            "misplaced_tasks": misplaced,
            "params": {
                "locality_miss_threshold": locality_miss_threshold,
                "min_remote_bytes": min_remote_bytes,
            },
        }
