"""Direct task transport: submitter-to-worker task push over leased
workers, daemons only for placement.

This is the TPU-native analog of the reference's direct task calls
(reference: src/ray/core_worker/transport/normal_task_submitter.cc:23,
83,141 — the submitter leases a worker per scheduling key from the
raylet, then pushes task specs worker-to-worker with the raylet out of
the data path; and actor_task_submitter.h — actor calls go straight to
the actor's worker over an established connection).

Architecture
------------
- Every worker process serves a tiny RPC endpoint (its *direct
  address*, a Unix socket in the session dir). ``execute_task``
  requests enqueue into the worker's single task loop and the reply —
  carrying inline results — is deferred until execution finishes, so
  per-connection ordering and single-threaded actor semantics are
  preserved while requests pipeline in the socket.
- For **normal tasks**, the driver holds leases per *scheduling key*
  (resources + TPU-ness), granted by the daemon (``request_lease`` — a
  pseudo-task through the LocalScheduler, so resource accounting and
  fairness are shared with the daemon-scheduled path). The hot path
  has NO dedicated threads: the submitting thread sends the spec with
  ``RpcClient.call_async`` and the lease connection's reader thread
  fulfills the result future and dispatches the next queued spec.
  One background "requester" thread serves lease-pool growth, idle
  release, and starvation sweeps off the critical path.
- For **actor tasks**, one router thread per actor handle resolves the
  actor's direct address once (blocking ``actor_address`` call that
  the daemon answers when the actor is ALIVE) and then pushes calls
  directly. Actors hosted off-node (or whose worker died) fall back
  to the daemon path — *sticky*, so per-handle ordering is never
  split across two transports in flight.
- Results come back inline in the RPC reply (small) or as
  ``("shm", size)`` markers after the worker seals them in the node's
  shared store (large — the zero-copy path). The driver fulfills a
  local future per return id; ``get``/``wait`` consult these futures
  before asking the daemon.

Tasks that need daemon machinery — placement groups, node affinity,
runtime envs, TPU gangs — keep the daemon path (eligibility below).
System failures (lease connection lost) retry submitter-side up to the
task's ``max_retries``, matching the reference's handling of leased
worker death.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .ids import ObjectID
from .rpc import ConnectionLost, RpcClient, RpcError
from .task_spec import make_error_payload
from .wire import decode_spec, encode_spec, encode_spec_batch
from ray_tpu.devtools.lock_witness import make_lock

#: In-flight request cap per leased connection when batching is OFF
#: (config task_submit_batching=False). 1 = every task lands on an
#: idle worker (no head-of-line blocking behind a slow task); queued
#: backlog is re-dispatched from reply callbacks, which already
#: pipelines the socket turnaround. With batching ON the cap comes
#: from config submit_inflight_specs instead.
_PIPELINE_CAP = 1


#: Shared mutation lock for every ResultFuture's done/callback/event
#: state. One process-wide lock instead of a Lock + Event + Condition
#: + waiter deque PER future: that threading machinery measured ~1 KB
#: per future — the single largest driver-side allocation at 1M
#: queued tasks (~1 GB of the measured RSS). Critical sections are a
#: few instructions, and completions arrive at RPC rate, so a shared
#: lock contends negligibly.
_fut_lock = threading.Lock()  # rt: noqa[RT004] — driver-only module state; workers re-import post-fork


class ResultFuture:
    """One task's worth of direct results (all return ids). The
    kernel-wait Event is allocated LAZILY — only for futures somebody
    actually blocks on; a pipelined submit-then-collect burst never
    pays for it."""

    __slots__ = (
        "_done", "_event", "results", "error", "daemon_fallback",
        "hold_refs", "_callbacks",
    )

    def __init__(self):
        self._done = False
        self._event: Optional[threading.Event] = None
        self.results: Optional[List[tuple]] = None  # aligned w/ returns
        self.error: Optional[bytes] = None
        self.daemon_fallback = False
        #: Submitter-side arg pinning: ObjectRef args stay referenced
        #: until the task completes, or the daemon may delete a dep the
        #: caller dropped while the worker still needs it (the daemon
        #: path pins args in _pin_args; direct specs never transit it).
        self.hold_refs: Optional[list] = None
        self._callbacks: Optional[List] = None

    def done(self) -> bool:
        return self._done

    def fulfill(self, results: Optional[List[tuple]], error: Optional[bytes]):
        self.results = results
        self.error = error
        self.hold_refs = None
        self._finish()

    def to_daemon(self):
        self.daemon_fallback = True
        self._finish()

    def _finish(self) -> None:
        with _fut_lock:
            callbacks, self._callbacks = self._callbacks, None
            self._done = True
            event = self._event
        if event is not None:
            event.set()
        for cb in callbacks or ():
            try:
                cb(self)
            except Exception:
                pass

    def add_done_callback(self, cb) -> None:
        """Run `cb(self)` when the future completes (immediately if it
        already has). Callbacks run on whichever thread completes the
        future — keep them short and non-blocking on that connection."""
        with _fut_lock:
            if not self._done:
                if self._callbacks is None:
                    self._callbacks = []
                self._callbacks.append(cb)
                return
        cb(self)

    def remove_done_callback(self, cb) -> None:
        """Deregister a pending callback (no-op if already fired) —
        polling wait() loops must not accumulate one closure per call
        on a long-pending future."""
        with _fut_lock:
            if self._callbacks is not None:
                try:
                    self._callbacks.remove(cb)
                except ValueError:
                    pass

    def wait(self, timeout: Optional[float]) -> bool:
        if self._done:
            return True
        with _fut_lock:
            if self._done:
                return True
            if self._event is None:
                self._event = threading.Event()
            event = self._event
        return event.wait(timeout)


class _Lease:
    __slots__ = (
        "lease_id", "worker_id", "address", "client", "in_flight",
        "last_used", "dead", "proven", "blocked",
    )

    def __init__(self, lease_id, worker_id, address):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.address = address
        self.client: Optional[RpcClient] = None
        self.in_flight = 0
        self.last_used = time.monotonic()
        self.dead = False
        #: A lease takes multi-spec frames only after completing at
        #: least one spec. Until then it gets singles, so a burst of
        #: BLOCKING tasks (gang rendezvous, collectives) spreads
        #: across the growing pool exactly like the per-task wire
        #: shape did — stacking gang members behind each other on one
        #: worker deadlocks the gang.
        self.proven = False
        #: The worker reclaimed queued specs because its running spec
        #: wouldn't finish: stop refilling until a real outcome shows
        #: the loop is moving again.
        self.blocked = False


class _Pending:
    """One queued submission in batch mode: the flat-codec blob plus
    the driver-side bookkeeping batching must keep (returns for
    fulfillment, retry budget). The spec DICT is dropped at submit —
    at the 1M-queued-task scale the ~150-byte blob replaces a
    kilobyte-class dict in driver RSS; the rare daemon-fallback path
    recovers the dict via decode_spec."""

    __slots__ = ("blob", "returns", "retries_left", "solo")

    def __init__(
        self,
        blob: bytes,
        returns: list,
        retries_left: int,
        solo: bool = False,
    ):
        self.blob = blob
        self.returns = returns
        self.retries_left = retries_left
        #: Must ride a SIZE-1 frame: one of this spec's args is a
        #: still-pending direct result, and the executing worker will
        #: block on it until the producer's reply lands driver-side
        #: and is published. Inside a multi-spec frame that wait can
        #: deadlock — the producer's own reply may be the tail of a
        #: batch whose earlier spec is doing the waiting.
        self.solo = solo


class _KeyState:
    """Per-scheduling-key backlog + lease pool (lock: ks.lock)."""

    __slots__ = (
        "queue", "lock", "leases", "requests_in_flight", "closed", "hot",
    )

    def __init__(self):
        # Deque, not list: the flood regime (1M queued specs) pops
        # from the head at batch rate — list.pop(0) is O(queue) and
        # turned the drain quadratic exactly when the queue was
        # deepest.
        from collections import deque

        self.queue = deque()
        # One shared witness name for every key-state: two instances
        # are never nested, so merging their order edges is safe.
        self.lock = make_lock("direct.keystate")
        self.leases: Dict[str, _Lease] = {}
        self.requests_in_flight = 0
        self.closed = False
        #: Submission-regime hysteresis (batch mode). Cold: a lone
        #: submit ships immediately to an idle lease (latency mode).
        #: Hot (monotonic deadline): a multi-spec drain proved a
        #: submit loop is outpacing replies — submissions only queue,
        #: and reply-driven drains coalesce them into large frames.
        #: Without this, fast workers make a lease idle between two
        #: `.remote()` calls and every task ships as its own frame:
        #: one sendmsg wakeup per task was the measured flood
        #: ceiling. Time-decayed (not a flag) so one drain that
        #: briefly empties the queue mid-flood doesn't flap the
        #: regime back to per-task frames.
        self.hot = 0.0


def scheduling_key(spec: dict) -> tuple:
    res = spec.get("resources") or {}
    return (tuple(sorted(res.items())), res.get("TPU", 0) > 0)


class DirectTaskManager:
    """Driver-side direct submitter for normal tasks."""

    def __init__(self, core):
        self._core = core  # CoreWorker (driver role)
        # RLock: forget()'s dict pop can drop the last reference to a
        # future whose hold_refs chain ObjectRef.__del__ ->
        # remove_local_ref -> forget() on the SAME thread (cyclic GC
        # fires during the pop). A plain Lock self-deadlocks there.
        self._lock = make_lock("direct.manager", "rlock")
        self._futures: Dict[bytes, Tuple[ResultFuture, int]] = {}
        #: direct results already published to the daemon object table
        #: (large/shm results are implicitly published by the worker).
        self._published: set = set()
        self._keys: Dict[tuple, _KeyState] = {}
        self._shutdown = False
        cfg = core.config
        self._idle_timeout = cfg.worker_lease_idle_timeout_s
        # Batched + pipelined submission (ROADMAP item 3): coalesce
        # queued specs into execute_tasks frames (flat-codec blobs,
        # wire.encode_spec_batch) under a bounded in-flight window.
        # Batches form only from backlog — an idle lease still gets a
        # single-spec frame immediately, so latency never waits on a
        # flush timer.
        self._batching = cfg.task_submit_batching
        self._batch_max = max(1, cfg.submit_batch_max_specs)
        self._window = (
            max(1, cfg.submit_inflight_specs)
            if self._batching
            else _PIPELINE_CAP
        )
        # The real concurrency gate is the daemon scheduler's resource
        # admission (lease grants reserve the task's resources); this
        # is only an anti-runaway cap. It must NOT be lower than the
        # concurrency the declared resources admit — gang-rendezvous
        # tasks (util.collective) deadlock if fewer workers can run
        # than the resource model promises.
        self._max_leases = max(1, cfg.direct_call_max_leases)
        # One persistent requester/maintenance thread: lease-pool
        # growth, idle lease release, starvation sweep. Never on the
        # submit/reply hot path.
        self._req_cond = threading.Condition()
        self._req_jobs: List = []
        self._req_thread: Optional[threading.Thread] = None

    # -- eligibility ---------------------------------------------------
    def eligible(self, spec: dict) -> bool:
        if self._shutdown:
            return False
        if spec["kind"] != "normal":
            return False
        if spec.get("scheduling_strategy") or spec.get("pg_context"):
            return False
        if spec.get("runtime_env"):
            return False
        # TPU tasks ride the daemon path: gang resources and visibility
        # env handling live there.
        if (spec.get("resources") or {}).get("TPU", 0) > 0:
            return False
        return True

    # -- submission ----------------------------------------------------
    def register(self, spec: dict) -> ResultFuture:
        """Create the shared future covering all of a spec's returns."""
        fut = ResultFuture()
        with self._lock:
            for i, ret in enumerate(spec["returns"]):
                self._futures[ret] = (fut, i)
        return fut

    def submit(self, spec: dict, solo: bool = False) -> None:
        key = scheduling_key(spec)
        ks = self._key_state(key)
        if self._batching:
            # FIFO through the queue, always: a new spec never jumps
            # ahead of queued backlog onto a freshly-idle lease. An
            # idle lease takes a batch NOW (a lone spec ships as a
            # single-spec frame — no flush-timer latency); with every
            # lease busy the spec just queues and reply-driven drains
            # coalesce it into a large frame. That hysteresis is what
            # turns a tight `.remote()` loop into hundreds-of-specs
            # frames instead of one frame per task.
            entry = _Pending(
                encode_spec(spec),
                spec["returns"],
                spec.get("max_retries", 0),
                solo=solo,
            )
            batch = None
            lease = None
            want_more = False
            with ks.lock:
                ks.queue.append(entry)
                if ks.hot < time.monotonic():
                    lease = self._pick_lease(ks)
                    if lease is not None:
                        batch = self._take_batch_locked(ks, lease)
                # Grow the pool ONE request at a time while backlog
                # remains (see the legacy branch's rationale below).
                if ks.queue and (
                    ks.requests_in_flight == 0
                    and len(ks.leases) < self._max_leases
                ):
                    want_more = True
                    ks.requests_in_flight += 1
            if batch:
                self._send_batch(key, ks, lease, batch)
            if want_more:
                self._enqueue_lease_request(key, ks)
            return
        spec["_retries_left"] = spec.get("max_retries", 0)
        lease = None
        want_more = False
        with ks.lock:
            lease = self._pick_lease(ks)
            if lease is not None:
                lease.in_flight += 1
                lease.last_used = time.monotonic()
            else:
                ks.queue.append(spec)
                # Grow the pool ONE request at a time: each grant
                # chains the next while backlog remains (_on_lease_
                # reply), so growth proceeds at grant latency (~1ms)
                # but never floods the daemon's queue with requests it
                # cannot admit — a 64-deep request backlog keeps
                # churning grants/releases for seconds after the burst
                # ends (reference: normal_task_submitter.cc pipelines
                # exactly one lease request per scheduling key).
                want_more = (
                    ks.requests_in_flight == 0
                    and len(ks.leases) < self._max_leases
                )
                if want_more:
                    ks.requests_in_flight += 1
        if lease is not None:
            self._send(key, ks, lease, spec)
        elif want_more:
            self._enqueue_lease_request(key, ks)

    @staticmethod
    def _pick_lease(ks: _KeyState) -> Optional[_Lease]:
        """An IDLE live lease (caller holds ks.lock). Only idle leases
        take a submission inline — busy leases coalesce backlog from
        ks.queue into batch frames as their replies drain, which is
        what turns a tight `.remote()` loop into a few large frames
        instead of one frame per task."""
        for lease in ks.leases.values():
            if not lease.dead and lease.in_flight == 0:
                return lease
        return None

    def _key_state(self, key) -> _KeyState:
        with self._lock:
            ks = self._keys.get(key)
            if ks is None:
                ks = self._keys[key] = _KeyState()
            return ks

    # -- hot path ------------------------------------------------------
    def _send(self, key, ks: _KeyState, lease: _Lease, spec: dict) -> None:
        lease.client.call_async(
            "execute_task",
            lambda reply: self._on_reply(key, ks, lease, spec, reply),
            spec=spec,
        )

    def _send_batch(
        self, key, ks: _KeyState, lease: _Lease, batch: List[_Pending]
    ) -> None:
        """One execute_tasks frame for N specs: blobs were encoded at
        submit, so the frame build is a length-prefixed join plus one
        outer pickle of a single bytes object. Outcomes stream back
        as partial reply frames (`seen` tracks fulfilled indexes
        across them) — a quick spec is never held hostage by a slow
        one later in the same frame."""
        seen: set = set()
        lease.client.call_async(
            "execute_tasks",
            lambda reply: self._on_batch_reply(
                key, ks, lease, batch, seen, reply
            ),
            # Hub-thread delivery: accounting + window refill + fulfill
            # run with zero thread handoffs; refill sends are bounded
            # by the in-flight window, so the socket buffer the hub
            # writes into is one the worker is actively draining.
            inline=True,
            specs=encode_spec_batch(e.blob for e in batch),
            count=len(batch),
        )

    def _take_batch_locked(
        self, ks: _KeyState, lease: _Lease
    ) -> Optional[List[_Pending]]:
        """Reserve the next batch of queued specs for `lease` (caller
        holds ks.lock): bounded by the in-flight window (backpressure)
        and the per-frame batch cap. Maintains the hot/cold regime:
        a multi-spec take proves a submit loop is outpacing replies
        (go hot); an empty queue proves it ended (go cold)."""
        if ks.closed or lease.dead or lease.blocked or not ks.queue:
            return None
        if not lease.proven and lease.in_flight > 0:
            return None  # one spec at a time until the first completes
        room = self._window - lease.in_flight
        if room <= 0:
            return None
        n = min(room, self._batch_max, len(ks.queue))
        if ks.queue[0].solo:
            # Pending-direct-dep spec: its own frame, AND never
            # stacked behind anything on this worker — solo specs
            # block in-worker on results other specs must be free to
            # produce, so each occupies a lease exclusively (the
            # per-task wire shape's concurrency contract).
            if lease.in_flight > 0:
                return None
            n = 1
        elif not lease.proven:
            # Unproven lease (nothing completed yet — could be about
            # to run a blocking gang member): singles until the first
            # completion (see _Lease.proven).
            n = 1
        else:
            # Stop a multi-spec frame BEFORE the first solo entry.
            for i in range(1, n):
                if ks.queue[i].solo:
                    n = i
                    break
        pop = ks.queue.popleft
        now = time.monotonic()
        batch = [pop() for _ in range(n)]
        lease.in_flight += n
        lease.last_used = now
        if n > 1:
            ks.hot = now + 0.005  # stay in coalescing mode ~5ms
        return batch

    def _drain_lease(self, key, ks: _KeyState, lease: _Lease) -> None:
        """Refill `lease`'s in-flight window from the backlog (batch
        mode). Sends run outside ks.lock."""
        while True:
            with ks.lock:
                batch = self._take_batch_locked(ks, lease)
            if not batch:
                return
            self._send_batch(key, ks, lease, batch)

    def _on_batch_reply(
        self, key, ks, lease, batch: List[_Pending], seen: set,
        reply: dict,
    ) -> None:
        """Runs per outcome frame (partial or final) on the lease
        connection's reader thread. Per-spec error isolation: one
        failed spec fails only its own returns — the batch envelope
        succeeds or fails as transport, never as semantics."""
        err = reply.get("_error")
        if err is not None:
            # Only the specs whose outcomes never arrived are
            # affected — earlier partial frames already fulfilled
            # (and window-released) theirs.
            unseen = [
                entry for i, entry in enumerate(batch) if i not in seen
            ]
            if err == "__chaos_injected_failure__":
                # Injected drop (RT_testing_rpc_failure): nothing hit
                # the wire and the worker is healthy — requeue at the
                # front in original order and resend on the SAME
                # lease. No retry budget is spent and nothing can
                # have executed: exactly-once by construction.
                with ks.lock:
                    lease.in_flight -= len(unseen)
                    for entry in reversed(unseen):
                        ks.queue.appendleft(entry)
                self._drain_lease(key, ks, lease)
                return
            self._on_lease_failure_batch(key, ks, lease, unseen, err)
            return
        parts = reply.get("parts") or []
        final = not reply.get("_part")
        # A worker that reclaimed unstarted specs from behind a
        # long-running one returns them as requeue outcomes: they go
        # back to the FRONT of the queue for other leases (the pool
        # grows if none are free) — never re-executed, never failed.
        real_parts = []
        requeued: List[_Pending] = []
        for index, outcome in parts:
            seen.add(index)
            if outcome.get("requeue"):
                requeued.append(batch[index])
            else:
                real_parts.append((index, outcome))
        # Lease accounting (and window refill) BEFORE fulfilling: a
        # fulfilled waiter may submit its next task immediately and
        # must see this lease's window open.
        missing: List[int] = []
        want_more = False
        with ks.lock:
            lease.in_flight -= len(parts)
            if final:
                missing = [
                    i for i in range(len(batch)) if i not in seen
                ]
                lease.in_flight -= len(missing)
            lease.last_used = time.monotonic()
            if real_parts:
                lease.proven = True
                lease.blocked = False
            elif requeued:
                lease.blocked = True
            # APPEND, not appendleft: requeue frames arrive oldest-
            # first (the worker reclaims its queue in FIFO order), so
            # appending preserves the original submission order
            # across frames — prepending inverted it, putting
            # consumers ahead of the producers they block on, which
            # deadlocked dependency chains.
            ks.queue.extend(requeued)
            if requeued and ks.queue and (
                ks.requests_in_flight == 0
                and len(ks.leases) < self._max_leases
            ):
                want_more = True
                ks.requests_in_flight += 1
        # One manager-lock acquisition for the whole frame's future
        # lookups (not one per spec), then fulfill outside the lock.
        fulfills = []
        with self._lock:
            futures = self._futures

            def find(entry):
                for ret in entry.returns:
                    found = futures.get(ret)
                    if found is not None:
                        return found[0]
                return None  # every handle dropped pre-completion

            for index, outcome in real_parts:
                fut = find(batch[index])
                if fut is not None:
                    fulfills.append((fut, outcome))
            for index in missing:
                # A well-formed final frame accounts for every spec;
                # a gap means the executor dropped one — fail it
                # individually.
                seen.add(index)
                fut = find(batch[index])
                if fut is not None:
                    fulfills.append((fut, {
                        "error": make_error_payload(
                            "WorkerCrashedError",
                            "batch reply missing this spec's outcome",
                        )
                    }))
        if want_more:
            self._enqueue_lease_request(key, ks)
        self._drain_lease(key, ks, lease)
        for fut, outcome in fulfills:
            fut.fulfill(outcome.get("results"), outcome.get("error"))

    def _on_reply(self, key, ks, lease, spec, reply: dict) -> None:
        """Runs on the lease connection's reader thread (per-task
        wire shape: task_submit_batching=False)."""
        if reply.get("_error") is not None:
            if reply["_error"] == "__chaos_injected_failure__":
                # Injected drop: resend on the same (healthy) lease —
                # see _on_batch_reply. Nothing was sent or executed.
                self._send(key, ks, lease, spec)
                return
            self._on_lease_failure(key, ks, lease, spec, reply["_error"])
            return
        # Lease accounting BEFORE fulfilling: the fulfilled waiter may
        # submit its next task immediately, and must see this lease as
        # free or it queues the spec and grows the pool for nothing.
        next_spec = None
        with ks.lock:
            if ks.queue and not ks.closed and not lease.dead:
                next_spec = ks.queue.popleft()
                lease.last_used = time.monotonic()
            else:
                lease.in_flight -= 1
                lease.last_used = time.monotonic()
        if next_spec is not None:
            self._send(key, ks, lease, next_spec)
        self._fulfill(spec, reply)

    # -- lease lifecycle -----------------------------------------------
    def _enqueue_lease_request(self, key, ks: _KeyState) -> None:
        self._enqueue_job(lambda: self._request_lease(key, ks))

    def _enqueue_job(self, job) -> None:
        with self._req_cond:
            self._req_jobs.append(job)
            if self._req_thread is None:
                self._req_thread = threading.Thread(
                    target=self._requester_loop, daemon=True,
                    name="rt-lease-requester",
                )
                self._req_thread.start()
            self._req_cond.notify()

    def _requester_loop(self) -> None:
        """Lease-pool maintenance off the hot path: run queued jobs
        (lease grants/denials), release idle leases, rescue starved
        queues (work queued, no request outstanding — e.g. every lease
        busy with a long task)."""
        while not self._shutdown:
            with self._req_cond:
                if not self._req_jobs:
                    # Timed wait only while there is lease state to
                    # sweep; otherwise park until the next job arrives
                    # (no 10 Hz idle wakeups for the driver's life).
                    with self._lock:
                        has_state = any(
                            ks.leases or ks.queue
                            for ks in self._keys.values()
                        )
                    self._req_cond.wait(0.1 if has_state else None)
                batch, self._req_jobs = self._req_jobs, []
            for job in batch:
                try:
                    job()
                except Exception:
                    pass
            if self._shutdown:
                return
            with self._lock:
                keys = list(self._keys.items())
            now = time.monotonic()
            for key, ks in keys:
                to_release = []
                starved = False
                drain = None
                with ks.lock:
                    for lid, lease in list(ks.leases.items()):
                        if (
                            lease.in_flight == 0
                            and now - lease.last_used > self._idle_timeout
                        ):
                            del ks.leases[lid]
                            to_release.append(lease)
                    if self._batching and ks.queue:
                        # Backlog + an idle survivor (e.g. after a
                        # batch requeue landed while every reply was
                        # already drained): refill its window rather
                        # than leasing another worker.
                        drain = self._pick_lease(ks)
                    starved = (
                        bool(ks.queue)
                        and drain is None
                        and ks.requests_in_flight == 0
                        and self._pick_lease(ks) is None
                        and len(ks.leases) < self._max_leases
                    )
                    if starved:
                        ks.requests_in_flight += 1
                for lease in to_release:
                    self._drop_lease(lease, release=True)
                if drain is not None:
                    self._drain_lease(key, ks, drain)
                if starved:
                    self._request_lease(key, ks)

    def _request_lease(self, key, ks: _KeyState) -> None:
        """Fire the lease request without blocking: the daemon defers
        its reply until a worker is free (no client timeout — a timed
        out request whose grant arrives later would leak the worker),
        and the reply is handled as a requester-thread job."""
        self._core._client.call_async(
            "request_lease",
            lambda reply: self._enqueue_job(
                lambda: self._on_lease_reply(key, ks, reply)
            ),
            resources=dict(key[0]),
            needs_tpu=key[1],
        )

    def _on_lease_reply(self, key, ks: _KeyState, reply: dict) -> None:
        granted = None
        if reply.get("address"):
            granted = _Lease(
                reply["lease_id"], reply["worker_id"], reply["address"]
            )
            try:
                granted.client = RpcClient(granted.address)
            except ConnectionLost:
                self._core.notify(
                    "release_lease", lease_id=granted.lease_id
                )
                granted = None
        if granted is None:
            with ks.lock:
                ks.requests_in_flight -= 1
                # Could not lease (daemon lost/infeasible): if nothing
                # is serving this key, push queued work back to the
                # daemon path so nothing strands.
                if not ks.leases and not ks.requests_in_flight:
                    stranded = list(ks.queue)
                    ks.queue.clear()
                else:
                    stranded = []
            for entry in stranded:
                self._fallback_to_daemon(entry)
            return
        sends = []
        chain = False
        with ks.lock:
            ks.requests_in_flight -= 1
            if self._shutdown or ks.closed:
                leave = True
            else:
                leave = False
                ks.leases[granted.lease_id] = granted
                if not self._batching:
                    while ks.queue and granted.in_flight < _PIPELINE_CAP:
                        sends.append(ks.queue.popleft())
                        granted.in_flight += 1
                granted.last_used = time.monotonic()
                # Backlog remains: chain the next growth request.
                if (
                    ks.queue
                    and ks.requests_in_flight == 0
                    and len(ks.leases) < self._max_leases
                ):
                    ks.requests_in_flight += 1
                    chain = True
        if leave:
            self._drop_lease(granted, release=True)
            return
        if self._batching:
            self._drain_lease(key, ks, granted)
        else:
            for spec in sends:
                self._send(key, ks, granted, spec)
        if chain:
            self._request_lease(key, ks)

    def _drop_lease(self, lease: _Lease, release: bool) -> None:
        lease.dead = True
        if lease.client is not None:
            try:
                lease.client.close()
            except Exception:
                pass
        if release and not self._shutdown:
            try:
                self._core.notify("release_lease", lease_id=lease.lease_id)
            except Exception:
                pass

    def _on_lease_failure(self, key, ks, lease, spec, err) -> None:
        """Leased worker died (or the connection broke) with `spec` in
        flight. System failure: retry on another lease if the task has
        retries left (the task may have executed — at-least-once, the
        reference's semantics for worker-crash retries), else fail."""
        with ks.lock:
            ks.leases.pop(lease.lease_id, None)
        self._drop_lease(lease, release=False)  # daemon saw the death
        if spec.get("_retries_left", 0) > 0:
            spec["_retries_left"] -= 1
            requeued = False
            with ks.lock:
                if not ks.closed:
                    ks.queue.appendleft(spec)
                    if ks.requests_in_flight == 0:
                        ks.requests_in_flight += 1
                        requeued = True
            if requeued:
                self._enqueue_lease_request(key, ks)
        else:
            payload = make_error_payload(
                "WorkerCrashedError",
                f"leased worker died while running task ({err})",
            )
            self._fulfill(spec, {"error": payload})

    def _on_lease_failure_batch(
        self, key, ks, lease, batch: List[_Pending], err
    ) -> None:
        """A whole batch frame failed in transport (lease connection
        broke, chaos injection). The failure maps back to the
        INDIVIDUAL specs: each retries on another lease under its own
        budget (in original submission order) or fails its own
        returns — exactly the per-spec semantics of N separate
        submissions. A chaos-injected drop happens before any bytes
        hit the wire, so the retried batch executes exactly once."""
        with ks.lock:
            ks.leases.pop(lease.lease_id, None)
        self._drop_lease(lease, release=False)  # daemon saw the death
        retry: List[_Pending] = []
        failed: List[_Pending] = []
        for entry in batch:
            if entry.retries_left > 0:
                entry.retries_left -= 1
                retry.append(entry)
            else:
                failed.append(entry)
        requeued = False
        if retry:
            with ks.lock:
                if not ks.closed:
                    # Front of the queue in original order: retried
                    # specs keep their place ahead of younger work.
                    for entry in reversed(retry):
                        ks.queue.appendleft(entry)
                    if ks.requests_in_flight == 0:
                        ks.requests_in_flight += 1
                        requeued = True
                else:
                    failed.extend(retry)
        if requeued:
            self._enqueue_lease_request(key, ks)
        for entry in failed:
            self._fulfill_returns(entry.returns, {
                "error": make_error_payload(
                    "WorkerCrashedError",
                    f"leased worker died while running task ({err})",
                )
            })

    def _fallback_to_daemon(self, entry) -> None:
        """Strip direct bookkeeping and hand the spec to the daemon
        path; mark its futures so get()/wait() consult the daemon.
        Batch-mode entries recover their spec dict from the blob —
        this path runs only when the lease plane is gone."""
        if isinstance(entry, _Pending):
            spec = decode_spec(entry.blob)
        else:
            spec = entry
        spec.pop("_retries_left", None)
        with self._lock:
            futures = {
                self._futures.pop(ret, (None, 0))[0]
                for ret in spec["returns"]
            }
        for fut in futures:
            if fut is not None:
                fut.to_daemon()
        try:
            self._core.call("submit_task", spec=spec)
        except RpcError as e:
            payload = make_error_payload(
                "TaskError", f"daemon fallback submission failed: {e}"
            )
            for ret in spec["returns"]:
                try:
                    self._core.call("seal_error", oid=ret, error=payload)
                except RpcError:
                    pass
        finally:
            # The daemon has pinned the args (or sealed errors) now.
            for fut in futures:
                if fut is not None:
                    fut.hold_refs = None

    # -- results -------------------------------------------------------
    def _fulfill(self, spec: dict, reply: dict) -> None:
        self._fulfill_returns(spec["returns"], reply)

    def _fulfill_returns(self, returns, reply: dict) -> None:
        fut = None
        with self._lock:
            # Any surviving return's entry holds the shared future
            # (individual returns are forgotten as their refs are GC'd).
            for ret in returns:
                entry = self._futures.get(ret)
                if entry is not None:
                    fut = entry[0]
                    break
        if fut is None:
            # Every handle to the result was dropped before completion;
            # nothing to record (the object was never globally visible).
            return
        fut.fulfill(reply.get("results"), reply.get("error"))

    def lookup(self, oid: ObjectID):
        with self._lock:
            return self._futures.get(oid.binary())

    def forget(self, oid: ObjectID) -> None:
        with self._lock:
            self._futures.pop(oid.binary(), None)
            self._published.discard(oid.binary())

    def publish_when_done(self, oid: ObjectID) -> None:
        """Arrange for a (possibly still pending) direct result to be
        published to the daemon's object table once it completes —
        used when a dependent spec carries the ref so the executing
        worker's fetch can resolve daemon-side. Never blocks."""
        entry = self.lookup(oid)
        if entry is None:
            return
        fut, _ = entry

        def _publish(_fut):
            # Hop to the requester thread: done-callbacks may fire on
            # the hub thread (inline batch replies), and
            # ensure_published makes BLOCKING calls whose replies only
            # the hub itself could deliver — publishing inline there
            # would self-deadlock.
            self._enqueue_job(lambda: self._ensure_published_safe(oid))

        fut.add_done_callback(_publish)

    def _ensure_published_safe(self, oid: ObjectID) -> None:
        try:
            self.ensure_published(oid)
        except Exception:
            pass

    def ensure_published(self, oid: ObjectID) -> bool:
        """Make a direct inline result globally visible (daemon object
        table) before its ref escapes this process — nested in another
        value, or borrowed cross-process. A still-pending result is
        published on completion (never blocks the caller: consumers
        block daemon-side until the publish lands, so pickling a
        pending ref keeps pipelining). Returns False if `oid` is not a
        direct result."""
        entry = self.lookup(oid)
        if entry is None:
            return False
        fut, index = entry
        if not fut.done():
            self.publish_when_done(oid)
            return True
        if fut.daemon_fallback:
            return True  # daemon already owns it
        key = oid.binary()
        with self._lock:
            if key in self._published:
                return True
        if fut.error is not None:
            self._core.call("seal_error", oid=key, error=fut.error)
        else:
            kind, payload = fut.results[index]
            if kind == "inline":
                self._core.call("put_inline", oid=key, data=payload)
            # kind == "shm": the worker already sealed + reported it.
        with self._lock:
            self._published.add(key)
        return True

    # -- shutdown ------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        with self._req_cond:
            self._req_cond.notify_all()
        with self._lock:
            keys = list(self._keys.values())
        for ks in keys:
            with ks.lock:
                ks.closed = True
                leases = list(ks.leases.values())
                ks.leases.clear()
            for lease in leases:
                self._drop_lease(lease, release=False)


_router_pool = None
_router_pool_lock = threading.Lock()  # rt: noqa[RT004] — driver-only module state; workers re-import post-fork


def _router_executor():
    """Shared pool draining actor routers (reference role:
    actor_task_submitter's client callbacks). One THREAD per actor
    handle collapses at the 10k-actor scale; per-actor ordering
    survives because each router drains its own queue with at most one
    pool task at a time."""
    global _router_pool
    with _router_pool_lock:
        if _router_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _router_pool = ThreadPoolExecutor(
                max_workers=int(
                    os.environ.get("RT_DIRECT_ROUTER_THREADS", "64")
                ),
                thread_name_prefix="rt-actor-router",
            )
        return _router_pool


def _reset_router_pool_after_fork() -> None:
    global _router_pool
    _router_pool = None


os.register_at_fork(after_in_child=_reset_router_pool_after_fork)


class ActorDirectRouter:
    """Per-actor direct call router.

    An ORDERED per-actor queue drained by at most one shared-pool task
    at a time preserves submission order across transport decisions:
    the drain resolves the actor's direct address (blocking until the
    actor is ALIVE), then pushes calls over a dedicated connection.
    Remote-node actors and unrecoverable connection failures fall back
    to the daemon path — sticky, so ordering never interleaves
    between transports."""

    def __init__(self, core, actor_id):
        self._core = core
        self._actor_id = actor_id
        self._queue: List[tuple] = []
        self._cond = threading.Condition()
        self._mode = "resolving"  # resolving | direct | daemon | dead
        self._client: Optional[RpcClient] = None
        self._shutdown = False
        self._draining = False

    def submit(self, spec: dict, fut: ResultFuture) -> None:
        with self._cond:
            self._queue.append((spec, fut))
            if self._draining or self._shutdown:
                return
            self._draining = True
        _router_executor().submit(self._drain)

    def _drain(self) -> None:
        while True:
            with self._cond:
                if not self._queue or self._shutdown:
                    self._draining = False
                    return
                spec, fut = self._queue.pop(0)
            try:
                self._dispatch(spec, fut)
            except Exception:
                pass

    def _dispatch(self, spec: dict, fut: ResultFuture) -> None:
        if self._mode == "daemon":
            self._send_daemon(spec, fut)
            return
        client = self._resolve()
        if client is None:
            self._send_daemon(spec, fut)
            return
        # Pipelined send: the reply is handled on the connection's
        # reader thread, so N calls can be in flight at once — the
        # worker's task queue (and its max_concurrency pool) provides
        # the actual concurrency. Send order on one socket preserves
        # per-handle submission order.
        client.call_async(
            "execute_task",
            lambda reply: self._on_reply(spec, fut, reply),
            spec=spec,
        )

    def _on_reply(self, spec: dict, fut: ResultFuture, reply: dict) -> None:
        if reply.get("_error") is not None:
            # Actor worker died (or connection broke) with this call in
            # flight. The call may already have executed — re-running
            # would break at-most-once actor semantics, so without
            # retries it fails like the daemon path fails in-flight
            # tasks on actor death (reference: actor_task_submitter
            # DisconnectRpcClient will_retry=false path). Subsequent
            # calls re-resolve: the daemon's actor_address defers while
            # the actor restarts and answers with the NEW worker once
            # ALIVE (or empty if it stays dead).
            self._teardown_client()
            with self._cond:
                self._mode = "resolving"
            if spec.get("max_retries", 0) > 0:
                spec["max_retries"] -= 1
                rearm = False
                with self._cond:
                    self._queue.insert(0, (spec, fut))
                    if not self._draining and not self._shutdown:
                        self._draining = True
                        rearm = True
                if rearm:
                    _router_executor().submit(self._drain)
            else:
                fut.fulfill(None, make_error_payload(
                    "ActorDiedError",
                    "actor worker died while executing direct call",
                ))
            return
        fut.fulfill(reply.get("results"), reply.get("error"))

    def _resolve(self) -> Optional[RpcClient]:
        with self._cond:
            client = self._client
        if client is not None:
            return client
        # Retry around the window where the actor's worker died but the
        # daemon hasn't processed the death yet: actor_address still
        # answers the OLD address (connect fails) until the daemon sees
        # the disconnect, after which it defers until restart completes.
        for attempt in range(50):
            try:
                reply = self._core.call(
                    "actor_address",
                    actor_id=self._actor_id.binary(),
                    timeout=None,
                )
            except RpcError:
                break
            address = reply.get("address")
            if not address:
                break  # remote node / dead — daemon path owns it
            try:
                client = RpcClient(address, connect_timeout=0.5)
            except ConnectionLost:
                time.sleep(min(0.02 * (attempt + 1), 0.2))
                continue
            # Publish under _cond: the reply-reader thread's
            # _teardown_client swaps this attribute concurrently — an
            # unguarded store here could leak the client it replaces
            # (never closed) or hand back one already being closed.
            with self._cond:
                self._client = client
                self._mode = "direct"
            return client
        with self._cond:
            self._mode = "daemon"
        return None

    def _send_daemon(self, spec: dict, fut: ResultFuture) -> None:
        fut.to_daemon()
        try:
            self._core.call("submit_actor_task", spec=spec)
        except RpcError as e:
            payload = make_error_payload(
                "ActorDiedError", f"actor submission failed: {e}"
            )
            for ret in spec["returns"]:
                try:
                    self._core.call("seal_error", oid=ret, error=payload)
                except RpcError:
                    pass
        finally:
            fut.hold_refs = None  # daemon owns arg pinning now

    def _teardown_client(self) -> None:
        # Swap under the lock, close outside it: exactly one caller
        # wins the swap (no double-close when the reader thread and
        # shutdown() race), and the potentially-blocking socket close
        # never runs while holding _cond.
        with self._cond:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    def shutdown(self) -> None:
        self._shutdown = True
        self._teardown_client()
